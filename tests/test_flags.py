"""Unified flag registry (paddle_tpu.flags) — the reference's gflags
re-export surface (python/paddle/fluid/__init__.py:125-163 __bootstrap__):
typed defs, FLAGS_<name> env override, programmatic set/reset, and the
runtime honoring the values."""

import numpy as np
import pytest

from paddle_tpu import flags


def test_defaults_and_types():
    assert flags.get("check_nan_inf") is False
    assert flags.get("debug_graphviz_path") == ""
    assert isinstance(flags.get("eager_delete_tensor_gb"), float)


def test_env_override(monkeypatch):
    monkeypatch.setenv("FLAGS_check_nan_inf", "true")
    assert flags.get("check_nan_inf") is True
    monkeypatch.setenv("FLAGS_check_nan_inf", "0")
    assert flags.get("check_nan_inf") is False


def test_programmatic_set_wins_over_env(monkeypatch):
    monkeypatch.setenv("FLAGS_benchmark", "0")
    flags.set("benchmark", True)
    try:
        assert flags.get("benchmark") is True
    finally:
        flags.reset("benchmark")
    assert flags.get("benchmark") is False


def test_unknown_flag_raises():
    with pytest.raises(KeyError):
        flags.get("no_such_flag")
    with pytest.raises(KeyError):
        flags.set("no_such_flag", 1)


def test_bad_parse_warns_and_defaults(monkeypatch):
    monkeypatch.setenv("FLAGS_eager_delete_tensor_gb", "not-a-float")
    with pytest.warns(UserWarning):
        assert flags.get("eager_delete_tensor_gb") == 0.0


def test_check_nan_inf_honored_by_executor(monkeypatch):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[2], dtype="float32")
        y = layers.log(x)        # log(-1) -> NaN
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    bad = np.array([[-1.0, 2.0]], dtype=np.float32)
    # off: runs fine (NaN in output)
    (out,) = exe.run(main, feed={"x": bad}, fetch_list=[y])
    assert np.isnan(out).any()
    flags.set("check_nan_inf", True)
    try:
        with pytest.raises(FloatingPointError):
            exe.run(main, feed={"x": bad}, fetch_list=[y])
    finally:
        flags.reset("check_nan_inf")


def test_benchmark_flag_prints(capsys):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.fill_constant([2], "float32", 1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    flags.set("benchmark", True)
    try:
        exe.run(main, fetch_list=[x])
    finally:
        flags.reset("benchmark")
    assert "[FLAGS_benchmark]" in capsys.readouterr().out


def test_flag_listing_module():
    import subprocess
    import sys
    out = subprocess.run([sys.executable, "-m", "paddle_tpu.flags"],
                         capture_output=True, text=True, cwd="/root/repo")
    assert out.returncode == 0
    assert "FLAGS_check_nan_inf" in out.stdout
    assert "FLAGS_debug_graphviz_path" in out.stdout
