"""Process-level chaos for the distributed-tracing stack (ISSUE 12):
a SIGTERM'd process dumps its flight recorder atomically; a SIGKILLed
server's black box names the injected kill point; a trainer killed
mid-lease leaves the held lease in its black box AND its RPC spans in
the merged cross-process trace; and (slow) the two-process serving
acceptance — tools/launch.py client + server, one ``trace_collect``
command, the client's request span strictly containing the server's
admission -> prefill@bucket -> decode-step -> settle lifecycle."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)

pytestmark = pytest.mark.chaos


def _env_base():
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PADDLE_", "FLAGS_"))}
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _read_jsonl(path):
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                pass                      # torn final line of a killed proc
    return out


def _one(glob_dir, suffix):
    names = [n for n in os.listdir(glob_dir) if n.endswith(suffix)]
    assert len(names) == 1, (suffix, sorted(os.listdir(glob_dir)))
    return os.path.join(glob_dir, names[0])


def _trace_collect(mod_name="trace_collect"):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        mod_name, os.path.join(REPO_ROOT, "tools", "trace_collect.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_SIGTERM_BODY = """
import sys, time
from paddle_tpu import flags
flags.set("flight_recorder_dir", sys.argv[1])
flags.set("trace_role", "termee")
from paddle_tpu.observability import flight_recorder, tracing
assert tracing.active()
flight_recorder.note("armed", phase="steady")
print("READY", flush=True)
while True:
    time.sleep(0.05)
"""


def test_sigterm_dumps_flight_recorder(tmp_path):
    """SIGTERM: the handler dumps atomically, then the process still
    dies OF SIGTERM (honest wait status), and the dump carries the
    breadcrumbs recorded before the signal."""
    d = str(tmp_path / "rec")
    p = subprocess.Popen([sys.executable, "-c", _SIGTERM_BODY, d],
                         stdout=subprocess.PIPE, text=True,
                         cwd=REPO_ROOT, env=_env_base())
    try:
        assert p.stdout.readline().strip() == "READY"
        p.send_signal(signal.SIGTERM)
        assert p.wait(timeout=30) == -signal.SIGTERM
    finally:
        if p.poll() is None:
            p.kill()
    dump = json.load(open(_one(d, ".dump.json")))
    assert dump["reason"] == "sigterm"
    assert dump["role"] == "termee"
    kinds = [e["kind"] for e in dump["events"]]
    assert "sigterm" in kinds
    notes = [e for e in dump["events"] if e["kind"] == "note"]
    assert any(n["what"] == "armed" for n in notes)
    # the black box has the same trail, flushed line by line
    bb = _read_jsonl(_one(d, ".blackbox.jsonl"))
    assert [e for e in bb if e["kind"] == "sigterm"]


def test_sigkill_blackbox_names_kill_point(tmp_path):
    """SIGKILL mid-request: no dump hook fires, but the always-flushed
    black box survives — its last fault event IS the injected kill
    point (the serving.handle delay the kill rides on)."""
    d = str(tmp_path / "rec")
    env = _env_base()
    env["FLAGS_flight_recorder_dir"] = d
    env["FLAGS_trace_spool_dir"] = d
    env["FLAGS_trace_role"] = "victim"
    env["FLAGS_fault_plan"] = "serving.handle:delay@1:s=30"
    p = subprocess.Popen(
        [sys.executable, os.path.join(TESTS_DIR, "serving_victim.py"),
         str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO_ROOT, env=env)
    try:
        line = p.stdout.readline()
        assert line.startswith("READY"), line
        endpoint = line.split()[1]
        host, port = endpoint.rsplit(":", 1)
        import socket
        s = socket.create_connection((host, int(port)), timeout=10)
        s.sendall(b'{"method": "ping"}\n')
        # the fault observer records the site BEFORE the 30s delay —
        # wait for that line to hit the black box, then kill mid-delay
        bb_path = os.path.join(d, f"victim.{p.pid}.blackbox.jsonl")
        deadline = time.time() + 60
        while time.time() < deadline:
            if os.path.exists(bb_path) and any(
                    e["kind"] == "fault" for e in _read_jsonl(bb_path)):
                break
            time.sleep(0.05)
        p.kill()                           # SIGKILL: no hook, no dump
        assert p.wait(timeout=30) == -signal.SIGKILL
        s.close()
    finally:
        if p.poll() is None:
            p.kill()
    events = _read_jsonl(bb_path)
    faults_seen = [e for e in events if e["kind"] == "fault"]
    assert faults_seen, events
    assert faults_seen[-1]["site"] == "serving.handle"
    assert faults_seen[-1]["mode"] == "delay"
    # the fault fire also dumped (before its effect): the atomic dump
    # survived the SIGKILL and its last fault names the kill point too
    dump = json.load(open(
        os.path.join(d, f"victim.{p.pid}.dump.json")))
    assert dump["reason"] == "fault"
    dump_faults = [e for e in dump["events"] if e["kind"] == "fault"]
    assert dump_faults[-1]["site"] == "serving.handle"


def test_trainer_killed_mid_lease(tmp_path):
    """Kill a trainer holding a chunk lease: its black box names the
    lease, and the merged trace still shows its master.get_task span
    parented into the master's handler span (a cross-process flow
    edge) — the dump + merged-trace reconstruction of the acceptance
    criteria."""
    from _dist_utils import PortReservation
    from paddle_tpu import recordio
    d = str(tmp_path / "share")
    os.makedirs(d, exist_ok=True)
    data = str(tmp_path / "part-000.recordio")
    w = recordio.Writer(data, max_chunk_records=2)
    for i in range(8):
        w.write(f"r{i}".encode())
    w.close()

    env = _env_base()
    env["FLAGS_trace_spool_dir"] = d
    env["FLAGS_trace_role"] = "master"
    env["MASTER_SNAPSHOT"] = str(tmp_path / "snap.json")
    env["MASTER_PATHS"] = data
    env["MASTER_LEASE_S"] = "30"
    trainer = None
    with PortReservation() as r:
        env["MASTER_PORT"] = str(r.port)
        master = subprocess.Popen(
            [sys.executable, os.path.join(TESTS_DIR, "master_host.py")],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=REPO_ROOT, env=env)
        try:
            line = master.stdout.readline()
            assert line.startswith("READY"), line
            endpoint = line.split()[1]

            tenv = _env_base()
            from paddle_tpu.data.master_service import MASTER_ENV
            tenv[MASTER_ENV] = endpoint
            trainer = subprocess.Popen(
                [sys.executable,
                 os.path.join(TESTS_DIR, "lease_worker.py"), d],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=REPO_ROOT, env=tenv)
            line = trainer.stdout.readline()
            assert line.startswith("LEASED"), line
            task_id = int(line.split()[1])
            trainer.kill()                 # mid-lease SIGKILL
            assert trainer.wait(timeout=30) == -signal.SIGKILL
        finally:
            master.terminate()
            master.wait(timeout=30)
            if trainer is not None and trainer.poll() is None:
                trainer.kill()

    bb = _read_jsonl(_one(d, ".blackbox.jsonl"))
    leases = [e for e in bb if e["kind"] == "note"
              and e["what"] == "lease_taken"]
    assert leases and leases[-1]["task"] == task_id
    # merged trace: the trainer's get_task span and the master's handler
    # span share a trace, stitched by a cross-process flow edge
    tc = _trace_collect()
    evs = tc.merge(tc.find_spools(d))["traceEvents"]
    gets = [e for e in evs if e.get("ph") == "X"
            and e["name"] == "master.get_task"]
    assert len(gets) >= 2                  # client side + server side
    assert len({e["pid"] for e in gets}) == 2
    assert [e for e in evs if e.get("ph") == "s"]


@pytest.mark.slow
def test_two_process_serving_acceptance(tmp_path):
    """The ISSUE 12 acceptance: launch a real ServingClient process and
    a real ModelServer process with tools/launch.py, run ONE
    ``trace_collect`` command over the spools, and verify the client's
    request span strictly contains the server's admission ->
    prefill@bucket -> decode-step -> settle spans via propagated
    context, with >=1 flow event per cross-process edge."""
    d = str(tmp_path / "share")
    os.makedirs(d, exist_ok=True)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "launch.py"),
         "--nprocs", "2", "--use-cpu",
         os.path.join(TESTS_DIR, "serving_duo.py"), d],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO_ROOT, env=_env_base(), timeout=900)
    assert r.returncode == 0, r.stdout[-4000:]
    trace_id = next(line.split()[-1] for line in r.stdout.splitlines()
                    if "TRACE_ID" in line)
    assert len(trace_id) == 32

    # the one command
    rc = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "tools", "trace_collect.py"), d],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO_ROOT, env=_env_base(), timeout=120)
    assert rc.returncode == 0, rc.stdout
    assert os.path.exists(os.path.join(d, "trace.json"))
    chk = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "tools", "trace_collect.py"), d,
         "--check"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO_ROOT, env=_env_base(), timeout=120)
    assert chk.returncode == 0, chk.stdout

    tc = _trace_collect()
    spools = {os.path.basename(p).split(".")[0]: p
              for p in tc.find_spools(d)}
    _, client_spans, _ = tc.load_spool(spools["client"])
    _, server_spans, _ = tc.load_spool(spools["server"])
    req = next(s for s in client_spans
               if s["name"] == "serving.generate"
               and s.get("trace_id") == trace_id)
    mine = [s for s in server_spans if s.get("trace_id") == trace_id]
    names = {s["name"] for s in mine}
    assert "serving.admission" in names, names
    assert any(n.startswith("serving.prefill@") for n in names), names
    assert "serving.decode_step" in names, names
    assert "serving.settle" in names, names
    for s in mine:
        assert s["ts"] >= req["ts"] - 1.0, (s["name"], s["ts"], req)
        assert s["ts"] + s["dur"] <= req["ts"] + req["dur"] + 1.0, \
            (s["name"], s, req)
    # >=1 flow event per cross-process edge in the merged trace
    evs = json.load(open(os.path.join(d, "trace.json")))["traceEvents"]
    starts = [e for e in evs if e.get("ph") == "s"]
    finishes = [e for e in evs if e.get("ph") == "f"]
    assert starts and len(starts) == len(finishes)
