"""Elastic data-master tests (reference: go/master/service_test.go +
client_internal_test.go — task leasing, timeout re-issue, failure-max
drop, snapshot/recover; worker death simulated by not reporting, as the
reference tests kill processes)."""

import numpy as np
import pytest

from paddle_tpu import recordio
from paddle_tpu.core import native
from paddle_tpu.data.master import Master, task_reader

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native runtime unavailable (no g++)")


def _dataset(tmp_path, nfiles=2, records_per_file=30, per_chunk=10):
    paths = []
    for f in range(nfiles):
        p = str(tmp_path / f"data-{f}.recordio")
        with recordio.Writer(p, max_chunk_records=per_chunk) as w:
            for i in range(records_per_file):
                w.write(f"f{f}r{i}".encode())
        paths.append(p)
    return paths


def test_partition_and_drain(tmp_path):
    paths = _dataset(tmp_path)
    m = Master(timeout_s=60, failure_max=3)
    m.set_dataset(paths, chunks_per_task=1)
    assert m.stats()["todo"] == 6            # 2 files x 3 chunks
    got = sorted(r.decode() for r in task_reader(m))
    want = sorted(f"f{f}r{i}" for f in range(2) for i in range(30))
    assert got == want
    assert m.stats() == {"todo": 0, "pending": 0, "done": 6, "dropped": 0}


def test_lease_timeout_reissues(tmp_path):
    """A worker that leases a task and dies never reports; after the lease
    expires the task is re-issued and the epoch still completes fully."""
    import time
    paths = _dataset(tmp_path, nfiles=1)
    m = Master(timeout_s=0.2, failure_max=5)
    m.set_dataset(paths, chunks_per_task=1)

    killed = {"n": 0}

    def die_once(task):
        if killed["n"] == 0:
            killed["n"] += 1
            return True              # worker dies holding the lease
        return False

    got = sorted(r.decode() for r in task_reader(m, poll_interval=0.05,
                                                 fail_injector=die_once))
    assert killed["n"] == 1
    want = sorted(f"f0r{i}" for i in range(30))
    assert got == want               # nothing lost despite the death


def test_failure_max_drops_task(tmp_path):
    paths = _dataset(tmp_path, nfiles=1)
    # corrupt the file after partitioning so every scan fails
    m = Master(timeout_s=60, failure_max=2)
    m.set_dataset(paths, chunks_per_task=3)   # single task
    blob = bytearray(open(paths[0], "rb").read())
    blob[40] ^= 0xFF
    open(paths[0], "wb").write(bytes(blob))
    got = list(task_reader(m))
    stats = m.stats()
    assert stats["dropped"] == 1              # dropped after failure_max
    assert m.done


def test_stale_lease_report_rejected(tmp_path):
    """A timed-out worker's late finish/fail must not touch the re-issued
    lease of the same task (epoch guard, master.cc)."""
    import time
    paths = _dataset(tmp_path, nfiles=1)
    m = Master(timeout_s=0.1, failure_max=2)
    m.set_dataset(paths, chunks_per_task=3)    # single task
    stale = m.get_task()
    assert stale is not None
    time.sleep(0.15)                           # lease expires
    fresh = m.get_task()                       # re-issued, new epoch
    assert fresh is not None and fresh.id == stale.id
    assert not m.task_failed(stale)            # stale report rejected
    assert not m.task_finished(stale)
    assert m.stats()["pending"] == 1           # fresh lease untouched
    assert m.task_finished(fresh)
    assert m.done


def test_snapshot_recover(tmp_path):
    paths = _dataset(tmp_path, nfiles=1)
    m = Master(timeout_s=60, failure_max=3)
    m.set_dataset(paths, chunks_per_task=1)
    t = m.get_task()
    assert t is not None
    snap = str(tmp_path / "master.snap")
    m.snapshot(snap)                          # lease outstanding

    m2 = Master(timeout_s=60, failure_max=3)  # "restarted" master
    m2.recover(snap)
    # snapshot v2 preserves the outstanding lease WITH its epoch (the
    # reference re-queued instead, service.go:166 — lease preservation
    # is strictly stronger: the holder's report is still accepted, so a
    # master restart cannot re-train an in-flight chunk)
    assert m2.stats() == {"todo": 2, "pending": 1, "done": 0, "dropped": 0}
    # the original holder reports FAILED across the restart: accepted
    # (epoch matched) and the chunk re-queues for the drain below
    assert m2.task_failed(t)
    assert m2.stats()["todo"] == 3
    got = sorted(r.decode() for r in task_reader(m2))
    assert got == sorted(f"f0r{i}" for i in range(30))


def test_elastic_training_resume(tmp_path):
    """Checkpoint-restart elasticity: train, snapshot master + params,
    'crash', recover both, finish the epoch — every record seen exactly
    once across the crash (the EDL capability, SURVEY §5 failure
    detection/elastic recovery)."""
    import pickle
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    # dataset of pickled (x, y) samples
    rng = np.random.RandomState(0)
    p = str(tmp_path / "train.recordio")
    with recordio.Writer(p, max_chunk_records=8) as w:
        for i in range(32):
            x = rng.rand(4).astype(np.float32)
            y = np.asarray([x.sum()], dtype=np.float32)
            w.write(pickle.dumps((x, y)))

    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 9
        startup.random_seed = 9
        with fluid.program_guard(main, startup):
            x = layers.data(name="x", shape=[4], dtype="float32")
            y = layers.data(name="y", shape=[1], dtype="float32")
            pred = layers.fc(x, size=1)
            loss = layers.mean(layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        return main, startup, loss

    main, startup, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)

    m = Master(timeout_s=60, failure_max=3)
    m.set_dataset([p], chunks_per_task=1)

    seen = []

    def train_records(master, limit=None):
        batch_x, batch_y = [], []
        n = 0
        for rec in task_reader(master):
            x, y = pickle.loads(rec)
            seen.append(tuple(np.round(x, 6)))
            batch_x.append(x)
            batch_y.append(y)
            if len(batch_x) == 8:
                exe.run(main, feed={"x": np.stack(batch_x),
                                    "y": np.stack(batch_y)},
                        fetch_list=[loss.name], scope=scope)
                batch_x, batch_y = [], []
            n += 1
            if limit and n >= limit:
                return True          # "crash" mid-epoch
        return False

    crashed = train_records(m, limit=10)      # dies inside chunk 2
    assert crashed
    snap = str(tmp_path / "m.snap")
    m.snapshot(snap)
    fluid.io.save_persistables(exe, str(tmp_path / "ckpt"), main,
                               scope=scope)

    # --- restart: fresh master + scope, recover, finish the epoch -------
    m2 = Master(timeout_s=60, failure_max=3)
    m2.recover(snap)
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    fluid.io.load_persistables(exe, str(tmp_path / "ckpt"), main,
                               scope=scope)
    train_records(m2)
    assert m2.done
    # completed leases before the snapshot are not replayed; the leased-
    # but-unfinished chunk is; so every record appears at least once and
    # completed chunks exactly once
    assert len(set(seen)) == 32
