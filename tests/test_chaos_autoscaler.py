"""Process-level chaos for the autoscaling serving fleet (ISSUE 16):
the two closed-loop proofs the fast tier cannot stage.

1. **Load spike**: the same offered load that sheds >=5% of requests
   on a STATIC 2-replica fleet (shallow admission queues, fixed
   capacity) serves CLEAN under the autoscaled policy (deep queues
   absorbing while elastic capacity catches up) — zero sheds, zero
   client-visible failures — and the autoscaler's fleet-size trace
   shows the scale-up AND the drain-based scale-down in one run.

2. **Replica OOM under load**: an injected MemoryError mid-dispatch
   kills the replica WITHOUT acking (oom_exit), the supervisor finds
   the ``<role>.<pid>.memdump.json`` witness, classifies the death
   ``cause="oom"``, and REPLACES the slot with the registered
   smaller-footprint spec instead of re-entering the restart/
   quarantine loop — with zero acked-request loss (the router
   re-dispatches the unacked in-flight ids to the survivor).

Everything spawns real replica processes and compiles the tiny
decoder LM, so every test is ``slow``; the control law itself is
unit-proven in tests/test_autoscaler.py.
"""

import itertools
import json
import os
import threading
import time

import pytest

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

_LM_PARAMS = {"prompt_len": 8, "max_new": 8, "vocab": 32, "d_model": 16,
              "d_inner": 32, "n_head": 2, "n_layer": 2}


def _wave_spec(max_queue_depth=64, buckets=(1, 2), env=None):
    """The wave-path tiny decoder LM (slots=false selects
    GenerativeModel — the engine with the ``serving.dispatch`` chaos
    site the OOM injection needs)."""
    spec = {"model": {"kind": "decoder_lm", "name": "lm",
                      "slots": False, "buckets": list(buckets),
                      "params": dict(_LM_PARAMS)},
            "max_queue_depth": int(max_queue_depth)}
    if env:
        spec["env"] = dict(env)
    return spec


def _wait(pred, timeout, msg):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timeout waiting for {msg}")


def _spike_threads(endpoint, stop, results, sheds, errors, n=6):
    """The offered load of the spike: n generator threads issuing
    back-to-back greedy requests. A typed shed is COUNTED (the static
    arm's failure mode), any other client-visible failure is an
    error; every completed stream is recorded for the determinism
    audit."""
    from paddle_tpu.serving.client import ServingClient
    from paddle_tpu.serving.server import RequestShedError
    lock = threading.Lock()
    ids = itertools.count()

    def loop():
        cl = ServingClient(endpoint)
        try:
            while not stop.is_set():
                i = next(ids)
                rid = f"spike-{i}"
                prompt = (1 + (i % 5), 2, 3)
                try:
                    toks = cl.generate("lm", [prompt], max_new=4,
                                       request_id=rid)
                except RequestShedError:
                    with lock:
                        sheds.append(rid)
                    continue
                with lock:
                    results[rid] = (prompt, [int(x) for x in toks[0]])
        except Exception as e:          # audit, don't swallow
            errors.append(repr(e))
        finally:
            cl.close()

    threads = [threading.Thread(target=loop, daemon=True)
               for _ in range(n)]
    for t in threads:
        t.start()
    return threads


def _audit_streams(results):
    """Deterministic greedy: same prompt -> bit-identical stream,
    wherever (and however often, under failover) it executed."""
    by_prompt = {}
    for rid, (prompt, toks) in results.items():
        assert by_prompt.setdefault(prompt, toks) == toks, \
            f"stream diverged for {rid} (prompt {prompt})"


def test_load_spike_static_sheds_autoscaled_serves_clean(tmp_path):
    """The tentpole chaos proof, arm vs arm under the SAME offered
    load: static-2 with shallow queues sheds >=5%; the autoscaled
    fleet (deep queues + elastic capacity) sheds NOTHING and loses no
    acked request, while the fleet-size trace records a scale-up
    during the spike and a drain-based scale-down after it."""
    from paddle_tpu.serving import metrics as smetrics
    from paddle_tpu.serving.autoscaler import (Autoscaler,
                                               AutoscalePolicy)
    from paddle_tpu.serving.router import Router

    # -- arm 1: static-2, shallow queues --------------------------------
    shallow = _wave_spec(max_queue_depth=1)
    router = Router(spec=shallow, replicas=2,
                    workdir=str(tmp_path / "static"),
                    breaker_reset_s=0.5)
    router.start()
    assert router.wait_ready(timeout_s=600)
    ep = router.serve()
    stop = threading.Event()
    results, sheds, errors = {}, [], []
    threads = _spike_threads(ep, stop, results, sheds, errors, n=8)
    try:
        _wait(lambda: len(results) + len(sheds) >= 120, 120,
              "the static arm to absorb the spike")
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
        router.stop()
    assert not errors, f"static arm leaked non-shed failures: {errors}"
    total = len(results) + len(sheds)
    static_shed_ratio = len(sheds) / total
    assert static_shed_ratio >= 0.05, \
        (f"the spike must overwhelm static-2: only {len(sheds)}/{total} "
         f"shed ({static_shed_ratio:.1%}) — not a spike")

    # -- arm 2: the SAME spike, autoscaled ------------------------------
    deep = _wave_spec(max_queue_depth=512)
    router = Router(spec=deep, replicas=2,
                    workdir=str(tmp_path / "scaled"),
                    breaker_reset_s=0.5)
    router.start()
    assert router.wait_ready(timeout_s=600)
    ep = router.serve()
    policy = AutoscalePolicy(
        slo_queue_wait_p99_s=0.02, min_replicas=2, max_replicas=3,
        breach_window_s=0.5, clear_window_s=1.5, cooldown_s=2.0,
        window_s=4.0, poll_interval_s=0.25, scale_spec=deep)
    asc = Autoscaler(router=router, policy=policy).start()
    stop = threading.Event()
    results, sheds, errors = {}, [], []
    threads = _spike_threads(ep, stop, results, sheds, errors, n=8)
    try:
        # the saturated queue-wait p99 breaches the SLO -> the loop
        # scales to 3 and the new replica warms into the pool
        _wait(lambda: router.stats()["size"] >= 3, 120,
              "the breach to trigger a scale-up")
        _wait(lambda: router.stats()["ready"] >= 3, 600,
              "the scale-up replica to pass readyz")
        _wait(lambda: len(results) >= 120, 120,
              "the spike to keep flowing over the grown fleet")
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
    try:
        # the spike is over: the windowed signal clears and the loop
        # drains one replica back out — scale-DOWN rides the graceful
        # drain path, so it can never lose an acked request either
        _wait(lambda: any(d["action"] == "scale_down"
                          for d in asc.decisions), 120,
              "the clear signal to drain the fleet back down")
        _wait(lambda: router.stats()["size"] == 2, 60,
              "the pool to shrink to the floor")
    finally:
        asc.stop()
        trace = list(asc.fleet_trace)
        router.stop()

    assert not errors, f"autoscaled arm failures: {errors}"
    assert not sheds, \
        f"the autoscaled fleet shed {len(sheds)} requests (static " \
        f"shed {static_shed_ratio:.1%}); the loop failed to absorb"
    _audit_streams(results)
    sizes = [t["size"] for t in trace]
    assert max(sizes) >= 3, "no scale-up in the fleet-size trace"
    assert sizes[-1] == 2, "no scale-down in the fleet-size trace"
    down = [d for d in asc.decisions if d["action"] == "scale_down"]
    assert down and down[0].get("drained") is True, \
        "scale-down must be drain-based (graceful), not a kill"
    assert smetrics.AUTOSCALER_DECISIONS.labels(
        action="scale_up").value >= 1
    assert smetrics.AUTOSCALER_DECISIONS.labels(
        action="scale_down").value >= 1


def test_replica_oom_replaced_with_fallback_not_restart_looped(tmp_path):
    """OOM under load: the 10th ``serving.dispatch`` in slot 0's
    process (6 warmup dispatches + mid-wave under load) raises an
    injected MemoryError. The replica memdumps and dies WITHOUT
    acking; the supervisor classifies cause="oom" from the witness
    file and respawns the slot ONCE with the registered smaller
    fallback spec — no crash-loop accounting, no quarantine — while
    every client call completes on the survivor."""
    from paddle_tpu.serving import metrics as smetrics
    from paddle_tpu.serving.router import Router

    faulty = _wave_spec(env={
        "FLAGS_fault_plan":
            "serving.dispatch:raise@10:exc=MemoryError"})
    clean = _wave_spec()
    fallback = _wave_spec(buckets=(1,))    # the smaller-footprint config
    router = Router(specs=[faulty, clean],
                    workdir=str(tmp_path), breaker_reset_s=0.5,
                    oom_fallback=fallback)
    router.start()
    assert router.wait_ready(timeout_s=600)
    ep = router.serve()
    oom0 = smetrics.ROUTER_RESTARTS.labels(cause="oom").value
    quar0 = smetrics.ROUTER_RESTARTS.labels(
        cause="quarantine_retry").value
    pid0 = router.stats()["replicas"][0]["pid"]
    stop = threading.Event()
    results, sheds, errors = {}, [], []
    threads = _spike_threads(ep, stop, results, sheds, errors, n=2)
    st0 = None
    try:
        _wait(lambda: (router.stats()["replicas"][0]["last_exit"]
                       or {}).get("cause") == "oom",
              180, "slot 0 to die of the injected OOM")
        # replaced, not restart-looped: fresh pid, READY again, and the
        # slot is NOT failed/quarantined
        _wait(lambda: (router.stats()["replicas"][0]["state"] == "ready"
                       and router.stats()["replicas"][0]["pid"]
                       not in (None, pid0)),
              600, "the fallback replacement to pass readyz")
        time.sleep(1.0)                    # load outlives the outage
        st0 = router.stats()["replicas"][0]
        replaced_spec = router._by_index[0].spec
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
        router.stop()

    assert not errors, f"acked-request loss under the OOM: {errors}"
    assert not sheds
    _audit_streams(results)
    assert len(results) > 20, "load generator barely ran"

    # the memdump witness, where the supervisor promised to look
    ex = st0["last_exit"]
    assert ex["cause"] == "oom", ex
    assert ex["memdump"] and os.path.exists(ex["memdump"]), ex
    assert os.path.dirname(ex["memdump"]).endswith("replica0-flight")
    with open(ex["memdump"]) as f:
        dump = json.load(f)
    assert dump["exc_type"] == "MemoryError", dump
    assert dump["reason"] == "oom" and dump["role"] == "replica"
    import re
    assert re.fullmatch(r"replica\.\d+\.memdump\.json",
                        os.path.basename(ex["memdump"]))

    # classified + counted, and the slot took the FALLBACK config
    assert smetrics.ROUTER_RESTARTS.labels(
        cause="oom").value - oom0 >= 1
    assert replaced_spec == fallback, \
        "the OOM'd slot must come back on the smaller-footprint spec"
    assert st0["state"] == "ready"
    assert st0["restarts"] == 0 and st0["quarantines"] == 0, \
        f"an OOM replace must not enter crash-loop accounting: {st0}"
    assert smetrics.ROUTER_RESTARTS.labels(
        cause="quarantine_retry").value == quar0
