"""Numeric-gradient grid over ATTR-DEPENDENT branches (round-2 verdict
item 8): the single `__vjp__` design makes per-op grad bugs structurally
unlikely, but padding modes, strides, dilation, groups, axis cases and
interpolation flags each take different code paths inside an emitter —
this parametrized grid puts a central-difference check on every such
branch of the highest-risk ops (reference pattern: OpTest check_grad,
unittests/op_test.py:414, run across attr variants per op file)."""

import numpy as np
import pytest

from op_test import check_grad


def _r(*shape, seed=0, lo=-0.5, hi=0.5):
    rng = np.random.RandomState(seed)
    return (rng.rand(*shape) * (hi - lo) + lo).astype(np.float32)


# ------------------------------------------------------------------ conv2d

CONV2D_GRID = [
    # (stride, padding, dilation, groups)
    (1, 0, 1, 1),
    (2, 0, 1, 1),
    (1, 1, 1, 1),
    (2, 1, 1, 1),
    (1, 2, 1, 1),
    (1, 0, 2, 1),
    (2, 1, 2, 1),
    (1, 1, 1, 2),
    (1, 0, 1, 4),
    (2, 2, 2, 1),
]


@pytest.mark.parametrize("stride,pad,dil,groups", CONV2D_GRID)
def test_grad_conv2d_attr_grid(stride, pad, dil, groups):
    cin, cout, k = 4, 4, 3
    check_grad("conv2d",
               {"Input": {"x": _r(2, cin, 8, 8)},
                "Filter": {"w": _r(cout, cin // groups, k, k, seed=1)}},
               attrs={"strides": [stride, stride],
                      "paddings": [pad, pad],
                      "dilations": [dil, dil], "groups": groups},
               out_slot="Output", rtol=2e-2, atol=2e-3)


@pytest.mark.parametrize("stride,pad", [(1, 0), (2, 1), (2, 0), (1, 1)])
def test_grad_conv2d_transpose_attr_grid(stride, pad):
    check_grad("conv2d_transpose",
               {"Input": {"x": _r(2, 3, 5, 5)},
                "Filter": {"w": _r(3, 4, 3, 3, seed=1)}},
               attrs={"strides": [stride, stride], "paddings": [pad, pad],
                      "dilations": [1, 1], "groups": 1},
               out_slot="Output", rtol=2e-2, atol=2e-3)


@pytest.mark.parametrize("stride,pad", [(1, 1), (2, 1)])
def test_grad_depthwise_conv2d_attr_grid(stride, pad):
    check_grad("depthwise_conv2d",
               {"Input": {"x": _r(2, 4, 6, 6)},
                "Filter": {"w": _r(4, 1, 3, 3, seed=1)}},
               attrs={"strides": [stride, stride], "paddings": [pad, pad],
                      "dilations": [1, 1]},
               out_slot="Output", rtol=2e-2, atol=5e-4)


@pytest.mark.parametrize("stride,pad,dil", [(1, 0, 1), (2, 1, 1),
                                            (1, 1, 2)])
def test_grad_conv3d_attr_grid(stride, pad, dil):
    check_grad("conv3d",
               {"Input": {"x": _r(1, 2, 5, 5, 5)},
                "Filter": {"w": _r(3, 2, 3, 3, 3, seed=1)}},
               attrs={"strides": [stride] * 3, "paddings": [pad] * 3,
                      "dilations": [dil] * 3},
               out_slot="Output", rtol=2e-2, atol=5e-4)


# ------------------------------------------------------------------ pooling

POOL_GRID = [
    # (ptype, k, stride, pad, exclusive, global)
    ("max", 2, 2, 0, True, False),
    ("max", 3, 2, 1, True, False),
    ("max", 3, 1, 1, True, False),
    ("max", 2, 2, 0, True, True),
    ("avg", 2, 2, 0, True, False),
    ("avg", 3, 2, 1, True, False),
    ("avg", 3, 2, 1, False, False),
    ("avg", 3, 1, 1, True, False),
    ("avg", 2, 2, 0, True, True),
]


@pytest.mark.parametrize("ptype,k,stride,pad,excl,glob", POOL_GRID)
def test_grad_pool2d_attr_grid(ptype, k, stride, pad, excl, glob):
    # distinct, well-separated values: a max-pool kink inside the
    # central-difference stencil would corrupt the numeric grad
    rng = np.random.RandomState(0)
    x = np.linspace(-1, 1, 2 * 3 * 6 * 6).astype(np.float32)
    x = rng.permutation(x).reshape(2, 3, 6, 6)
    check_grad("pool2d", {"X": {"x": x}},
               attrs={"pooling_type": ptype, "ksize": [k, k],
                      "strides": [stride, stride], "paddings": [pad, pad],
                      "exclusive": excl, "global_pooling": glob},
               rtol=2e-2, atol=2e-3)


@pytest.mark.parametrize("ptype,stride", [("max", 2), ("avg", 2),
                                          ("avg", 1)])
def test_grad_pool3d_attr_grid(ptype, stride):
    check_grad("pool3d", {"X": {"x": _r(1, 2, 4, 4, 4)}},
               attrs={"pooling_type": ptype, "ksize": [2, 2, 2],
                      "strides": [stride] * 3, "paddings": [0, 0, 0]},
               rtol=2e-2, atol=5e-4)


# ------------------------------------------------------------------ padding

@pytest.mark.parametrize("mode", ["constant", "reflect", "edge"])
@pytest.mark.parametrize("pads", [[1, 1, 1, 1], [0, 2, 1, 0]])
def test_grad_pad2d_attr_grid(mode, pads):
    check_grad("pad2d", {"X": {"x": _r(2, 3, 5, 5)}},
               attrs={"paddings": pads, "mode": mode, "pad_value": 0.5},
               rtol=2e-2, atol=5e-4)


# -------------------------------------------------------------- interpolate

@pytest.mark.parametrize("op", ["bilinear_interp", "nearest_interp"])
@pytest.mark.parametrize("oh,ow", [(8, 8), (3, 7), (1, 5)])
def test_grad_interp_attr_grid(op, oh, ow):
    check_grad(op, {"X": {"x": _r(2, 2, 5, 5)}},
               attrs={"out_h": oh, "out_w": ow},
               rtol=2e-2, atol=5e-4)


# ---------------------------------------------------------- slice / strided

SLICE_GRID = [
    ([0], [1], [3]),
    ([1], [0], [2]),
    ([0, 2], [0, 1], [2, 4]),
    ([2], [-3], [-1]),          # negative starts/ends
    ([1], [2], [100]),          # end past the dim clamps
]


@pytest.mark.parametrize("axes,starts,ends", SLICE_GRID)
def test_grad_slice_attr_grid(axes, starts, ends):
    check_grad("slice", {"Input": {"x": _r(3, 4, 5)}},
               attrs={"axes": axes, "starts": starts, "ends": ends},
               rtol=2e-2, atol=5e-4)


@pytest.mark.parametrize("offsets", [[0, 0, 0], [1, 1, 2]])
def test_grad_crop_attr_grid(offsets):
    check_grad("crop", {"X": {"x": _r(3, 4, 5)}},
               attrs={"offsets": offsets, "shape": [2, 2, 2]},
               rtol=2e-2, atol=5e-4)


@pytest.mark.parametrize("times", [[2, 1, 1], [1, 2, 3]])
def test_grad_expand_attr_grid(times):
    check_grad("expand", {"X": {"x": _r(2, 3, 2)}},
               attrs={"expand_times": times}, rtol=2e-2, atol=5e-4)


# ------------------------------------------------------------------ reduces

@pytest.mark.parametrize("op", ["reduce_sum", "reduce_mean", "reduce_max",
                                "reduce_min", "reduce_prod"])
@pytest.mark.parametrize("dim,keep", [([0], False), ([1], True),
                                      ([0, 2], False)])
def test_grad_reduce_attr_grid(op, dim, keep):
    # reduce_max/min route grads only to the argmax; use distinct values
    x = np.linspace(-1, 1, 2 * 3 * 4).reshape(2, 3, 4).astype(np.float32)
    check_grad(op, {"X": {"x": x}},
               attrs={"dim": dim, "keep_dim": keep},
               rtol=2e-2, atol=5e-4)


def test_grad_reduce_all_attr():
    check_grad("reduce_sum", {"X": {"x": _r(2, 3)}},
               attrs={"reduce_all": True}, rtol=2e-2, atol=5e-4)


# ------------------------------------------------------- elementwise / axis

@pytest.mark.parametrize("op", ["elementwise_add", "elementwise_mul",
                                "elementwise_sub", "elementwise_div"])
@pytest.mark.parametrize("axis,yshape", [(-1, (2, 3, 4)), (0, (2,)),
                                         (1, (3,))])
def test_grad_elementwise_broadcast_grid(op, axis, yshape):
    ylo, yhi = (0.5, 1.5) if op == "elementwise_div" else (-0.5, 0.5)
    check_grad(op, {"X": {"x": _r(2, 3, 4)},
                    "Y": {"y": _r(*yshape, seed=1, lo=ylo, hi=yhi)}},
               attrs={"axis": axis}, rtol=2e-2, atol=5e-4)


# ------------------------------------------------------------------ matmuls

@pytest.mark.parametrize("tx,ty", [(False, False), (True, False),
                                   (False, True), (True, True)])
def test_grad_matmul_transpose_grid(tx, ty):
    xs = (4, 3) if tx else (3, 4)
    ys = (5, 4) if ty else (4, 5)
    check_grad("matmul", {"X": {"x": _r(*xs)}, "Y": {"y": _r(*ys, seed=1)}},
               attrs={"transpose_X": tx, "transpose_Y": ty},
               rtol=2e-2, atol=5e-4)


@pytest.mark.parametrize("ncol", [1, 2])
def test_grad_mul_num_col_dims_grid(ncol):
    check_grad("mul", {"X": {"x": _r(2, 3, 4)},
                       "Y": {"y": _r(12 if ncol == 1 else 4, 5, seed=1)}},
               attrs={"x_num_col_dims": ncol}, rtol=2e-2, atol=5e-4)


# ---------------------------------------------------------------- axis ops

@pytest.mark.parametrize("axis", [-1, 0, 1])
def test_grad_softmax_axis_grid(axis):
    check_grad("softmax", {"X": {"x": _r(3, 4, 5)}},
               attrs={"axis": axis}, rtol=2e-2, atol=5e-4)


@pytest.mark.parametrize("axis", [1, 2])
def test_grad_layer_norm_axis_grid(axis):
    d = (4, 5) if axis == 1 else (5,)
    import numpy as _np
    size = int(_np.prod(d)) if axis == 1 else 5
    check_grad("layer_norm",
               {"X": {"x": _r(3, 4, 5)},
                "Scale": {"s": _r(size, seed=1, lo=0.5, hi=1.5)},
                "Bias": {"b": _r(size, seed=2)}},
               attrs={"begin_norm_axis": axis},
               out_slot="Y", extra_out_slots=("Mean", "Variance"),
               rtol=2e-2, atol=5e-4)


@pytest.mark.parametrize("groups", [1, 2, 4])
def test_grad_group_norm_groups_grid(groups):
    check_grad("group_norm",
               {"X": {"x": _r(2, 4, 3, 3, lo=-1.5, hi=1.5)},
                "Scale": {"s": _r(4, seed=1, lo=0.5, hi=1.5)},
                "Bias": {"b": _r(4, seed=2)}},
               attrs={"groups": groups, "epsilon": 1e-5},
               out_slot="Y", extra_out_slots=("Mean", "Variance"),
               rtol=5e-2, atol=2e-3)


@pytest.mark.parametrize("perm", [[1, 0, 2], [2, 1, 0], [0, 2, 1]])
def test_grad_transpose_perm_grid(perm):
    check_grad("transpose", {"X": {"x": _r(2, 3, 4)}},
               attrs={"axis": perm}, rtol=2e-2, atol=5e-4)


@pytest.mark.parametrize("mode", ["all", "channel", "element"])
def test_grad_prelu_mode_grid(mode):
    shape = {"all": (1,), "channel": (3,), "element": (2, 3, 4, 4)}[mode]
    # keep x away from 0 (prelu kink) for the central difference
    x = _r(2, 3, 4, 4)
    x = np.where(np.abs(x) < 0.1, 0.2, x).astype(np.float32)
    check_grad("prelu",
               {"X": {"x": x},
                "Alpha": {"a": _r(*shape, seed=1, lo=0.1, hi=0.4)}},
               attrs={"mode": mode}, rtol=2e-2, atol=5e-4)


@pytest.mark.parametrize("axis", [0, 1, 2])
def test_grad_concat_axis_grid(axis):
    check_grad("concat",
               {"X": {"a": _r(2, 3, 4), "b": _r(2, 3, 4, seed=1)}},
               attrs={"axis": axis}, rtol=2e-2, atol=5e-4)


@pytest.mark.parametrize("axis", [0, 1, 2])
def test_grad_stack_axis_grid(axis):
    check_grad("stack",
               {"X": {"a": _r(2, 3), "b": _r(2, 3, seed=1)}},
               attrs={"axis": axis}, out_slot="Y",
               rtol=2e-2, atol=5e-4)


@pytest.mark.parametrize("pads", [[0, 1, 0, 1], [1, 0, 2, 0]])
def test_grad_pad_attr_grid(pads):
    check_grad("pad", {"X": {"x": _r(3, 4)}},
               attrs={"paddings": pads, "pad_value": 0.25},
               rtol=2e-2, atol=5e-4)
