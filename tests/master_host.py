"""Subprocess body for the master-failover test: host the chunk-lease
MasterServer on a FIXED port with a durability snapshot. First launch
partitions the dataset; a RELAUNCH with the same snapshot path recovers
the queue (pending leases included) and resumes serving — the reference's
master-recovers-from-etcd restart (go/master/service.go:165 recover,
clients re-dial via etcd watch, go/master/etcd_client.go:191).

Parent kills this process with SIGKILL mid-drain to simulate master
death. Prints "READY <endpoint>" once serving."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from paddle_tpu.data.master import Master                 # noqa: E402
from paddle_tpu.data.master_service import MasterServer   # noqa: E402


def main():
    port = int(os.environ["MASTER_PORT"])
    snap = os.environ["MASTER_SNAPSHOT"]
    paths = [p for p in os.environ.get("MASTER_PATHS", "").split(os.pathsep)
             if p]
    master = Master(timeout_s=float(os.environ.get("MASTER_LEASE_S", "10")),
                    failure_max=5)
    if not os.path.exists(snap):
        master.set_dataset(paths, chunks_per_task=1)
    # else: MasterServer(snapshot_path=snap) recovers the queue itself
    MasterServer(master, port=port, snapshot_path=snap)
    print(f"READY 127.0.0.1:{port}", flush=True)
    while True:          # serve until the parent kills us
        time.sleep(0.1)


if __name__ == "__main__":
    main()
