"""Two-process serving-acceptance body for tools/launch.py (ISSUE 12):
rank 0 hosts a slot-scheduled ModelServer, rank 1 drives one traced
generate through ServingClient. Each rank spools spans (and runs the
flight recorder) under its own role in the shared directory (argv[1]);
the parent test merges the spools with tools/trace_collect.py and
asserts the client's request span strictly CONTAINS the server's
admission -> prefill@bucket -> decode-step -> settle spans, stitched by
cross-process flow events. Rendezvous is file-based (endpoint.txt /
done.txt in the spool dir)."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np                                        # noqa: E402

from paddle_tpu import flags, serving                     # noqa: E402


def _await_file(path, deadline_s=180.0):
    deadline = time.time() + deadline_s
    while not os.path.exists(path):
        if time.time() > deadline:
            raise TimeoutError(f"timed out waiting for {path}")
        time.sleep(0.05)


def main():
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    share = sys.argv[1]
    role = "server" if rank == 0 else "client"
    flags.set("trace_spool_dir", share)
    flags.set("flight_recorder_dir", share)
    flags.set("trace_role", role)
    from paddle_tpu.observability import tracing
    assert tracing.active(), "spool autostart failed"

    ep_file = os.path.join(share, "endpoint.txt")
    done_file = os.path.join(share, "done.txt")
    if rank == 0:
        from paddle_tpu.models import transformer as T
        sgm = serving.SlotGenerativeModel(
            "lm", T.build_decoder_lm_programs(
                prompt_len=8, max_new=8, vocab=32, d_model=16,
                d_inner=32, n_head=2, n_layer=2,
                modes=("prefill_slot", "decode_slot"), n_slots=2))
        sgm.warmup()
        server = serving.ModelServer()
        server.add_model(sgm)
        endpoint = server.serve()
        tmp = ep_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(endpoint)
        os.replace(tmp, ep_file)          # atomic: never read half-written
        print(f"READY {endpoint}", flush=True)
        _await_file(done_file)
        server.stop()
    else:
        _await_file(ep_file)
        with open(ep_file) as f:
            endpoint = f.read().strip()
        client = serving.ServingClient(endpoint, timeout_s=120)
        (toks,) = client.generate("lm", [np.arange(1, 6)], max_new=6)
        assert len(toks) == 6, f"expected 6 tokens, got {len(toks)}"
        print(f"TRACE_ID {client.last_trace_id}", flush=True)
        client.close()
        with open(done_file, "w") as f:
            f.write("ok")

    from paddle_tpu.observability import flight_recorder, spool
    spool.shutdown()
    flight_recorder.shutdown()


if __name__ == "__main__":
    main()
