"""Real-format dataset parsers against tiny crafted fixture files
(round-1 verdict item 8: the zoo fell back to synthetic unless a cached
npz existed; now the actual formats parse — MNIST idx, cifar-python
pickled tars, aclImdb tokenization — with the reference's exact
conventions: mnist.py:44-76 normalization x/255*2-1, cifar.py /255.0 +
b'labels'/b'fine_labels', imdb.py punctuation-stripped lowercase split
with (-freq, word)-sorted vocab and pos=0/neg=1 labels)."""

import gzip
import io
import pickle
import struct
import tarfile

import numpy as np
import pytest

from paddle_tpu.dataset import cifar, imdb, mnist


# --- fixtures -----------------------------------------------------------

def _write_idx_images(path, images):
    """images: uint8 [N, rows, cols]."""
    n, r, c = images.shape
    with gzip.GzipFile(path, "wb") as f:
        f.write(struct.pack(">IIII", mnist.IMAGE_MAGIC, n, r, c))
        f.write(images.tobytes())


def _write_idx_labels(path, labels):
    with gzip.GzipFile(path, "wb") as f:
        f.write(struct.pack(">II", mnist.LABEL_MAGIC, len(labels)))
        f.write(np.asarray(labels, np.uint8).tobytes())


def _write_cifar_tar(path, batches):
    """batches: {member_name: (data uint8 [N,3072], labels, key)}."""
    with tarfile.open(path, "w:gz") as tf:
        for name, (data, labels, key) in batches.items():
            payload = pickle.dumps({b"data": data, key: labels})
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            tf.addfile(info, io.BytesIO(payload))


def _write_imdb_tar(path, docs):
    """docs: [(member_name, text bytes)]."""
    with tarfile.open(path, "w:gz") as tf:
        for name, text in docs:
            info = tarfile.TarInfo(name)
            info.size = len(text)
            tf.addfile(info, io.BytesIO(text))


# --- mnist --------------------------------------------------------------

def test_mnist_idx_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (4, 28, 28)).astype(np.uint8)
    labels = np.array([3, 1, 4, 1], np.uint8)
    ip = str(tmp_path / "imgs.gz")
    lp = str(tmp_path / "labels.gz")
    _write_idx_images(ip, imgs)
    _write_idx_labels(lp, labels)

    samples = list(mnist.reader_from_idx(ip, lp)())
    assert len(samples) == 4
    for (x, y), img, lab in zip(samples, imgs, labels):
        assert x.shape == (784,) and x.dtype == np.float32
        # reference normalization: /255*2-1
        np.testing.assert_allclose(
            x, img.reshape(784).astype(np.float32) / 255.0 * 2.0 - 1.0,
            rtol=1e-6)
        assert y == int(lab)


def test_mnist_idx_bad_magic(tmp_path):
    p = str(tmp_path / "bad.gz")
    with gzip.GzipFile(p, "wb") as f:
        f.write(struct.pack(">IIII", 1234, 1, 28, 28))
        f.write(bytes(784))
    with pytest.raises(ValueError, match="magic"):
        mnist.parse_idx_images(p)


def test_mnist_count_mismatch(tmp_path):
    ip, lp = str(tmp_path / "i.gz"), str(tmp_path / "l.gz")
    _write_idx_images(ip, np.zeros((2, 28, 28), np.uint8))
    _write_idx_labels(lp, np.zeros(3, np.uint8))
    with pytest.raises(ValueError, match="mismatch"):
        list(mnist.reader_from_idx(ip, lp)())


def test_mnist_discovery_via_data_home(tmp_path, monkeypatch):
    base = tmp_path / "mnist"
    base.mkdir()
    imgs = np.full((2, 28, 28), 128, np.uint8)
    _write_idx_images(str(base / "train-images-idx3-ubyte.gz"), imgs)
    _write_idx_labels(str(base / "train-labels-idx1-ubyte.gz"),
                      np.array([7, 2], np.uint8))
    monkeypatch.setattr("paddle_tpu.dataset.common.DATA_HOME",
                        str(tmp_path))
    samples = list(mnist.train()())
    assert len(samples) == 2 and samples[0][1] == 7


# --- cifar --------------------------------------------------------------

def test_cifar10_tar_parsing(tmp_path):
    rng = np.random.RandomState(1)
    d1 = rng.randint(0, 256, (3, 3072)).astype(np.uint8)
    d2 = rng.randint(0, 256, (2, 3072)).astype(np.uint8)
    p = str(tmp_path / "cifar-10-python.tar.gz")
    _write_cifar_tar(p, {
        "cifar-10-batches-py/data_batch_1": (d1, [0, 1, 2], b"labels"),
        "cifar-10-batches-py/data_batch_2": (d2, [3, 4], b"labels"),
        "cifar-10-batches-py/test_batch": (d2, [5, 6], b"labels"),
    })
    train = list(cifar.reader_from_tar(p, "data_batch")())
    assert len(train) == 5
    np.testing.assert_allclose(train[0][0],
                               d1[0].astype(np.float32) / 255.0)
    assert [y for _, y in train] == [0, 1, 2, 3, 4]
    test = list(cifar.reader_from_tar(p, "test_batch")())
    assert [y for _, y in test] == [5, 6]


def test_cifar100_fine_labels(tmp_path):
    d = np.zeros((2, 3072), np.uint8)
    p = str(tmp_path / "cifar-100-python.tar.gz")
    _write_cifar_tar(p, {
        "cifar-100-python/train": (d, [17, 93], b"fine_labels")})
    out = list(cifar.reader_from_tar(p, "train")())
    assert [y for _, y in out] == [17, 93]


def test_cifar_discovery_via_data_home(tmp_path, monkeypatch):
    base = tmp_path / "cifar"
    base.mkdir()
    d = np.ones((2, 3072), np.uint8)
    _write_cifar_tar(str(base / "cifar-10-python.tar.gz"), {
        "cifar-10-batches-py/data_batch_1": (d, [1, 2], b"labels")})
    monkeypatch.setattr("paddle_tpu.dataset.common.DATA_HOME",
                        str(tmp_path))
    out = list(cifar.train10()())
    assert len(out) == 2 and out[1][1] == 2


# --- imdb ---------------------------------------------------------------

_DOCS = [
    ("aclImdb/train/pos/0_9.txt", b"A great, GREAT movie!\n"),
    ("aclImdb/train/pos/1_8.txt", b"great acting; great fun\n"),
    ("aclImdb/train/neg/0_2.txt", b"terrible. just terrible movie\n"),
    ("aclImdb/test/pos/0_7.txt", b"great\n"),
]


def test_imdb_tokenize(tmp_path):
    p = str(tmp_path / "aclImdb_v1.tar.gz")
    _write_imdb_tar(p, _DOCS)
    docs = list(imdb.tokenize_tar(p, r"aclImdb/train/pos/.*\.txt$"))
    # punctuation removed, lowercased, whitespace split
    assert docs[0] == [b"a", b"great", b"great", b"movie"]
    assert docs[1] == [b"great", b"acting", b"great", b"fun"]


def test_imdb_build_dict_ordering(tmp_path):
    p = str(tmp_path / "aclImdb_v1.tar.gz")
    _write_imdb_tar(p, _DOCS)
    wi = imdb.build_dict(p, r"aclImdb/train/.*\.txt$", cutoff=0)
    # 'great' is most frequent -> id 0; ties sort lexicographically;
    # <unk> is the last id
    assert wi[b"great"] == 0
    assert wi[b"<unk>"] == len(wi) - 1
    freqs_sorted = sorted((w for w in wi if w != b"<unk>"),
                          key=lambda w: wi[w])
    assert freqs_sorted[0] == b"great"


def test_imdb_reader_labels(tmp_path):
    p = str(tmp_path / "aclImdb_v1.tar.gz")
    _write_imdb_tar(p, _DOCS)
    wi = imdb.build_dict(p, r"aclImdb/train/.*\.txt$", cutoff=0)
    samples = list(imdb.reader_from_tar(p, "train", wi)())
    # reference label convention: pos = 0 first, then neg = 1
    assert [lab for _, lab in samples] == [0, 0, 1]
    ids, _ = samples[0]
    assert ids[1] == wi[b"great"] and ids[2] == wi[b"great"]
    # unseen words map to <unk>
    samples_t = list(imdb.reader_from_tar(p, "test", wi)())
    assert samples_t[0][0] == [wi[b"great"]]


# ---------------------------------------------------------------------------
# round-3: real-format fixtures for the remaining zoo entries (13/13)
# ---------------------------------------------------------------------------

def _tar_add_bytes(tar, name, data):
    import io
    import tarfile
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tar.addfile(info, io.BytesIO(data))


def test_wmt14_tar_parsing(tmp_path):
    import tarfile
    from paddle_tpu.dataset import wmt14
    tar_path = str(tmp_path / "wmt14.tgz")
    src_dict = b"<s>\n<e>\n<unk>\nhello\nworld\n"
    trg_dict = b"<s>\n<e>\n<unk>\nbonjour\nmonde\n"
    corpus = b"hello world\tbonjour monde\nhello oov\tmonde\n"
    with tarfile.open(tar_path, "w:gz") as t:
        _tar_add_bytes(t, "wmt14/src.dict", src_dict)
        _tar_add_bytes(t, "wmt14/trg.dict", trg_dict)
        _tar_add_bytes(t, "wmt14/train", corpus)
    rows = list(wmt14.parse_tar(tar_path, "train", dict_size=5))
    # <s>=0 <e>=1 <unk>=2 hello=3 world=4 / bonjour=3 monde=4
    assert rows[0] == ([0, 3, 4, 1], [0, 3, 4], [3, 4, 1])
    assert rows[1] == ([0, 3, 2, 1], [0, 4], [4, 1])   # oov -> <unk>


def test_wmt16_dict_built_from_corpus(tmp_path):
    import tarfile
    from paddle_tpu.dataset import wmt16
    tar_path = str(tmp_path / "wmt16.tar.gz")
    corpus = (b"the cat sat\tdie katze sass\n"
              b"the dog\tder hund\n")
    with tarfile.open(tar_path, "w:gz") as t:
        _tar_add_bytes(t, "wmt16/train", corpus)
    d = wmt16.build_dict(tar_path, dict_size=6, lang="en")
    # marks first, then 'the' (freq 2) then first-seen order
    assert d["<s>"] == 0 and d["<e>"] == 1 and d["<unk>"] == 2
    assert d["the"] == 3
    assert len(d) == 6
    rows = list(wmt16.parse_tar(tar_path, "wmt16/train", 6, 6))
    assert rows[0][0][0] == 0 and rows[0][0][-1] == 1     # <s> ... <e>
    assert rows[0][2][-1] == 1                            # trg_next ends <e>


def test_movielens_zip_parsing(tmp_path):
    import zipfile
    from paddle_tpu.dataset import movielens
    zp = str(tmp_path / "ml-1m.zip")
    with zipfile.ZipFile(zp, "w") as z:
        z.writestr("ml-1m/movies.dat",
                   "1::Toy Story (1995)::Animation|Children's\n"
                   "2::Jumanji (1995)::Adventure\n")
        z.writestr("ml-1m/users.dat",
                   "1::F::1::10::48067\n2::M::56::16::70072\n")
        z.writestr("ml-1m/ratings.dat",
                   "1::1::5::978300760\n2::2::3::978299026\n")
    movies, users, ratings = movielens.parse_zip(zp)
    assert movies[1][0].strip() == "Toy Story"
    assert users[2] == (True, movielens.AGES.index(56), 16)
    assert ratings[0] == (1, 1, 5.0)          # 5*2-5
    rows = list(movielens.real_reader(zp, is_test=False))
    for row in rows:
        uid, gender, age, job, mid, cats, title, rating = row
        assert isinstance(cats, list) and isinstance(title, list)
        assert rating[0] in (5.0, 1.0)


def test_conll05_bracket_decoding(tmp_path):
    import gzip
    import io
    import tarfile
    from paddle_tpu.dataset import conll05
    words = b"The\ncat\nsat\n\n"
    props = b"-  (A0*\n-  *)\nsat  (V*)\n\n"
    tar_path = str(tmp_path / "conll05st-tests.tar.gz")

    def gz(data):
        buf = io.BytesIO()
        with gzip.GzipFile(fileobj=buf, mode="wb") as f:
            f.write(data)
        return buf.getvalue()

    with tarfile.open(tar_path, "w:gz") as t:
        _tar_add_bytes(t, "conll05st-release/test.wsj/words/"
                       "test.wsj.words.gz", gz(words))
        _tar_add_bytes(t, "conll05st-release/test.wsj/props/"
                       "test.wsj.props.gz", gz(props))
    rows = list(conll05.corpus_reader(tar_path)())
    assert rows == [(["The", "cat", "sat"], "sat",
                     ["B-A0", "I-A0", "B-V"])]
    # dict loading + 9-tuple framing
    (tmp_path / "wordDict.txt").write_text("The\ncat\nsat\n")
    (tmp_path / "verbDict.txt").write_text("sat\n")
    (tmp_path / "targetDict.txt").write_text("B-A0\nI-A0\nB-V\nO\n")
    wd = conll05.load_dict(str(tmp_path / "wordDict.txt"))
    vd = conll05.load_dict(str(tmp_path / "verbDict.txt"))
    ld = conll05.load_label_dict(str(tmp_path / "targetDict.txt"))
    nine = list(conll05.reader_creator(
        conll05.corpus_reader(tar_path), wd, vd, ld)())
    assert len(nine) == 1 and len(nine[0]) == 9
    words_idx, *ctxs, verb, mark, labels = nine[0]
    assert words_idx == [0, 1, 2]
    assert mark == [1, 1, 1]                    # +-2 window covers all
    assert verb == [0, 0, 0]
    assert labels == [ld["B-A0"], ld["I-A0"], ld["B-V"]]


def test_sentiment_corpus_dir(tmp_path, monkeypatch):
    from paddle_tpu.dataset import common, sentiment
    root = tmp_path / "corpora" / "movie_reviews"
    (root / "neg").mkdir(parents=True)
    (root / "pos").mkdir(parents=True)
    (root / "neg" / "a.txt").write_text("bad awful bad")
    (root / "pos" / "b.txt").write_text("good great good great good")
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    d = dict(sentiment.build_word_dict(str(root)))
    assert d["good"] == 0                      # freq 3
    rows = list(sentiment._reader("train", 4, 0)())
    assert [y for _, y in rows] == [0, 1]      # interleaved neg/pos
    assert rows[0][0] == [d["bad"], d["awful"], d["bad"]]


def test_voc2012_tar_parsing(tmp_path):
    import io
    import tarfile
    import numpy as np
    from PIL import Image
    from paddle_tpu.dataset import voc2012
    tar_path = str(tmp_path / "voc.tar")

    def png_bytes(arr, mode):
        buf = io.BytesIO()
        Image.fromarray(arr, mode=mode).save(buf, format="PNG")
        return buf.getvalue()

    def jpg_bytes(arr):
        buf = io.BytesIO()
        Image.fromarray(arr, mode="RGB").save(buf, format="JPEG")
        return buf.getvalue()

    img = (np.arange(12 * 10 * 3) % 255).astype(np.uint8).reshape(12, 10, 3)
    lbl = (np.arange(12 * 10) % 21).astype(np.uint8).reshape(12, 10)
    with tarfile.open(tar_path, "w") as t:
        _tar_add_bytes(t, voc2012.SET_FILE.format("val"), b"2007_000001\n")
        _tar_add_bytes(t, voc2012.DATA_FILE.format("2007_000001"),
                       jpg_bytes(img))
        _tar_add_bytes(t, voc2012.LABEL_FILE.format("2007_000001"),
                       png_bytes(lbl, "L"))
    rows = list(voc2012.parse_tar(tar_path, "val"))
    assert len(rows) == 1
    x, y = rows[0]
    assert x.shape == (12, 10, 3) and y.shape == (12, 10)
    np.testing.assert_array_equal(y, lbl)      # png mask is lossless


def test_flowers_archives(tmp_path):
    import io
    import tarfile
    import numpy as np
    import scipy.io as scio
    from PIL import Image
    from paddle_tpu.dataset import flowers
    tgz = str(tmp_path / "102flowers.tgz")
    rng = np.random.RandomState(0)
    with tarfile.open(tgz, "w:gz") as t:
        for i in (1, 2):
            arr = rng.randint(0, 255, (300, 280, 3)).astype(np.uint8)
            buf = io.BytesIO()
            Image.fromarray(arr).save(buf, format="JPEG")
            _tar_add_bytes(t, f"jpg/image_{i:05d}.jpg", buf.getvalue())
    scio.savemat(str(tmp_path / "imagelabels.mat"),
                 {"labels": np.array([[5, 9]])})
    scio.savemat(str(tmp_path / "setid.mat"),
                 {"tstid": np.array([[1, 2]]), "trnid": np.array([[2]]),
                  "valid": np.array([[1]])})
    rows = list(flowers.parse_archives(tgz, str(tmp_path /
                "imagelabels.mat"), str(tmp_path / "setid.mat"), "train"))
    assert len(rows) == 2
    x, y = rows[0]
    assert x.shape == (3 * 224 * 224,) and y in (4, 8)   # 0-based labels
    rows_v = list(flowers.parse_archives(tgz, str(tmp_path /
                  "imagelabels.mat"), str(tmp_path / "setid.mat"),
                  "valid"))
    assert len(rows_v) == 1 and rows_v[0][1] == 4


def test_imikolov_ptb_tar(tmp_path):
    import tarfile
    from paddle_tpu.dataset import imikolov
    tar_path = str(tmp_path / "simple-examples.tgz")
    train = b"the cat sat\nthe dog sat\n"
    valid = b"the cat\n"
    with tarfile.open(tar_path, "w:gz") as t:
        _tar_add_bytes(t, imikolov.TRAIN_MEMBER, train)
        _tar_add_bytes(t, imikolov.TEST_MEMBER, valid)
    d = imikolov.build_dict_real(tar_path, min_word_freq=2)
    # freq: the=3, sat=2, cat=2 (+<s>/<e> 3 each); <unk> appended last
    assert d["<unk>"] == len(d) - 1
    assert d["the"] < d["cat"]
    sents = list(imikolov.parse_tar(tar_path, imikolov.TRAIN_MEMBER))
    assert sents[0] == ["the", "cat", "sat"]


def test_uci_housing_file(tmp_path):
    import numpy as np
    from paddle_tpu.dataset import uci_housing
    rows = np.random.RandomState(0).rand(10, 14)
    path = str(tmp_path / "housing.data")
    with open(path, "w") as f:
        for r in rows:
            f.write(" ".join(f"{v:.6f}" for v in r) + "\n")
    tr, te = uci_housing.load_data(path)
    assert tr.shape == (8, 14) and te.shape == (2, 14)
    # normalization: (x - avg) / (max - min) on features, target untouched
    col0 = (rows[:, 0] - rows[:, 0].mean()) / (rows[:, 0].max()
                                               - rows[:, 0].min())
    np.testing.assert_allclose(tr[:, 0], col0[:8], rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(tr[:, -1], rows[:8, -1], rtol=1e-3, atol=1e-4)


def test_mq2007_letor_parsing(tmp_path):
    import numpy as np
    from paddle_tpu.dataset import mq2007
    path = str(tmp_path / "train.txt")
    with open(path, "w") as f:
        f.write("2 qid:10 1:0.5 3:0.25 46:1.0 #docid = d1\n")
        f.write("0 qid:10 1:0.1 #docid = d2\n")
        f.write("1 qid:11 2:0.9 #docid = d3\n")
    groups = list(mq2007.parse_letor(path))
    assert len(groups) == 2
    labels, feats = groups[0]
    np.testing.assert_allclose(labels, [2.0, 0.0])
    assert feats.shape == (2, 46)
    assert feats[0, 0] == 0.5 and feats[0, 2] == 0.25 and feats[0, 45] == 1.0
    assert groups[1][1][0, 1] == np.float32(0.9)


def test_imikolov_real_reader_end_to_end(tmp_path, monkeypatch):
    """The reader-level real path: tar-discovered sentences map through
    word_idx to integer n-grams (code-review regression: a generator
    `return` dropped the stream and tokens went unmapped)."""
    import tarfile
    from paddle_tpu.dataset import common, imikolov
    (tmp_path / "imikolov").mkdir()
    tar_path = str(tmp_path / "imikolov" / "simple-examples.tgz")
    with tarfile.open(tar_path, "w:gz") as t:
        _tar_add_bytes(t, imikolov.TRAIN_MEMBER,
                       b"the cat sat\nthe dog sat\n")
        _tar_add_bytes(t, imikolov.TEST_MEMBER, b"the cat\n")
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    word_idx = imikolov.build_dict(min_word_freq=1)
    grams = list(imikolov.train(word_idx, 3)())
    assert grams, "real-path reader yielded nothing"
    flat = [w for g in grams for w in g]
    assert all(isinstance(w, int) for w in flat)
    assert max(flat) < len(word_idx)
    # the same sentence framing as the reference: last gram ends with <e>
    assert grams[0][-1] != word_idx["<e>"] or len(grams[0]) == 3
