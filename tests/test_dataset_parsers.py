"""Real-format dataset parsers against tiny crafted fixture files
(round-1 verdict item 8: the zoo fell back to synthetic unless a cached
npz existed; now the actual formats parse — MNIST idx, cifar-python
pickled tars, aclImdb tokenization — with the reference's exact
conventions: mnist.py:44-76 normalization x/255*2-1, cifar.py /255.0 +
b'labels'/b'fine_labels', imdb.py punctuation-stripped lowercase split
with (-freq, word)-sorted vocab and pos=0/neg=1 labels)."""

import gzip
import io
import pickle
import struct
import tarfile

import numpy as np
import pytest

from paddle_tpu.dataset import cifar, imdb, mnist


# --- fixtures -----------------------------------------------------------

def _write_idx_images(path, images):
    """images: uint8 [N, rows, cols]."""
    n, r, c = images.shape
    with gzip.GzipFile(path, "wb") as f:
        f.write(struct.pack(">IIII", mnist.IMAGE_MAGIC, n, r, c))
        f.write(images.tobytes())


def _write_idx_labels(path, labels):
    with gzip.GzipFile(path, "wb") as f:
        f.write(struct.pack(">II", mnist.LABEL_MAGIC, len(labels)))
        f.write(np.asarray(labels, np.uint8).tobytes())


def _write_cifar_tar(path, batches):
    """batches: {member_name: (data uint8 [N,3072], labels, key)}."""
    with tarfile.open(path, "w:gz") as tf:
        for name, (data, labels, key) in batches.items():
            payload = pickle.dumps({b"data": data, key: labels})
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            tf.addfile(info, io.BytesIO(payload))


def _write_imdb_tar(path, docs):
    """docs: [(member_name, text bytes)]."""
    with tarfile.open(path, "w:gz") as tf:
        for name, text in docs:
            info = tarfile.TarInfo(name)
            info.size = len(text)
            tf.addfile(info, io.BytesIO(text))


# --- mnist --------------------------------------------------------------

def test_mnist_idx_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (4, 28, 28)).astype(np.uint8)
    labels = np.array([3, 1, 4, 1], np.uint8)
    ip = str(tmp_path / "imgs.gz")
    lp = str(tmp_path / "labels.gz")
    _write_idx_images(ip, imgs)
    _write_idx_labels(lp, labels)

    samples = list(mnist.reader_from_idx(ip, lp)())
    assert len(samples) == 4
    for (x, y), img, lab in zip(samples, imgs, labels):
        assert x.shape == (784,) and x.dtype == np.float32
        # reference normalization: /255*2-1
        np.testing.assert_allclose(
            x, img.reshape(784).astype(np.float32) / 255.0 * 2.0 - 1.0,
            rtol=1e-6)
        assert y == int(lab)


def test_mnist_idx_bad_magic(tmp_path):
    p = str(tmp_path / "bad.gz")
    with gzip.GzipFile(p, "wb") as f:
        f.write(struct.pack(">IIII", 1234, 1, 28, 28))
        f.write(bytes(784))
    with pytest.raises(ValueError, match="magic"):
        mnist.parse_idx_images(p)


def test_mnist_count_mismatch(tmp_path):
    ip, lp = str(tmp_path / "i.gz"), str(tmp_path / "l.gz")
    _write_idx_images(ip, np.zeros((2, 28, 28), np.uint8))
    _write_idx_labels(lp, np.zeros(3, np.uint8))
    with pytest.raises(ValueError, match="mismatch"):
        list(mnist.reader_from_idx(ip, lp)())


def test_mnist_discovery_via_data_home(tmp_path, monkeypatch):
    base = tmp_path / "mnist"
    base.mkdir()
    imgs = np.full((2, 28, 28), 128, np.uint8)
    _write_idx_images(str(base / "train-images-idx3-ubyte.gz"), imgs)
    _write_idx_labels(str(base / "train-labels-idx1-ubyte.gz"),
                      np.array([7, 2], np.uint8))
    monkeypatch.setattr("paddle_tpu.dataset.common.DATA_HOME",
                        str(tmp_path))
    samples = list(mnist.train()())
    assert len(samples) == 2 and samples[0][1] == 7


# --- cifar --------------------------------------------------------------

def test_cifar10_tar_parsing(tmp_path):
    rng = np.random.RandomState(1)
    d1 = rng.randint(0, 256, (3, 3072)).astype(np.uint8)
    d2 = rng.randint(0, 256, (2, 3072)).astype(np.uint8)
    p = str(tmp_path / "cifar-10-python.tar.gz")
    _write_cifar_tar(p, {
        "cifar-10-batches-py/data_batch_1": (d1, [0, 1, 2], b"labels"),
        "cifar-10-batches-py/data_batch_2": (d2, [3, 4], b"labels"),
        "cifar-10-batches-py/test_batch": (d2, [5, 6], b"labels"),
    })
    train = list(cifar.reader_from_tar(p, "data_batch")())
    assert len(train) == 5
    np.testing.assert_allclose(train[0][0],
                               d1[0].astype(np.float32) / 255.0)
    assert [y for _, y in train] == [0, 1, 2, 3, 4]
    test = list(cifar.reader_from_tar(p, "test_batch")())
    assert [y for _, y in test] == [5, 6]


def test_cifar100_fine_labels(tmp_path):
    d = np.zeros((2, 3072), np.uint8)
    p = str(tmp_path / "cifar-100-python.tar.gz")
    _write_cifar_tar(p, {
        "cifar-100-python/train": (d, [17, 93], b"fine_labels")})
    out = list(cifar.reader_from_tar(p, "train")())
    assert [y for _, y in out] == [17, 93]


def test_cifar_discovery_via_data_home(tmp_path, monkeypatch):
    base = tmp_path / "cifar"
    base.mkdir()
    d = np.ones((2, 3072), np.uint8)
    _write_cifar_tar(str(base / "cifar-10-python.tar.gz"), {
        "cifar-10-batches-py/data_batch_1": (d, [1, 2], b"labels")})
    monkeypatch.setattr("paddle_tpu.dataset.common.DATA_HOME",
                        str(tmp_path))
    out = list(cifar.train10()())
    assert len(out) == 2 and out[1][1] == 2


# --- imdb ---------------------------------------------------------------

_DOCS = [
    ("aclImdb/train/pos/0_9.txt", b"A great, GREAT movie!\n"),
    ("aclImdb/train/pos/1_8.txt", b"great acting; great fun\n"),
    ("aclImdb/train/neg/0_2.txt", b"terrible. just terrible movie\n"),
    ("aclImdb/test/pos/0_7.txt", b"great\n"),
]


def test_imdb_tokenize(tmp_path):
    p = str(tmp_path / "aclImdb_v1.tar.gz")
    _write_imdb_tar(p, _DOCS)
    docs = list(imdb.tokenize_tar(p, r"aclImdb/train/pos/.*\.txt$"))
    # punctuation removed, lowercased, whitespace split
    assert docs[0] == [b"a", b"great", b"great", b"movie"]
    assert docs[1] == [b"great", b"acting", b"great", b"fun"]


def test_imdb_build_dict_ordering(tmp_path):
    p = str(tmp_path / "aclImdb_v1.tar.gz")
    _write_imdb_tar(p, _DOCS)
    wi = imdb.build_dict(p, r"aclImdb/train/.*\.txt$", cutoff=0)
    # 'great' is most frequent -> id 0; ties sort lexicographically;
    # <unk> is the last id
    assert wi[b"great"] == 0
    assert wi[b"<unk>"] == len(wi) - 1
    freqs_sorted = sorted((w for w in wi if w != b"<unk>"),
                          key=lambda w: wi[w])
    assert freqs_sorted[0] == b"great"


def test_imdb_reader_labels(tmp_path):
    p = str(tmp_path / "aclImdb_v1.tar.gz")
    _write_imdb_tar(p, _DOCS)
    wi = imdb.build_dict(p, r"aclImdb/train/.*\.txt$", cutoff=0)
    samples = list(imdb.reader_from_tar(p, "train", wi)())
    # reference label convention: pos = 0 first, then neg = 1
    assert [lab for _, lab in samples] == [0, 0, 1]
    ids, _ = samples[0]
    assert ids[1] == wi[b"great"] and ids[2] == wi[b"great"]
    # unseen words map to <unk>
    samples_t = list(imdb.reader_from_tar(p, "test", wi)())
    assert samples_t[0][0] == [wi[b"great"]]
