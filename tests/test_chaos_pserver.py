"""Chaos: the async-pserver trainer client under injected faults — a
connection drop before the push is sent is retried (and applied exactly
once), while a persistently dead pserver trips the circuit breaker into
fast-fail instead of hanging every training step.

The paddle_pserver_* / paddle_breaker_* counters are asserted against
the injected fault schedule — the telemetry is a second witness for the
retry/breaker behavior."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.distributed import AsyncPServer, AsyncTrainerClient
from paddle_tpu.distributed import async_pserver as aps
from paddle_tpu.distributed import resilience
from paddle_tpu.distributed.resilience import (CircuitBreaker,
                                               CircuitOpenError, RetryError,
                                               RetryPolicy)
from paddle_tpu.fluid.transpiler import DistributeTranspiler
from paddle_tpu.utils import faults
from _dist_utils import bound_listener as _bound_listener

pytestmark = pytest.mark.chaos


def _server(lr=0.1):
    from paddle_tpu.fluid import unique_name
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = 7
    startup.random_seed = 7
    with unique_name.guard():
        with fluid.program_guard(main_p, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.fc(x, 1, bias_attr=False)
            loss = fluid.layers.mean(y)
            fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    t = DistributeTranspiler()
    ep = "127.0.0.1:0"
    t.transpile(0, program=main_p, pservers=ep, trainers=2,
                sync_mode=False, startup_program=startup)
    ps_prog = t.get_pserver_program(ep)
    ps = AsyncPServer(ps_prog, t.get_startup_program(ep, ps_prog))
    g = t.send_vars[0]
    pname = next(p for p in t.params if g == p + "@GRAD")
    return ps, g, pname


def _fast_retry(max_attempts=5):
    return RetryPolicy(max_attempts=max_attempts, base_delay_s=0.001,
                       max_delay_s=0.004, deadline_s=5.0,
                       retryable=(ConnectionError, OSError, EOFError))


def test_push_retried_through_connect_fault_applies_exactly_once():
    ps, g, pname = _server()
    listener, port = _bound_listener()
    ps.serve(listener=listener)
    retries0 = aps.PS_RPC_RETRIES.labels(op="push").value
    applied0 = aps.PS_GRADS_APPLIED.value
    push_lat0 = aps.PS_RPC_SECONDS.labels(op="push").count
    try:
        c = AsyncTrainerClient(("127.0.0.1", port), trainer_id=0,
                               retry_policy=_fast_retry())
        w0 = c.pull([pname])[pname].copy()
        # the fault fires at the top of the first attempt — before the
        # request hits the wire — so the retry is safe and the gradient
        # applies exactly once
        with faults.active(
                "pserver.push_grad:raise@1:exc=ConnectionError"):
            c.push_grad(g, np.ones(w0.shape, np.float32))
        assert ps.n_applied == 1, "retried push must apply exactly once"
        # counters match the schedule: one injected drop → one recorded
        # push retry, one applied gradient, one latency sample
        assert aps.PS_RPC_RETRIES.labels(op="push").value \
            - retries0 == 1
        assert aps.PS_GRADS_APPLIED.value - applied0 == 1
        assert aps.PS_RPC_SECONDS.labels(op="push").count \
            - push_lat0 == 1
        w1 = c.pull([pname])[pname]
        np.testing.assert_allclose(w1, w0 - 0.1 * np.ones(w0.shape),
                                   rtol=1e-6)
        c.close()
    finally:
        ps.stop()


def test_pull_retried_through_transient_fault():
    ps, g, pname = _server()
    listener, port = _bound_listener()
    ps.serve(listener=listener)
    try:
        c = AsyncTrainerClient(("127.0.0.1", port), trainer_id=0,
                               retry_policy=_fast_retry())
        with faults.active("pserver.pull:raise@1:exc=ConnectionError"):
            params = c.pull([pname])
        assert pname in params
        c.close()
    finally:
        ps.stop()


def test_breaker_fast_fails_a_dead_pserver():
    ps, g, pname = _server()
    listener, port = _bound_listener()
    ps.serve(listener=listener)
    opens0 = resilience.BREAKER_OPENS.labels(name="chaos-ps").value
    exhausted0 = resilience.RETRY_EXHAUSTED.labels(what="push").value
    try:
        c = AsyncTrainerClient(
            ("127.0.0.1", port), trainer_id=0,
            retry_policy=_fast_retry(max_attempts=1),
            breaker=CircuitBreaker(failure_threshold=2,
                                   reset_timeout_s=60.0,
                                   name="chaos-ps"))
        with faults.active(
                "pserver.push_grad:raise@every1:exc=ConnectionError"):
            for _ in range(2):             # exhaust the breaker threshold
                with pytest.raises(RetryError):
                    c.push_grad(g, np.zeros((4, 1), np.float32))
            # circuit open: fast-fail without touching the retry budget
            with pytest.raises(CircuitOpenError):
                c.push_grad(g, np.zeros((4, 1), np.float32))
        assert ps.n_applied == 0
        # telemetry matches the schedule: two spent retry budgets, one
        # breaker trip, and the state gauge reads open (2)
        assert resilience.RETRY_EXHAUSTED.labels(what="push").value \
            - exhausted0 == 2
        assert resilience.BREAKER_OPENS.labels(
            name="chaos-ps").value - opens0 == 1
        assert resilience.BREAKER_STATE.labels(name="chaos-ps").value == 2
        c.close()
    finally:
        ps.stop()
