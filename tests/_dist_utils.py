"""Shared helpers for the multiprocess distributed tests — ONE definition
of the small-DeepFM build (the param-name contract between trainer
workers, pserver programs, and eval programs: all three must construct
byte-identical graphs) plus the race-free port utilities and held-out
-eval helpers shared across the dist suites.

Port discipline (round-4 VERDICT weak #6): never allocate-close-rebind a
port number — hold a PortReservation open across the child's bind
(coordinator case), or bind the server socket at allocation and hand it
to serve() (pserver case)."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.utils.net import PortReservation, bound_listener  # noqa: F401



def build_deepfm_small(is_train: bool = True):
    """Deterministic names (unique_name.guard) + fixed seed: trainer,
    pserver, and eval processes all rebuild this exact graph."""
    from paddle_tpu import models
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = 3
    startup.random_seed = 3
    with fluid.unique_name.guard(), fluid.program_guard(main_p, startup):
        loss, _, _ = models.deepfm.build(
            is_train=is_train, num_fields=4, vocab_size=64, embed_dim=8,
            lr=1e-2)
    return main_p, startup, loss


def noisy_deepfm_labels(rng, ids) -> np.ndarray:
    """Training labels for the dist suites: `ids[:,0,0] % 2` with ~5% of
    examples flipped per OCCURRENCE (fresh randomness each batch, so the
    noise is irreducible — a deterministic flip would just be a
    relearnable relabeling). Why the floor matters (r5 stability loop,
    two distinct 1-in-10 failures): on the perfectly separable task the
    sync baseline drives the loss to ~1e-9, which (a) makes relative
    tolerance bands meaningless and (b) saturates the softmax so a
    single stale async push explodes the loss (observed 1e-6 → 8.0).
    With a ~5% noise floor the trained model stays at p≈0.95 and
    gradients stay bounded."""
    base = (ids[:, 0, 0] % 2).astype(np.float32)
    flip = (rng.rand(ids.shape[0]) < 0.05).astype(np.float32)
    return np.abs(base - flip)[:, None]


def eval_deepfm_loss(scope, label_fn=None) -> float:
    """Held-out batch loss under the params in `scope`. label_fn(ids) ->
    label column; default matches the convergence-matrix data regime."""
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(999)
    ids = rng.randint(0, 64, size=(128, 4, 1)).astype("int64")
    if label_fn is None:
        label = (ids[:, 0, 0] % 2).astype(np.float32)[:, None]
    else:
        label = label_fn(ids)
    eval_p, _, eval_l = build_deepfm_small(is_train=False)
    (lv,) = exe.run(eval_p, feed={"feat_ids": ids, "label": label},
                    fetch_list=[eval_l.name], scope=scope)
    return float(np.asarray(lv).reshape(()))
