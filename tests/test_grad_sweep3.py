"""Grad sweep 3: numeric-gradient coverage for differentiable ops no
other suite names directly (reference OpTest files: test_activation_op.py
for the activation grid, test_reduce_op.py max/min/prod,
test_elementwise_min_op.py / _mod, test_squeeze/unsqueeze/transpose/
reshape2, test_sequence_expand_as, test_fusion_seqconv_eltadd_relu,
test_fused_embedding_fc_lstm, test_fusion_conv_inception)."""

import numpy as np
import pytest

from op_test import check_grad, run_single_op


def _r(*shape, seed=0, lo=-0.9, hi=0.9):
    rng = np.random.RandomState(seed)
    return (rng.rand(*shape) * (hi - lo) + lo).astype(np.float32)


# -- activation grid (reference: test_activation_op.py one class per op) --
@pytest.mark.parametrize("op,attrs,lo,hi", [
    ("hard_sigmoid", {}, -0.8, 0.8),
    ("leaky_relu", {"alpha": 0.1}, -0.9, 0.9),
    ("logsigmoid", {}, -2.0, 2.0),
    ("reciprocal", {}, 0.3, 1.5),        # away from the pole
    ("relu6", {"threshold": 6.0}, -2.0, 5.0),
    ("softsign", {}, -2.0, 2.0),
    ("swish", {"beta": 1.0}, -2.0, 2.0),
    ("tanh_shrink", {}, -2.0, 2.0),
])
def test_activation_grads(op, attrs, lo, hi):
    x = _r(3, 7, lo=lo, hi=hi, seed=sum(map(ord, op)) % 1000)
    # keep clear of the kink points where central differences lie
    if op == "relu6":
        x = x[(np.abs(x) > 1e-2) & (np.abs(x - 6.0) > 1e-2)].reshape(-1, 1)
    if op in ("leaky_relu", "hard_sigmoid"):
        x = np.where(np.abs(x) < 5e-2, 0.2, x)
    check_grad(op, {"X": {"x": x}}, attrs=attrs)


# -- reductions (reference: test_reduce_op.py) ---------------------------
@pytest.mark.parametrize("op", ["reduce_max", "reduce_min", "reduce_prod"])
def test_reduce_grads(op):
    rng = np.random.RandomState(5)
    # distinct magnitudes so max/min choices are stable under the delta
    x = (rng.permutation(24).reshape(4, 6).astype(np.float32) + 1.0) * 0.1
    check_grad(op, {"X": {"x": x}}, attrs={"dim": [1]})


def test_elementwise_min_grad():
    x = _r(4, 5, seed=1)
    y = _r(4, 5, seed=2)
    # separate the operands so min() choices are stable
    y = np.where(np.abs(x - y) < 5e-2, y + 0.2, y)
    check_grad("elementwise_min", {"X": {"x": x}, "Y": {"y": y}})


def test_elementwise_mod_int():
    x = np.array([[7, -7, 5], [9, 4, 11]], np.int64)
    y = np.array([[3, 3, 4], [4, 5, 4]], np.int64)
    out = run_single_op("elementwise_mod", {"X": {"x": x}, "Y": {"y": y}})
    np.testing.assert_array_equal(out["__out_Out_0"], x % y)


# -- shape ops (reference: test_squeeze_op.py etc.; grads are reshapes) --
def test_shape_op_grads():
    x = _r(2, 1, 3, seed=3)
    check_grad("squeeze2", {"X": {"x": x}}, attrs={"axes": [1]})
    check_grad("unsqueeze", {"X": {"x": _r(2, 3, seed=4)}},
               attrs={"axes": [1]})
    check_grad("unsqueeze2", {"X": {"x": _r(2, 3, seed=5)}},
               attrs={"axes": [0]})
    check_grad("transpose2", {"X": {"x": _r(2, 3, 4, seed=6)}},
               attrs={"axis": [2, 0, 1]})
    check_grad("reshape2", {"X": {"x": _r(2, 6, seed=7)}},
               attrs={"shape": [3, 4]})


def test_sequence_expand_as_grad():
    x = _r(3, 4, seed=8)
    y = _r(3, 5, 2, seed=9)              # provides the target time extent
    lens = np.array([2, 5, 1], np.int32)
    check_grad("sequence_expand_as",
               {"X": {"x": x}, "Y": {"y": y}, "SeqLens": {"l": lens}})


# -- fused ops (reference: operators/fused/) -----------------------------
def test_fusion_seqconv_eltadd_relu_grad():
    x = _r(2, 5, 3, seed=9, lo=0.1, hi=0.9)   # positive: relu-smooth
    f = _r(9, 4, seed=10, lo=0.05, hi=0.5)
    b = _r(4, seed=11, lo=0.3, hi=0.8)
    lens = np.array([5, 4], np.int32)
    check_grad("fusion_seqconv_eltadd_relu",
               {"X": {"x": x}, "Filter": {"f": f}, "Bias": {"b": b},
                "SeqLens": {"l": lens}},
               attrs={"contextLength": 3, "contextStart": -1})


def test_conv2d_inception_fusion_forward():
    """Four 1x1 branches vs hand-built conv+relu+concat."""
    x = _r(2, 3, 5, 5, seed=12)
    ws = [_r(2, 3, 1, 1, seed=13 + i, lo=-0.5, hi=0.5) for i in range(4)]
    bs = [_r(2, seed=20 + i, lo=-0.2, hi=0.2) for i in range(4)]
    out = run_single_op(
        "conv2d_inception_fusion",
        {"Input": {"x": x},
         "Filter": {f"w{i}": ws[i] for i in range(4)},
         "Bias": {f"b{i}": bs[i] for i in range(4)}},
        out_slots=("Output",))["__out_Output_0"]
    expect = []
    for w, b in zip(ws, bs):
        o = np.einsum("bchw,oc->bohw", x, w[:, :, 0, 0]) \
            + b.reshape(1, -1, 1, 1)
        expect.append(np.maximum(o, 0.0))
    np.testing.assert_allclose(out, np.concatenate(expect, axis=1),
                               rtol=1e-4, atol=1e-5)


def test_fused_embedding_fc_lstm_matches_compose():
    """fused op == embedding-projected input through dynamic_lstm."""
    V, D, B, T = 11, 4, 2, 3
    table = _r(V, 4 * D, seed=30, lo=-0.3, hi=0.3)
    ids = np.random.RandomState(31).randint(0, V, (B, T, 1)).astype(np.int64)
    wh = _r(D, 4 * D, seed=32, lo=-0.3, hi=0.3)
    out = run_single_op(
        "fused_embedding_fc_lstm",
        {"Embeddings": {"e": table}, "Ids": {"i": ids},
         "WeightH": {"w": wh}},
        out_slots=("Hidden", "Cell"))
    proj = table[ids[..., 0]]
    ref = run_single_op(
        "dynamic_lstm", {"Input": {"x": proj}, "Weight": {"w": wh}},
        out_slots=("Hidden", "Cell"))
    np.testing.assert_allclose(out["__out_Hidden_0"],
                               ref["__out_Hidden_0"], rtol=1e-5)
    np.testing.assert_allclose(out["__out_Cell_0"],
                               ref["__out_Cell_0"], rtol=1e-5)
