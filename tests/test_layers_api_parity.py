"""API-surface parity: every public name the reference exports from
fluid.layers (the union of its submodules' __all__ lists) resolves in
paddle_tpu.fluid.layers — machine-checked the way the op-registry
closure is (tests/test_infra_ops.py). The only exceptions are the
reference's internal codegen/doc decorators, which its __all__ leaks but
which are not user API.

Plus functional smoke tests for the round-3 surface additions (wrappers
execute, not just resolve)."""

import glob
import re

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

REFERENCE_LAYERS_GLOB = "/root/reference/python/paddle/fluid/layers/*.py"

# internal helpers the reference's __all__ exposes but which are codegen
# machinery, not user API (layer_function_generator.py)
NOT_USER_API = {"autodoc", "templatedoc", "deprecated", "generate_layer_fn",
                "generate_layer_fn_noattr", "data_layer_not_check"}


def _reference_names():
    names = set()
    for f in glob.glob(REFERENCE_LAYERS_GLOB):
        src = open(f, encoding="utf-8", errors="ignore").read()
        for m in re.finditer(r"__all__\s*=\s*\[(.*?)\]", src, re.S):
            names.update(re.findall(r"['\"](\w+)['\"]", m.group(1)))
    return names - NOT_USER_API


def test_every_reference_layer_name_resolves():
    ref = _reference_names()
    assert len(ref) > 200, "reference scrape looks broken"
    missing = sorted(n for n in ref if not hasattr(layers, n))
    assert not missing, f"fluid.layers missing {len(missing)}: {missing}"


# -- functional smoke for the new wrappers ----------------------------------

def _run(fetch, feed=None):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe.run(fluid.default_main_program(), feed=feed or {},
                   fetch_list=fetch if isinstance(fetch, list) else [fetch])


def test_conv3d_pool3d_forward():
    x = layers.data("x3d", shape=[2, 4, 6, 6], dtype="float32")
    h = layers.conv3d(x, num_filters=3, filter_size=3, padding=1, act="relu")
    out = layers.pool3d(h, pool_size=2, pool_stride=2)
    (v,) = _run(out, {"x3d": np.random.RandomState(0)
                      .rand(1, 2, 4, 6, 6).astype("float32")})
    assert np.asarray(v).shape == (1, 3, 2, 3, 3)


def test_adaptive_pool2d_values():
    x = layers.data("xa", shape=[1, 6, 6], dtype="float32")
    out = layers.adaptive_pool2d(x, pool_size=[2, 2], pool_type="avg")
    xv = np.arange(36, dtype=np.float32).reshape(1, 1, 6, 6)
    (v,) = _run(out, {"xa": xv})
    # bin (0,0) = mean of xv[..., :3, :3]
    np.testing.assert_allclose(np.asarray(v)[0, 0, 0, 0],
                               xv[0, 0, :3, :3].mean(), rtol=1e-6)


def test_group_norm_normalizes():
    x = layers.data("xg", shape=[4, 4, 4], dtype="float32")
    out = layers.group_norm(x, groups=2)
    (v,) = _run(out, {"xg": np.random.RandomState(1)
                      .rand(2, 4, 4, 4).astype("float32") * 5 + 3})
    v = np.asarray(v)
    # per-(sample, group) standardized
    g = v.reshape(2, 2, 2 * 4 * 4)
    np.testing.assert_allclose(g.mean(-1), 0.0, atol=1e-4)


def test_prelu_channel_mode():
    x = layers.data("xp", shape=[3, 2, 2], dtype="float32")
    out = layers.prelu(x, mode="channel")
    xv = -np.ones((1, 3, 2, 2), np.float32)
    (v,) = _run(out, {"xp": xv})
    np.testing.assert_allclose(np.asarray(v), -0.25, rtol=1e-6)


def test_soft_relu_matches_formula():
    x = layers.data("xsr", shape=[4], dtype="float32")
    out = layers.soft_relu(x, threshold=2.0)
    xv = np.asarray([[-5.0, -1.0, 0.5, 7.0]], np.float32)
    (v,) = _run(out, {"xsr": xv})
    want = np.log1p(np.exp(np.clip(xv, -2.0, 2.0)))
    np.testing.assert_allclose(np.asarray(v), want, rtol=1e-5)


def test_hash_deterministic_and_bounded():
    ids = layers.data("hin", shape=[2], dtype="int64")
    out = layers.hash(ids, hash_size=100, num_hash=3)
    iv = np.asarray([[3, 5], [3, 5], [9, 1]], np.int64)
    (v,) = _run(out, {"hin": iv})
    v = np.asarray(v)
    assert v.shape == (3, 3, 1)
    assert (v >= 0).all() and (v < 100).all()
    np.testing.assert_array_equal(v[0], v[1])     # same row -> same hash
    assert (v[0] != v[2]).any()


def test_smooth_l1_and_dice_loss():
    x = layers.data("sx", shape=[4], dtype="float32")
    y = layers.data("sy", shape=[4], dtype="float32")
    sl = layers.smooth_l1(x, y)
    label = layers.data("dl", shape=[1], dtype="int64")
    probs = layers.softmax(layers.fc(x, 3))
    dice = layers.dice_loss(probs, label)
    rng = np.random.RandomState(2)
    vals = _run([sl, dice], {"sx": rng.rand(2, 4).astype("float32"),
                             "sy": rng.rand(2, 4).astype("float32"),
                             "dl": np.asarray([[0], [2]], np.int64)})
    assert all(np.isfinite(np.asarray(v)).all() for v in vals)


def test_cudnn_lstm_layer_shapes():
    x = layers.data("lx", shape=[4, 8], dtype="float32",
                    append_batch_size=False)   # [T=4, B, D] bound at feed
    init_h = layers.data("lh", shape=[1, 3, 16], dtype="float32",
                         append_batch_size=False)
    init_c = layers.data("lc", shape=[1, 3, 16], dtype="float32",
                         append_batch_size=False)
    out, lh, lc = layers.lstm(x, init_h, init_c, max_len=4, hidden_size=16,
                              num_layers=1)
    rng = np.random.RandomState(3)
    vals = _run([out, lh, lc],
                {"lx": rng.rand(4, 3, 8).astype("float32"),
                 "lh": np.zeros((1, 3, 16), np.float32),
                 "lc": np.zeros((1, 3, 16), np.float32)})
    assert np.asarray(vals[0]).shape == (4, 3, 16)
    assert np.asarray(vals[1]).shape == (1, 3, 16)


def test_logical_and_tensor_utils():
    a = layers.data("ba", shape=[3], dtype="bool")
    b = layers.data("bb", shape=[3], dtype="bool")
    both = layers.logical_and(a, b)
    neither = layers.logical_not(layers.logical_or(a, b))
    av = np.asarray([[True, False, True]])
    bv = np.asarray([[True, True, False]])
    vals = _run([both, neither], {"ba": av, "bb": bv})
    np.testing.assert_array_equal(np.asarray(vals[0]),
                                  [[True, False, False]])
    np.testing.assert_array_equal(np.asarray(vals[1]),
                                  [[False, False, False]])


def test_has_inf_nan_isfinite():
    x = layers.data("ov", shape=[3], dtype="float32")
    flags = [layers.has_inf(x), layers.has_nan(x), layers.isfinite(x)]
    vals = _run(flags, {"ov": np.asarray([[1.0, np.inf, 2.0]], np.float32)})
    assert bool(np.asarray(vals[0])[0]) is True
    assert bool(np.asarray(vals[1])[0]) is False
    assert bool(np.asarray(vals[2])[0]) is False


def test_create_global_var_and_step_counter():
    g = layers.create_global_var(shape=[1], value=7.0, dtype="float32",
                                 persistable=True, name="gvar7")
    ctr = layers.autoincreased_step_counter()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    for want in (1, 2, 3):
        vals = exe.run(fluid.default_main_program(),
                       fetch_list=[g, ctr])
        assert float(np.asarray(vals[0])[0]) == 7.0
        assert int(np.asarray(vals[1])[0]) == want


def test_py_reader_epoch_protocol():
    """The reference's canonical loop: decorate -> start -> run without
    feed -> EOFException at epoch end -> reset -> next epoch."""
    reader = layers.py_reader(capacity=4, shapes=[(-1, 4), (-1, 1)],
                              dtypes=["float32", "int64"])
    img, label = layers.read_file(reader)
    loss = layers.mean(layers.fc(img, 2))

    def batches():
        rng = np.random.RandomState(0)
        for _ in range(3):
            yield (rng.rand(2, 4).astype("float32"),
                   rng.randint(0, 2, (2, 1)).astype("int64"))

    reader.decorate_paddle_reader(batches)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    for epoch in range(2):
        reader.start()
        seen = 0
        while True:
            try:
                exe.run(fluid.default_main_program(), fetch_list=[loss])
                seen += 1
            except fluid.core.EOFException:
                reader.reset()
                break
        assert seen == 3, seen


def test_open_files_roundtrip(tmp_path):
    from paddle_tpu import recordio

    path = str(tmp_path / "data.recordio")

    def rd():
        rng = np.random.RandomState(1)
        for i in range(4):
            yield {"of_x": rng.rand(2, 3).astype("float32"),
                   "of_y": np.full((2, 1), i, np.int64)}

    recordio.convert_reader_to_recordio_file(path, rd)
    reader = layers.open_files([path])
    xs = layers.read_file(reader)
    x = xs[0] if isinstance(xs, list) else xs
    out = layers.mean(x)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    reader.start()
    n = 0
    while True:
        try:
            exe.run(fluid.default_main_program(), fetch_list=[out])
            n += 1
        except fluid.core.EOFException:
            reader.reset()
            break
    assert n == 4


# -- review-fix regressions -------------------------------------------------

def test_append_LARS_scales_the_update():
    """The decayed-lr Variable stored by append_LARS must actually drive
    the sgd op (optimizer._param_lr), not just be computed."""
    x = layers.data("lx2", shape=[4], dtype="float32")
    w_attr = fluid.ParamAttr(name="lars_w")
    out = layers.fc(x, 1, param_attr=w_attr, bias_attr=False)
    loss = layers.mean(out)
    opt = fluid.optimizer.SGDOptimizer(learning_rate=0.5)
    pgs = opt.backward(loss)
    from paddle_tpu.fluid.learning_rate_scheduler import append_LARS
    append_LARS(pgs, layers.fill_constant([1], "float32", 0.5),
                weight_decay=0.1)
    opt.apply_gradients(pgs)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(fluid.default_startup_program(), scope=scope)
    w0 = np.array(scope.find_var("lars_w"), copy=True)
    xv = np.ones((2, 4), np.float32)
    exe.run(fluid.default_main_program(), feed={"lx2": xv}, fetch_list=[],
            scope=scope)
    w1 = np.asarray(scope.find_var("lars_w"))
    # loss = mean over the [2,1] output of x@W with x=ones: dL/dW_j = 1
    g = np.ones_like(w0)
    wn = np.linalg.norm(w0)
    gn = np.linalg.norm(g)
    lars_lr = 0.5 * wn / (gn + 0.1 * wn)
    np.testing.assert_allclose(w1, w0 - lars_lr * g, rtol=1e-5)


def test_py_reader_mid_epoch_reset_is_clean():
    """reset() mid-epoch then start(): the new epoch sees exactly its own
    batches (no stale items or premature sentinel from the old thread)."""
    reader = layers.py_reader(capacity=2, shapes=[(-1, 2)],
                              dtypes=["float32"])
    xv = layers.read_file(reader)
    out = layers.mean(xv)

    def batches():
        for i in range(5):
            yield (np.full((1, 2), float(i), np.float32),)

    reader.decorate_paddle_reader(batches)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    reader.start()
    (v,) = exe.run(fluid.default_main_program(), fetch_list=[out])
    assert float(np.asarray(v).reshape(())) == 0.0
    reader.reset()                        # abandon mid-epoch
    reader.start()                        # fresh epoch
    seen = []
    while True:
        try:
            (v,) = exe.run(fluid.default_main_program(), fetch_list=[out])
            seen.append(float(np.asarray(v).reshape(())))
        except fluid.core.EOFException:
            reader.reset()
            break
    assert seen == [0.0, 1.0, 2.0, 3.0, 4.0], seen


def test_py_reader_multi_step_window():
    """exe.run(iterations=N) with a started reader consumes N DISTINCT
    batches (and the epoch tail shrinks the window)."""
    reader = layers.py_reader(capacity=8, shapes=[(-1, 2)],
                              dtypes=["float32"])
    xv = layers.read_file(reader)
    out = layers.mean(xv)

    def batches():
        for i in range(5):
            yield (np.full((1, 2), float(i), np.float32),)

    reader.decorate_paddle_reader(batches)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    reader.start()
    (v,) = exe.run(fluid.default_main_program(), fetch_list=[out],
                   iterations=3)
    np.testing.assert_allclose(np.asarray(v).reshape(-1), [0.0, 1.0, 2.0])
    (v,) = exe.run(fluid.default_main_program(), fetch_list=[out],
                   iterations=3)          # only 2 left: window shrinks
    np.testing.assert_allclose(np.asarray(v).reshape(-1), [3.0, 4.0])
    with pytest.raises(fluid.core.EOFException):
        exe.run(fluid.default_main_program(), fetch_list=[out],
                iterations=3)
    reader.reset()


def test_shuffle_applies_regardless_of_decorate_spelling():
    """shuffle() before decorate_tensor_provider still shuffles (the
    decorator list applies at start() time, not via monkeypatching)."""
    reader = layers.py_reader(capacity=16, shapes=[(-1, 1)],
                              dtypes=["float32"])
    xv = layers.read_file(reader)
    out = layers.mean(xv)
    layers.shuffle(reader, buffer_size=16)

    def batches():
        for i in range(12):
            yield (np.full((1, 1), float(i), np.float32),)

    reader.decorate_tensor_provider(batches)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    reader.start()
    seen = []
    while True:
        try:
            (v,) = exe.run(fluid.default_main_program(), fetch_list=[out])
            seen.append(float(np.asarray(v).reshape(())))
        except fluid.core.EOFException:
            reader.reset()
            break
    assert sorted(seen) == [float(i) for i in range(12)]
    assert seen != [float(i) for i in range(12)], "not shuffled"


def test_conv_transpose_output_size_derives_filter():
    x = layers.data("ct_x", shape=[2, 4, 4], dtype="float32")
    out = layers.conv2d_transpose(x, num_filters=3, output_size=8,
                                  stride=2, padding=1)
    x3 = layers.data("ct_x3", shape=[2, 4, 4, 4], dtype="float32")
    out3 = layers.conv3d_transpose(x3, num_filters=2, output_size=8,
                                   stride=2)
    rng = np.random.RandomState(0)
    vals = _run([out, out3],
                {"ct_x": rng.rand(1, 2, 4, 4).astype("float32"),
                 "ct_x3": rng.rand(1, 2, 4, 4, 4).astype("float32")})
    assert np.asarray(vals[0]).shape == (1, 3, 8, 8)
    assert np.asarray(vals[1]).shape == (1, 2, 8, 8, 8)


# -- fluid-package-wide closure (beyond layers) -----------------------------

FLUID_MODULE_PAIRS = {
    "initializer": "paddle_tpu.fluid.initializer",
    "optimizer": "paddle_tpu.fluid.optimizer",
    "io": "paddle_tpu.fluid.io",
    "nets": "paddle_tpu.fluid.nets",
    "clip": "paddle_tpu.fluid.clip",
    "metrics": "paddle_tpu.fluid.metrics",
    "regularizer": "paddle_tpu.fluid.regularizer",
    "backward": "paddle_tpu.fluid.backward",
    "profiler": "paddle_tpu.fluid.profiler",
    "data_feeder": "paddle_tpu.fluid.data_feeder",
    "evaluator": "paddle_tpu.fluid.evaluator",
    "param_attr": "paddle_tpu.fluid.param_attr",
    "executor": "paddle_tpu.fluid",
    "framework": "paddle_tpu.fluid.framework",
    "unique_name": "paddle_tpu.fluid.unique_name",
    "lod_tensor": "paddle_tpu.fluid",
    "transpiler/__init__": "paddle_tpu.fluid.transpiler",
}


@pytest.mark.parametrize("ref_mod,our_mod", sorted(FLUID_MODULE_PAIRS.items()))
def test_fluid_module_surface_resolves(ref_mod, our_mod):
    import importlib
    path = f"/root/reference/python/paddle/fluid/{ref_mod}.py"
    src = open(path, encoding="utf-8", errors="ignore").read()
    names = set()
    for m in re.finditer(r"__all__\s*=\s*\[(.*?)\]", src, re.S):
        names.update(re.findall(r"['\"](\w+)['\"]", m.group(1)))
    ours = importlib.import_module(our_mod)
    missing = sorted(n for n in names if not hasattr(ours, n))
    assert not missing, f"{our_mod} missing {missing}"


def test_weight_norm_param_attr():
    """w = g * v/||v|| with norm over non-dim axes; at init g=1 so the
    effective weight's per-column norm is exactly 1."""
    x = layers.data("wn_x", shape=[4], dtype="float32")
    out = layers.fc(x, 8, bias_attr=False,
                    param_attr=fluid.WeightNormParamAttr(dim=1,
                                                         name="wn_v"))
    loss = layers.mean(out)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(fluid.default_startup_program(), scope=scope)
    xv = np.random.RandomState(0).rand(2, 4).astype("float32")
    exe.run(fluid.default_main_program(), feed={"wn_x": xv},
            fetch_list=[loss], scope=scope)
    # v and g are the trainable parameters; both moved or exist
    assert scope.find_var("wn_v") is not None
    assert scope.find_var("wn_v.wn_g") is not None
    # reconstruct: columns of w = g_j * v_j/||v_j|| have norm |g_j|
    v = np.asarray(scope.find_var("wn_v"))
    g = np.asarray(scope.find_var("wn_v.wn_g")).reshape(-1)
    w = g[None, :] * v / np.linalg.norm(v, axis=0, keepdims=True)
    np.testing.assert_allclose(np.linalg.norm(w, axis=0), np.abs(g),
                               rtol=1e-5)


def test_scope_guard_routes_global_scope():
    s = fluid.Scope()
    x = layers.data("sg_x", shape=[2], dtype="float32")
    out = layers.fc(x, 2)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(s):
        exe.run(fluid.default_startup_program())
        exe.run(fluid.default_main_program(),
                feed={"sg_x": np.ones((1, 2), np.float32)},
                fetch_list=[out])
    # params landed in s, not in the default global scope
    pnames = [n for n in
              fluid.default_startup_program().global_block().vars
              if n.endswith(".w_0")]
    assert pnames and all(s.find_var(n) is not None for n in pnames)
    from paddle_tpu.core.scope import global_scope
    assert all(global_scope().find_var(n) is None for n in pnames)


def test_create_lod_tensor_pads():
    t = fluid.create_lod_tensor(np.arange(10, dtype=np.float32)[:, None],
                                [[3, 2, 5]])
    assert t.data.shape == (3, 5, 1)
    assert list(t.seq_lens) == [3, 2, 5]
    np.testing.assert_allclose(t.data[1, :2, 0], [3.0, 4.0])
    assert t.data[1, 2:].sum() == 0
    assert t.recursive_sequence_lengths() == [[3, 2, 5]]


def test_bilinear_initializer_upsamples():
    from paddle_tpu.fluid.initializer import Bilinear
    x = layers.data("bi_x", shape=[1, 4, 4], dtype="float32")
    up = layers.conv2d_transpose(x, num_filters=1, filter_size=4, stride=2,
                                 padding=1, bias_attr=False,
                                 param_attr=fluid.ParamAttr(
                                     initializer=Bilinear()))
    (v,) = _run(up, {"bi_x": np.ones((1, 1, 4, 4), np.float32)})
    v = np.asarray(v)
    assert v.shape == (1, 1, 8, 8)
    # interior of a constant input upsamples to the same constant
    np.testing.assert_allclose(v[0, 0, 2:6, 2:6], 1.0, rtol=1e-5)


def test_save_load_params_excludes_lr_state(tmp_path):
    x = layers.data("sp_x", shape=[2], dtype="float32")
    out = layers.fc(x, 2)
    loss = layers.mean(out)
    opt = fluid.optimizer.Adam(learning_rate=1e-3)
    opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(fluid.default_startup_program(), scope=scope)
    d = str(tmp_path / "params")
    saved = fluid.io.save_params(exe, d, scope=scope)
    assert any("fc" in n for n in saved)
    # Adam moment accumulators are persistable but NOT parameters
    assert not any("moment" in n.lower() or "beta" in n.lower()
                   for n in saved), saved
    loaded = fluid.io.load_params(exe, d, scope=scope)
    assert sorted(loaded) == sorted(saved)


def test_reader_decorator_surface_resolves():
    src = open("/root/reference/python/paddle/reader/decorator.py",
               encoding="utf-8", errors="ignore").read()
    names = set()
    for m in re.finditer(r"__all__\s*=\s*\[(.*?)\]", src, re.S):
        names.update(re.findall(r"['\"](\w+)['\"]", m.group(1)))
    import paddle_tpu.reader.decorator as d
    missing = sorted(n for n in names if not hasattr(d, n))
    assert not missing, missing


def test_dataset_module_files_resolve():
    import os
    ref = {os.path.basename(f)[:-3]
           for f in glob.glob("/root/reference/python/paddle/dataset/*.py")}
    ref -= {"__init__", "tests"}
    ours = {m[:-3] for m in os.listdir("/root/repo/paddle_tpu/dataset")
            if m.endswith(".py")} - {"__init__"}
    missing = sorted(ref - ours)
    assert not missing, f"dataset modules missing: {missing}"


def test_compose_alignment_contract():
    from paddle_tpu.reader.decorator import ComposeNotAligned, compose
    r1 = lambda: iter([(1,), (2,)])
    short = lambda: iter([(9,)])
    assert list(compose(r1, r1)()) == [(1, 1), (2, 2)]
    with pytest.raises(ComposeNotAligned):
        list(compose(r1, short)())
    # unchecked mode truncates silently (reference behavior)
    assert list(compose(r1, short, check_alignment=False)()) == [(1, 9)]


def test_image_simple_transform_contract():
    from paddle_tpu.dataset import image
    im = (np.random.RandomState(0).rand(40, 60, 3) * 255).astype("uint8")
    t = image.simple_transform(im, 32, 24, is_train=False,
                               mean=[1.0, 2.0, 3.0])
    assert t.shape == (3, 24, 24) and t.dtype == np.float32
    t2 = image.simple_transform(im, 32, 24, is_train=True)
    assert t2.shape == (3, 24, 24)
    assert image.resize_short(im, 30).shape[0] == 30


def test_name_scope_keeps_names_unique():
    """Two same-prefix scopes must not collide (counters are shared; a
    scope annotates, it never resets uniqueness)."""
    x = layers.data("ns_x", shape=[2], dtype="float32")
    with fluid.name_scope("block"):
        a = layers.fc(x, 2)
    with fluid.name_scope("block"):
        b = layers.fc(x, 2)
    params = [n for n in
              fluid.default_startup_program().global_block().vars
              if n.endswith(".w_0")]
    assert len(params) == len(set(params)) == 2, params


def test_data_norm_three_distinct_stat_params():
    x = layers.data("dn_x", shape=[4], dtype="float32")
    out = layers.data_norm(x)
    startup = fluid.default_startup_program().global_block().vars
    stats = [n for n in startup if "data_norm" in n]
    assert len(stats) == 3, stats
    (v,) = _run(out, {"dn_x": np.random.RandomState(0)
                      .rand(3, 4).astype("float32")})
    assert np.isfinite(np.asarray(v)).all()


def test_step_counter_reuse_single_increment():
    c1 = layers.autoincreased_step_counter()
    c2 = layers.autoincreased_step_counter()   # reuse, no extra inc op
    assert c1.name == c2.name
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    for want in (1, 2):
        (v,) = exe.run(fluid.default_main_program(), fetch_list=[c1])
        assert int(np.asarray(v)[0]) == want, (want, v)


def test_py_reader_provider_error_propagates():
    reader = layers.py_reader(capacity=2, shapes=[(-1, 2)],
                              dtypes=["float32"])
    xv = layers.read_file(reader)
    out = layers.mean(xv)

    def bad_batches():
        yield (np.ones((1, 2), np.float32),)
        raise ValueError("decode exploded")

    reader.decorate_paddle_reader(bad_batches)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    reader.start()
    exe.run(fluid.default_main_program(), fetch_list=[out])   # batch 1 ok
    with pytest.raises(RuntimeError, match="provider raised"):
        exe.run(fluid.default_main_program(), fetch_list=[out])
    reader.reset()


def test_compat_module_surface_and_behavior():
    src = open("/root/reference/python/paddle/compat.py",
               encoding="utf-8", errors="ignore").read()
    names = set()
    for m in re.finditer(r"__all__\s*=\s*\[(.*?)\]", src, re.S):
        names.update(re.findall(r"['\"](\w+)['\"]", m.group(1)))
    from paddle_tpu import compat
    missing = sorted(n for n in names if not hasattr(compat, n))
    assert not missing, missing
    assert compat.to_text(b"abc") == "abc"
    assert compat.to_bytes("abc") == b"abc"
    assert compat.to_text([b"a", b"b"]) == ["a", "b"]
    assert compat.round(2.5) == 3.0          # py2 half-away-from-zero
    assert compat.round(-2.5) == -3.0
    assert compat.floor_division(7, 2) == 3


def test_dynamic_lstmp_distinct_weights():
    """A shared param_attr must not alias weight and proj_weight."""
    x = layers.data("lp_x", shape=[3, 16], dtype="float32")
    proj, cell = layers.dynamic_lstmp(
        x, size=16, proj_size=2,
        param_attr=fluid.ParamAttr(name="lp_shared"))
    startup = fluid.default_startup_program().global_block().vars
    ws = [n for n in startup if n.startswith("lp_shared")]
    assert len(ws) == 2 and len(set(ws)) == 2, ws
    (v,) = _run(proj, {"lp_x": np.random.RandomState(0)
                       .rand(2, 3, 16).astype("float32")})
    assert np.isfinite(np.asarray(v)).all()


def test_multiprocess_reader_error_propagates():
    from paddle_tpu.reader.decorator import multiprocess_reader

    def good():
        yield (1,)

    def bad():
        yield (2,)
        raise ValueError("decode exploded")

    r = multiprocess_reader([good, bad])
    with pytest.raises(RuntimeError, match="worker raised"):
        list(r())


def test_create_lod_tensor_rejects_wrong_lens():
    with pytest.raises(ValueError, match="disagree"):
        fluid.create_lod_tensor([[1.0, 2.0], [3.0]], [[2, 2]])


def test_multi_reader_eof_pushes_back_pulled_batch():
    """Reader B's epoch ends first: the batch already pulled from A must
    survive to the next run, not vanish."""
    ra = layers.py_reader(capacity=8, shapes=[(-1, 1)], dtypes=["float32"],
                          name="rda")
    rb = layers.py_reader(capacity=8, shapes=[(-1, 1)], dtypes=["float32"],
                          name="rdb")
    a = layers.read_file(ra)
    b = layers.read_file(rb)
    out = layers.mean(layers.elementwise_add(a, b))

    def mk(vals):
        def batches():
            for v in vals:
                yield (np.full((1, 1), float(v), np.float32),)
        return batches

    ra.decorate_paddle_reader(mk([1, 2, 3]))       # long
    rb.decorate_paddle_reader(mk([10, 20]))        # short
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    ra.start()
    rb.start()
    vals = []
    while True:
        try:
            (v,) = exe.run(fluid.default_main_program(), fetch_list=[out])
            vals.append(float(np.asarray(v).reshape(())))
        except fluid.core.EOFException:
            break
    assert vals == [11.0, 22.0]
    # A's batch "3" was pulled during the failed third step — it must
    # come back on the next epoch instead of being dropped
    rb.reset()
    rb.decorate_paddle_reader(mk([30]))
    rb.start()
    (v,) = exe.run(fluid.default_main_program(), fetch_list=[out])
    assert float(np.asarray(v).reshape(())) == 33.0
    ra.reset()
    rb.reset()


def test_cudnn_lstm_bidirec_two_layer_packing():
    """The wrapper's packed-W sizing must match the emitter's per-layer
    per-direction consumption: layer1 in=D, layer2 in=2H (bidirec)."""
    D, H, T, B = 4, 3, 5, 2
    x = layers.data("bl_x", shape=[T, B, D], dtype="float32",
                    append_batch_size=False)
    h0 = layers.data("bl_h", shape=[2 * 2, B, H], dtype="float32",
                     append_batch_size=False)
    c0 = layers.data("bl_c", shape=[2 * 2, B, H], dtype="float32",
                     append_batch_size=False)
    out, lh, lc = layers.lstm(x, h0, c0, max_len=T, hidden_size=H,
                              num_layers=2, is_bidirec=True)
    # expected: L1 2*(D*4H + H*4H + 4H) + L2 2*((2H)*4H + H*4H + 4H)
    want = 2 * (D * 4 * H + H * 4 * H + 4 * H) \
        + 2 * (2 * H * 4 * H + H * 4 * H + 4 * H)
    wvar = [v for n, v in
            fluid.default_startup_program().global_block().vars.items()
            if n.startswith("lstm")][0]
    assert list(wvar.shape) == [want], (wvar.shape, want)
    rng = np.random.RandomState(0)
    vals = _run([out, lh], {
        "bl_x": rng.rand(T, B, D).astype("float32"),
        "bl_h": np.zeros((4, B, H), np.float32),
        "bl_c": np.zeros((4, B, H), np.float32)})
    assert np.asarray(vals[0]).shape == (T, B, 2 * H)
    assert np.asarray(vals[1]).shape == (4, B, H)


def test_multiprocess_reader_ndarray_samples():
    """Normal (features, label) 2-tuples of ndarrays must not trip the
    poison-sentinel check (ndarray == str is elementwise)."""
    from paddle_tpu.reader.decorator import multiprocess_reader

    def r1():
        yield (np.zeros((4,), np.float32), np.zeros((1,), np.int64))

    got = list(multiprocess_reader([r1])())
    assert len(got) == 1 and got[0][0].shape == (4,)


def test_trainer_fetch_metrics_flag():
    from paddle_tpu import contrib
    from paddle_tpu.fluid import layers

    def train_func():
        x = layers.data("fm_x", shape=[4], dtype="float32")
        return layers.mean(layers.fc(x, 1))

    tr = contrib.Trainer(train_func,
                         lambda: fluid.optimizer.SGD(learning_rate=0.1))
    metrics_seen = []

    def handler(ev):
        if isinstance(ev, contrib.high_level.BeginStepEvent):
            ev.fetch_metrics = ev.step % 2 == 0
        if isinstance(ev, contrib.high_level.EndStepEvent):
            metrics_seen.append(len(ev.metrics))

    def reader():
        for _ in range(4):
            yield {"fm_x": np.ones((2, 4), np.float32)}

    tr.train(1, handler, reader=reader)
    assert metrics_seen == [1, 0, 1, 0], metrics_seen
