"""Debug tooling tests: graphviz dump, timeline export, nan/inf checker
(reference: debugger.py draw_block_graphviz, tools/timeline.py,
FLAGS_check_nan_inf operator.cc:978)."""

import json
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import debugger, profiler


def _small_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, 2, act="relu")
        loss = fluid.layers.mean(y)
    return main, startup, loss


def test_draw_block_graphviz(tmp_path):
    main, _, _ = _small_program()
    path = str(tmp_path / "g.dot")
    dot = debugger.draw_program(main, path=path)
    assert dot.startswith("digraph")
    assert "mul" in dot and "reduce" in dot.lower() or "mean" in dot
    assert os.path.exists(path)


def test_profiler_timeline_export(tmp_path):
    main, startup, loss = _small_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    trace = str(tmp_path / "trace.json")
    with profiler.profiler():
        with profiler.record_event("train_step"):
            exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[loss.name])
        profiler.export_chrome_trace(trace)
    data = json.load(open(trace))
    names = [e["name"] for e in data["traceEvents"]]
    assert "train_step" in names


def test_check_nan_inf_flag(monkeypatch):
    monkeypatch.setenv("FLAGS_check_nan_inf", "1")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[2], dtype="float32")
        y = fluid.layers.log(x)        # log of negative → nan
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    with pytest.raises(FloatingPointError, match="check_nan_inf"):
        exe.run(main, feed={"x": np.array([[-1.0, 2.0]], np.float32)},
                fetch_list=[y.name])
    # clean input passes
    out = exe.run(main, feed={"x": np.array([[1.0, 2.0]], np.float32)},
                  fetch_list=[y.name])
    assert np.isfinite(out[0]).all()
