"""Debug tooling tests: graphviz dump, timeline export, nan/inf checker
(reference: debugger.py draw_block_graphviz, tools/timeline.py,
FLAGS_check_nan_inf operator.cc:978)."""

import json
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import debugger, profiler


def _small_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, 2, act="relu")
        loss = fluid.layers.mean(y)
    return main, startup, loss


def test_draw_block_graphviz(tmp_path):
    main, _, _ = _small_program()
    path = str(tmp_path / "g.dot")
    dot = debugger.draw_program(main, path=path)
    assert dot.startswith("digraph")
    assert "mul" in dot and "reduce" in dot.lower() or "mean" in dot
    assert os.path.exists(path)


def test_profiler_timeline_export(tmp_path):
    main, startup, loss = _small_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    trace = str(tmp_path / "trace.json")
    with profiler.profiler():
        with profiler.record_event("train_step"):
            exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[loss.name])
        profiler.export_chrome_trace(trace)
    data = json.load(open(trace))
    names = [e["name"] for e in data["traceEvents"]]
    assert "train_step" in names


def test_device_op_stats(tmp_path):
    """Per-HLO-op device-time attribution from a jax.profiler trace —
    the CUPTI DeviceTracer capability (platform/device_tracer.h:39) that
    host spans can't provide once exe.run(iterations=N) makes the whole
    window one dispatch.

    The trace is captured in a clean subprocess (env-selected cpu
    backend): with the axon TPU plugin registered in-process (the
    conftest uses the config API, which keeps the plugin), the plugin's
    profiler hooks swallow the XLA op planes and hlo_stats comes back
    empty — on a real TPU run the planes are present."""
    import subprocess
    import sys

    d = str(tmp_path / "devtrace")
    # raw jit payload: on the CPU backend, xprof's hlo_stats aggregates
    # the XLA:CPU op events only for directly-jitted computations (the
    # executor's scan-wrapped run shows the ops in trace_viewer but not
    # hlo_stats); on TPU both paths aggregate — the capture side of
    # exe.run(iterations=N) + device_op_stats is exercised on real
    # hardware (STATUS.md transformer/resnet profiles used exactly that)
    script = f"""
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.fluid import profiler
f = jax.jit(lambda a: jnp.tanh(a @ a))
x = jnp.ones((256, 256))
np.asarray(f(x))
profiler.start_profiler(trace_dir={d!r})
for _ in range(4):
    x = f(x)
np.asarray(x)
profiler.stop_profiler(trace_dir={d!r})
"""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("XLA_FLAGS", "JAX_"))}
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=300,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))))
    assert r.returncode == 0, r.stderr[-2000:]
    rows = profiler.device_op_stats(d)
    assert rows and all("self_time_us" in r for r in rows)
    assert rows == sorted(rows, key=lambda r: -r["self_time_us"])
    top = profiler.print_device_op_stats(d, top=3)
    assert len(top) <= 3


def test_check_nan_inf_flag(monkeypatch):
    monkeypatch.setenv("FLAGS_check_nan_inf", "1")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[2], dtype="float32")
        y = fluid.layers.log(x)        # log of negative → nan
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    with pytest.raises(FloatingPointError, match="check_nan_inf"):
        exe.run(main, feed={"x": np.array([[-1.0, 2.0]], np.float32)},
                fetch_list=[y.name])
    # clean input passes
    out = exe.run(main, feed={"x": np.array([[1.0, 2.0]], np.float32)},
                  fetch_list=[y.name])
    assert np.isfinite(out[0]).all()


def test_kube_gen_job_yaml():
    """Cluster fan-out template (round-2 verdict item 10; reference:
    benchmark/fluid/kube_gen_job.py): generated yaml carries an Indexed
    Job + headless Service with the PADDLE_* env convention."""
    import sys
    import os
    import yaml
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import kube_gen_job as kg
    args = kg.parse_args(["--jobname", "tj", "--trainers", "4",
                          "--image", "img:1", "--tpu", "4",
                          "--tpu-topology", "2x2",
                          "--entry", "python t.py",
                          "--env", "FLAGS_check_nan_inf=1"])
    svc, job = kg.gen_all(args)
    # round-trip through yaml like kubectl would consume it
    svc, job = yaml.safe_load(yaml.safe_dump(svc)), \
        yaml.safe_load(yaml.safe_dump(job))
    assert svc["kind"] == "Service" and svc["spec"]["clusterIP"] == "None"
    assert job["spec"]["completionMode"] == "Indexed"
    assert job["spec"]["completions"] == 4
    pod = job["spec"]["template"]["spec"]
    assert pod["subdomain"] == "tj"
    env = {e["name"]: e for e in pod["containers"][0]["env"]}
    assert env["PADDLE_COORDINATOR"]["value"] == "tj-0.tj:9876"
    assert env["PADDLE_TRAINERS_NUM"]["value"] == "4"
    assert "job-completion-index" in str(env["PADDLE_TRAINER_ID"])
    assert env["FLAGS_check_nan_inf"]["value"] == "1"
    res = pod["containers"][0]["resources"]["limits"]
    assert res["google.com/tpu"] == "4"
    assert pod["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "2x2"


def test_api_spec_matches():
    """API-stability gate (reference: paddle/fluid/API.spec +
    tools/diff_api.py in CI): the committed spec matches the live API;
    intentional changes must regenerate it (--update)."""
    import sys
    import os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import diff_api
    assert os.path.exists(diff_api.SPEC_PATH)
    removed, added = diff_api.spec_diff()
    assert not removed and not added, (removed[:10], added[:10])
