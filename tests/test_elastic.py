"""Elastic checkpoint-restart loop test (reference: the EDL capability —
go/master task leasing + snapshot/recover, pserver checkpoints; a worker
crashes mid-training and a fresh worker resumes without re-training
finished chunks or losing model state)."""

import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.data.elastic import ElasticTrainer
from paddle_tpu.core.scope import global_scope


def _build():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        loss = fluid.layers.mean(fluid.layers.square_error_cost(
            fluid.layers.fc(x, 1), y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def test_elastic_crash_and_resume(tmp_path):
    work = str(tmp_path / "elastic")
    paths = [f"shard_{i}" for i in range(6)]
    rng = np.random.RandomState(0)
    batches = {p: (rng.rand(8, 4).astype(np.float32),) for p in paths}
    for p in paths:
        x = batches[p][0]
        batches[p] = (x, x.sum(1, keepdims=True).astype(np.float32) * 0.3)

    trained_first = []

    def make_runner(exe, main, loss, log, crash_after=None):
        def train_chunk(task):
            if crash_after is not None and len(log) >= crash_after:
                raise RuntimeError("simulated worker crash")
            x, y = batches[task.path]
            exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss.name])
            log.append(task.path)
        return train_chunk

    # ---- first worker: trains 3 chunks then crashes
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    # lease generous vs chunk time: the master now expires leases with
    # timer semantics (a finish after the deadline is stale), so a lease
    # shorter than one chunk's compile+train would legitimately re-issue
    t1 = ElasticTrainer(work, paths, lease_timeout_s=60.0,
                        checkpoint_every=1)
    with pytest.raises(RuntimeError, match="simulated"):
        t1.run(make_runner(exe, main, loss, trained_first, crash_after=3),
               main_program=main)
    t1.ckpt.wait()
    assert len(trained_first) == 3
    w_name = [n for n, v in main.desc.global_block.vars.items()
              if v.persistable and "w" in n][0]
    w_after_crash = np.asarray(global_scope().find_var(w_name)).copy()

    # ---- fresh worker (new scope/params as if a new process): resumes
    from paddle_tpu.core import scope as scope_mod
    scope_mod._reset_global_scope_for_tests()
    main2, startup2, loss2 = _build()
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(startup2)
    # no expiry wait needed: recover() resets the crashed worker's
    # pending leases straight back to todo (service.go:166 semantics)
    t2 = ElasticTrainer(work, paths, lease_timeout_s=60.0,
                        checkpoint_every=1)
    restored = t2.restore_model(exe2, main_program=main2)
    assert restored is not None
    np.testing.assert_allclose(
        np.asarray(global_scope().find_var(w_name)), w_after_crash)

    trained_second = []
    t2.run(make_runner(exe2, main2, loss2, trained_second),
           main_program=main2)
    assert t2.master.done
    # no finished chunk re-trained; every chunk trained exactly once
    all_trained = trained_first + trained_second
    assert sorted(all_trained) == sorted(paths), all_trained


def test_elastic_trainer_multi_worker_shared_master(tmp_path):
    """ElasticTrainer in MULTI-WORKER mode: two trainers (threads here;
    OS processes in tests/test_edl_integration.py) drain ONE served
    master via MasterClient, each writing its own model checkpoints;
    every chunk trains exactly once across the pair (reference: EDL
    trainers share the go/master service)."""
    import threading
    from paddle_tpu.data.master import Master
    from paddle_tpu.data.master_service import MasterClient, MasterServer

    master = Master(timeout_s=30.0)
    for i in range(8):
        master.add_task(f"shard_{i}", 0, 1)
    srv = MasterServer(master)

    trained = {0: [], 1: []}
    errors = []

    def worker(rank):
        try:
            t = ElasticTrainer(str(tmp_path / f"w{rank}"),
                               master=MasterClient(srv.endpoint),
                               checkpoint_every=2)

            def train_chunk(task):
                import time as _t
                _t.sleep(0.03)           # let both workers participate
                trained[rank].append(task.path)

            t.run(train_chunk)
            t.ckpt.wait()
        except Exception as e:           # surfaced by the main thread
            errors.append((rank, e))

    threads = [threading.Thread(target=worker, args=(r,)) for r in (0, 1)]
    try:
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=120)
    finally:
        srv.stop()
    assert not errors, errors
    all_trained = trained[0] + trained[1]
    assert sorted(all_trained) == sorted(f"shard_{i}" for i in range(8))
    s = master.stats()
    assert s["done"] == 8 and s["dropped"] == 0
    # external-master mode never writes queue snapshots (queue durability
    # belongs to the master host) — but model checkpoints WERE written
    # (union over workers: chunk distribution is nondeterministic)
    total_serials = 0
    for rank in (0, 1):
        assert not os.path.exists(
            str(tmp_path / f"w{rank}" / "master_snapshot.json"))
        from paddle_tpu.fluid.io import AsyncCheckpointer
        total_serials += len(
            AsyncCheckpointer(str(tmp_path / f"w{rank}" / "ckpt")).serials())
    assert total_serials >= 1, "no model checkpoint written by any worker"
