"""PP/EP from the fluid Program API (round-2 verdict item 5): a
pipelined + mixture-of-experts model builds with fluid.layers, trains
through CompiledProgram.with_sharding over a pp x ep mesh, and matches
the sequential lowering of the SAME program (reference bar: every
parallelism mode reachable from the user program,
distribute_transpiler.py:276 — PP/EP are TPU-first extensions)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.parallel import DistributeConfig, make_mesh

D = 16


def _build(capacity_factor=8.0, seed=5):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[D], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        pipe = layers.Pipeline(n_stages=2, n_microbatches=4)
        with pipe.stage(x) as h:
            h1 = layers.fc(h, D, bias_attr=False, act="tanh")
            pipe.set_output(h1)
        moe_out, aux = layers.switch_moe(
            pipe.output, n_experts=4, d_ff=32,
            capacity_factor=capacity_factor)
        pred = layers.fc(moe_out, 1, bias_attr=False)
        mse = layers.mean(layers.square(pred - y))
        loss = mse + layers.mean(aux) * 0.01
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, mse, loss


def _feeds(n):
    rng = np.random.RandomState(0)
    w = np.random.RandomState(1).rand(D, 1)
    out = []
    for _ in range(n):
        x = rng.rand(8, D).astype(np.float32)
        out.append({"x": x, "y": (x @ w).astype(np.float32)})
    return out


# ops exercised end-to-end here (smoke-sweep CONTEXT_OPS contract):
# `pipeline` and `moe_ffn`


def test_pipeline_param_gets_stage_dim():
    main, startup, _, _ = _build()
    blk = main.desc.global_block
    pipe_op = next(op for op in blk.ops if op.type == "pipeline")
    for n in pipe_op.inputs["Params"]:
        assert blk.var(n).shape[0] == 2          # leading [n_stages]
        sblk = startup.desc.global_block
        init_op = next(o for o in sblk.ops if n in o.output_names())
        assert init_op.attrs["shape"][0] == 2


def test_pipeline_moe_sequential_vs_mesh_parity():
    """The SAME program lowered sequentially (no mesh) and over a
    pp x ep mesh computes the same losses step by step. The tiny
    tolerance absorbs the aux-loss estimator difference (per-shard
    fraction products pmean'd vs one global product) and collective
    reassociation."""
    feeds = _feeds(3)
    exe = fluid.Executor(fluid.CPUPlace())

    main, startup, mse, loss = _build()
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    base = [float(exe.run(main, feed=f, fetch_list=[mse], scope=scope)[0])
            for f in feeds]

    main2, startup2, mse2, loss2 = _build()
    mesh = make_mesh({"pp": 2, "ep": 2, "dp": 2})
    dist = DistributeConfig(mesh=mesh, data_axis=None, model_axis=None,
                            sp_axis=None, pp_axis="pp", ep_axis="ep")
    cp = fluid.CompiledProgram(main2).with_sharding(dist)
    scope2 = fluid.Scope()
    exe.run(startup2, scope=scope2)
    dist_losses = [float(exe.run(cp, feed=f, fetch_list=[mse2],
                                 scope=scope2)[0]) for f in feeds]
    np.testing.assert_allclose(base, dist_losses, rtol=5e-3, atol=1e-4)


def test_pipelined_moe_model_trains_on_mesh():
    """Verdict item 5 'done' condition: a 2-stage pipelined model TRAINS
    via the Program API over the mesh — loss decreases."""
    feeds = _feeds(25)
    main, startup, mse, loss = _build()
    mesh = make_mesh({"pp": 2, "ep": 2, "dp": 2})
    dist = DistributeConfig(mesh=mesh, data_axis=None, model_axis=None,
                            sp_axis=None, pp_axis="pp", ep_axis="ep")
    cp = fluid.CompiledProgram(main).with_sharding(dist)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    losses = [float(exe.run(cp, feed=f, fetch_list=[mse], scope=scope)[0])
              for f in feeds]
    assert np.mean(losses[-5:]) < 0.5 * np.mean(losses[:5]), losses


def test_pipeline_body_validation():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[D], dtype="float32")
        pipe = layers.Pipeline(n_stages=2, n_microbatches=2)
        with pytest.raises(ValueError, match="set_output"):
            with pipe.stage(x) as h:
                layers.relu(h)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[D], dtype="float32")
        other = layers.fc(x, D, bias_attr=False)   # non-param ancestor
        pipe = layers.Pipeline(n_stages=2, n_microbatches=2)
        with pytest.raises(ValueError, match="only close over parameters"):
            with pipe.stage(x) as h:
                pipe.set_output(layers.elementwise_add(h, other))


def test_switch_moe_dense_routing_grads():
    """Off-mesh dense fallback: trains and the aux loss pushes routing
    toward balance (finite grads through the dispatch/combine)."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[D], dtype="float32")
        y_out, aux = layers.switch_moe(x, n_experts=4, d_ff=8,
                                       capacity_factor=2.0)
        loss = layers.mean(layers.square(y_out)) + layers.mean(aux)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(16, D).astype(np.float32)}
    l0 = float(exe.run(main, feed=feed, fetch_list=[loss], scope=scope)[0])
    for _ in range(10):
        (lv,) = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
    assert np.isfinite(l0) and float(lv) < l0
