"""Multi-slice (ICI + DCN) hybrid meshes — `make_hybrid_mesh` lays DCN
axes outermost so per-layer tp/sp collectives stay inside one slice's
ICI torus and only the once-per-step dp gradient reduction crosses the
data-center network. The reference's analogue is the two-tier NCCL
topology (intra-node NVLink rings per trainer, nccl_helper.h:86, plus
the cross-host nccl2 tier stitched by gen_nccl_id,
distribute_transpiler.py:222); here the tiers are declared in the mesh
and XLA picks the collective per axis. Runs on the 8-device virtual CPU
mesh (conftest)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.parallel import DistributeConfig, make_hybrid_mesh
from paddle_tpu.parallel.mesh import _order_devices_by_slice


class _FakeDev:
    def __init__(self, i, slice_index=None, process_index=0):
        self.id = i
        if slice_index is not None:
            self.slice_index = slice_index
        self.process_index = process_index

    def __repr__(self):
        return f"dev{self.id}"


def test_layout_dcn_outermost():
    """8 devices, ici tp=4 x dcn dp=2: axis order (dp, tp), each dp row
    one contiguous emulated slice."""
    import jax
    devs = jax.devices()
    mesh = make_hybrid_mesh({"tp": 4}, {"dp": 2})
    assert mesh.axis_names == ("dp", "tp")
    assert dict(mesh.shape) == {"dp": 2, "tp": 4}
    arr = np.asarray(mesh.devices)
    assert list(arr[0]) == devs[:4] and list(arr[1]) == devs[4:]


def test_slice_index_grouping_wins_over_listing_order():
    """Devices arriving interleaved across slices are regrouped so each
    slice is contiguous (slice_index attribute, multi-slice TPU)."""
    devs = [_FakeDev(i, slice_index=i % 2) for i in range(8)]
    ordered = _order_devices_by_slice(devs, per_slice=4)
    assert [d.slice_index for d in ordered] == [0] * 4 + [1] * 4


def test_process_index_fallback_groups_hosts():
    """Without slice_index, one host = one slice (the multi-host DCN
    case, jax.distributed)."""
    devs = [_FakeDev(i, process_index=i // 2) for i in range(8)]
    ordered = _order_devices_by_slice(devs, per_slice=2)
    assert [d.process_index for d in ordered] == [0, 0, 1, 1, 2, 2, 3, 3]


def test_ici_straddling_slices_rejected():
    """An ICI extent larger than one physical slice must raise, not
    silently route per-layer collectives over DCN."""
    devs = [_FakeDev(i, slice_index=i // 4) for i in range(8)]
    with pytest.raises(ValueError, match="straddle"):
        _order_devices_by_slice(devs, per_slice=8)


def test_slice_may_hold_several_dcn_blocks():
    """One physical slice splitting into two DCN blocks is fine — ICI
    blocks stay within the slice."""
    devs = [_FakeDev(i, slice_index=i // 4) for i in range(8)]
    ordered = _order_devices_by_slice(devs, per_slice=2)
    assert [d.slice_index for d in ordered] == [0] * 4 + [1] * 4


def test_uneven_slices_rejected():
    devs = [_FakeDev(i, slice_index=0 if i < 3 else 1) for i in range(8)]
    with pytest.raises(ValueError, match="uneven"):
        _order_devices_by_slice(devs, per_slice=4)


def test_device_count_mismatch_rejected():
    with pytest.raises(ValueError, match="needs"):
        make_hybrid_mesh({"tp": 4}, {"dp": 4})


def test_training_on_hybrid_mesh_matches_single_device():
    """dp-over-DCN x tp-over-ICI training step: loss curve matches the
    unsharded single-device run (the ParallelExecutor convergence-
    equivalence pattern, unittests/parallel_executor_test_base.py)."""
    def build(seed=5):
        from paddle_tpu.fluid import unique_name
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = seed
        startup.random_seed = seed
        with unique_name.guard():
            with fluid.program_guard(main, startup):
                x = layers.data(name="x", shape=[16], dtype="float32")
                y = layers.data(name="y", shape=[1], dtype="float32")
                h = layers.fc(x, 32, act="relu",
                              param_attr=fluid.ParamAttr(name="hyb_w"))
                pred = layers.fc(h, 1)
                loss = layers.mean(layers.square(pred - y))
                fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(0)
    feeds = [{"x": rng.rand(8, 16).astype(np.float32),
              "y": rng.rand(8, 1).astype(np.float32)} for _ in range(5)]

    # single-device baseline
    main, startup, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    base_scope = fluid.Scope()
    exe.run(startup, scope=base_scope)
    base = [float(np.asarray(exe.run(main, feed=f, fetch_list=[loss],
                                     scope=base_scope)[0]).reshape(()))
            for f in feeds]

    # hybrid mesh: dp=2 over DCN, tp=4 over ICI, weight column-parallel
    main2, startup2, loss2 = build()
    mesh = make_hybrid_mesh({"tp": 4}, {"dp": 2})
    dist = DistributeConfig(mesh=mesh, data_axis="dp", model_axis="tp",
                            param_axes={"hyb_w": (None, "tp")})
    compiled = fluid.CompiledProgram(main2).with_sharding(dist)
    sh_scope = fluid.Scope()
    exe.run(startup2, scope=sh_scope)
    got = [float(np.asarray(exe.run(compiled, feed=f, fetch_list=[loss2],
                                    scope=sh_scope)[0]).reshape(()))
           for f in feeds]
    np.testing.assert_allclose(got, base, rtol=2e-5, atol=1e-6)
    assert got[-1] < got[0]
