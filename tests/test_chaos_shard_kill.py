"""Chaos: SIGKILL one embedding-table shard mid-training (ISSUE 14
satellite). The trainer's ShardedTableClient rides through via the
existing RetryPolicy/CircuitBreaker transport — and the at-most-once
contract is witnessed by the shard's fsync'd applied log: after the
kill + restart, every derived push id appears in the fleet's logs
EXACTLY once (nothing lost, nothing double-applied), a full replay of a
completed push is refused by every shard, and the surviving rows carry
exactly the last pushed values.

Failure-matrix row: docs/robustness.md."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.distributed import sharded_table as st
from paddle_tpu.distributed.resilience import RetryPolicy
from _dist_utils import bound_listener

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)

pytestmark = pytest.mark.chaos


def _env():
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PADDLE_", "FLAGS_"))}
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _spawn_shard(shard_id, port, log_path):
    p = subprocess.Popen(
        [sys.executable, os.path.join(TESTS_DIR, "table_shard_worker.py"),
         str(shard_id), str(port), log_path],
        cwd=REPO_ROOT, env=_env(), stdout=subprocess.PIPE, text=True)
    assert p.stdout.readline().strip() == "READY"
    return p


def _free_port():
    lis, port = bound_listener()
    lis.close()
    return port


def _log_lines(path):
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [ln.strip() for ln in f if ln.strip()]


def test_shard_sigkill_midtrain_at_most_once(tmp_path):
    height, width = 8, 3
    spec = st.ShardSpec(height, 2)
    ports = [_free_port(), _free_port()]
    logs = [str(tmp_path / f"applied{i}.log") for i in (0, 1)]
    procs = [_spawn_shard(i, ports[i], logs[i]) for i in (0, 1)]
    client = None
    try:
        client = st.ShardedTableClient(
            [("127.0.0.1", p) for p in ports], spec, codec="none",
            retry_policy=RetryPolicy(
                max_attempts=4, base_delay_s=0.01, max_delay_s=0.05,
                deadline_s=10.0,
                retryable=(ConnectionError, OSError, EOFError)))
        client.seed_from_value("emb", np.zeros((height, width),
                                               np.float32))
        rows = np.arange(height)          # every push spans both shards

        def vals(step):
            return {"param": np.full((height, width), float(step),
                                     np.float32)}

        applied_total = 0
        for step in range(3):             # healthy steady state
            applied_total += client.push_rows("emb", rows, vals(step),
                                              push_id=f"step{step}")
        assert applied_total == 6

        # SIGKILL shard 1 between steps — mid-training crash
        procs[1].kill()
        assert procs[1].wait(timeout=30) == -signal.SIGKILL

        # the in-flight push fails on the dead shard (shard 0's half may
        # already be applied — exactly the ambiguous state the applied
        # log disambiguates); the client surfaces instead of resending
        with pytest.raises(Exception):
            client.push_rows("emb", rows, vals(3), push_id="step3")

        # restart the shard from the SAME applied log and RETRY the SAME
        # push_id: the surviving half dedups, the restarted half applies
        procs[1] = _spawn_shard(1, ports[1], logs[1])
        applied_retry = client.push_rows("emb", rows, vals(3),
                                         push_id="step3")
        assert 1 <= applied_retry <= 2

        for step in range(4, 6):          # training continues
            assert client.push_rows("emb", rows, vals(step),
                                    push_id=f"step{step}") == 2

        # full replay of a completed push: refused by EVERY shard
        assert client.push_rows("emb", rows, vals(99),
                                push_id="step2") == 0

        # ---- the at-most-once witness -----------------------------------
        expect = {f"step{s}/s{sh}" for s in range(6) for sh in (0, 1)}
        expect.add("seed-emb/s0")
        expect.add("seed-emb/s1")
        lines0, lines1 = _log_lines(logs[0]), _log_lines(logs[1])
        # nothing double-applied: each log has no duplicate ids
        assert len(lines0) == len(set(lines0))
        assert len(lines1) == len(set(lines1))
        # nothing lost: every push the training loop issued is in the
        # fleet's logs exactly once, on its owning shard
        assert set(lines0) | set(lines1) == expect
        assert all(l.endswith("/s0") for l in lines0)
        assert all(l.endswith("/s1") for l in lines1)
        # and the rows carry the LAST pushed value — the replayed
        # step2 overwrite (value 99) never landed
        got = client.pull_rows("emb", rows, families=[("param", width)])
        np.testing.assert_array_equal(got["param"], 5.0)
        # client-side half of the accounting matches the fleet's logs
        assert client.pushes_acked == len(lines0) + len(lines1)
    finally:
        if client is not None:
            try:
                client.stop_servers()
            except Exception:
                pass
            client.close()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
