"""contrib coverage: BF16 inference transpiler + mixed-precision decorate
(reference: contrib/float16/float16_transpiler.py and the later
fluid.contrib.mixed_precision.decorate capability)."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


def test_bf16_transpiler_fetch_consumed_downstream():
    """The fetched var is ALSO consumed by a later op — the rewrite must
    keep that consumer reading the produced value."""
    from paddle_tpu.contrib.float16 import BF16Transpiler
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[8], dtype="float32")
        hidden = layers.fc(x, size=8, act="relu")
        out = layers.fc(hidden, size=4, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.random.RandomState(0).rand(3, 8).astype(np.float32)
    ref_h, ref_o = exe.run(main, feed={"x": xv},
                           fetch_list=[hidden, out])

    BF16Transpiler().transpile(main, scope=fluid.global_scope(),
                               feed_names=["x"],
                               fetch_names=[hidden.name, out.name])
    h2, o2 = exe.run(main, feed={"x": xv}, fetch_list=[hidden, out])
    assert np.asarray(h2).dtype == np.float32
    assert np.asarray(o2).dtype == np.float32
    np.testing.assert_allclose(np.asarray(o2), np.asarray(ref_o),
                               rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(np.asarray(h2), np.asarray(ref_h),
                               rtol=5e-2, atol=5e-2)


def test_amp_decorate_trains():
    from paddle_tpu.contrib import mixed_precision
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 9
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[10], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = mixed_precision.decorate(
            fluid.optimizer.SGD(learning_rate=0.05),
            init_loss_scaling=2.0 ** 8, use_dynamic_loss_scaling=True,
            incr_every_n_steps=5, decr_every_n_nan_or_inf=2)
        opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    w = rng.rand(10, 1).astype(np.float32)
    losses = []
    for _ in range(30):
        xv = rng.rand(16, 10).astype(np.float32)
        yv = xv @ w
        (l,) = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.5, losses
    scale = np.asarray(fluid.global_scope().find_var("loss_scaling@AMP"))
    assert float(scale.reshape(())) >= 2.0 ** 8  # grew or held, never shrank


def test_amp_decr_every_n_nan_or_inf():
    """A single overflow step must NOT shrink the scale when
    decr_every_n_nan_or_inf=2; two consecutive overflows must."""
    from paddle_tpu.contrib import mixed_precision
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = mixed_precision.decorate(
            fluid.optimizer.SGD(learning_rate=0.0),
            init_loss_scaling=1024.0, use_dynamic_loss_scaling=True,
            incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
            decr_ratio=0.5)
        opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    def run(xv):
        exe.run(main, feed={"x": xv, "y": np.zeros((2, 1), np.float32)},
                fetch_list=[loss])
        return float(np.asarray(
            fluid.global_scope().find_var("loss_scaling@AMP")).reshape(()))

    finite = np.ones((2, 4), np.float32)
    overflow = np.full((2, 4), np.inf, np.float32)
    assert run(finite) == 1024.0
    assert run(overflow) == 1024.0        # first bad step: hold
    assert run(overflow) == 512.0         # second consecutive: shrink
    assert run(overflow) == 512.0         # counter reset after shrink
    assert run(overflow) == 256.0


def test_quantize_transpiler_qat():
    """QAT transpile inserts fake quant/dequant pairs and the program still
    trains (reference: contrib/quantize/quantize_transpiler.py:81,
    tests in contrib/tests/test_quantize_transpiler.py)."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.contrib import QuantizeTranspiler

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 2
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, 16, act="relu")
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        QuantizeTranspiler().training_transpile(main)
        fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)

    types = [op.type for op in main.desc.global_block.ops]
    assert "fake_quantize_abs_max" in types
    assert "fake_dequantize_max_abs" in types

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    xs = rng.rand(32, 8).astype(np.float32)
    ys = (xs.sum(axis=1, keepdims=True) * 0.3).astype(np.float32)
    losses = []
    for _ in range(40):
        (lv,) = exe.run(main, feed={"x": xs, "y": ys},
                        fetch_list=[loss.name])
        losses.append(float(np.asarray(lv).reshape(())))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_fake_quantize_abs_max_grid():
    import numpy as np
    import sys
    sys.path.insert(0, "tests")
    from op_test import run_single_op
    x = np.array([[-1.0, 0.5, 0.25, 1.0]], np.float32)
    out = run_single_op("fake_quantize_abs_max", {"X": {"x": x}},
                        attrs={"bit_length": 8},
                        out_slots=("Out", "OutScale"))
    q = out["__out_Out_0"]
    assert float(out["__out_OutScale_0"]) == 1.0
    np.testing.assert_allclose(q, np.round(x * 127.0), atol=0.5)


def test_quantize_transpiler_range_abs_max():
    """range_abs_max activations keep a persistable scale window updated
    across steps (reference: fake_quantize_range_abs_max window buffers)."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.contrib import QuantizeTranspiler

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(fluid.layers.fc(x, 8, act="relu"), 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        QuantizeTranspiler(activation_quantize_type="range_abs_max",
                           window_size=16).training_transpile(main)
        fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)

    types = [op.type for op in main.desc.global_block.ops]
    assert "fake_quantize_range_abs_max" in types
    assert "fake_quantize_abs_max" in types     # weights still abs_max

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    xs = rng.rand(16, 8).astype(np.float32)
    ys = xs.sum(axis=1, keepdims=True).astype(np.float32) * 0.2
    for _ in range(10):
        (lv,) = exe.run(main, feed={"x": xs, "y": ys},
                        fetch_list=[loss.name])
    assert np.isfinite(float(np.asarray(lv).reshape(())))


def test_amp_bf16_rewrite_trains():
    """Pure-bf16 MXU compute mode (rewrite_program_amp): tagged ops cast to
    bf16, training still converges and matches fp32 within bf16 tolerance."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.contrib.mixed_precision import rewrite_program_amp

    def build(amp):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 12
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            h = fluid.layers.fc(x, 16, act="relu")
            pred = fluid.layers.fc(h, 1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            if amp:
                n = rewrite_program_amp(main)
                assert n >= 2        # both fc muls tagged
            fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(0)
    xs = rng.rand(32, 8).astype(np.float32)
    ys = xs.sum(axis=1, keepdims=True).astype(np.float32) * 0.3

    results = {}
    for amp in (False, True):
        main, startup, loss = build(amp)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        losses = []
        for _ in range(25):
            (lv,) = exe.run(main, feed={"x": xs, "y": ys},
                            fetch_list=[loss.name], scope=scope)
            losses.append(float(np.asarray(lv).reshape(())))
        results[amp] = losses
    assert results[True][-1] < results[True][0] * 0.5
    # same trajectory within bf16 noise
    np.testing.assert_allclose(results[True][0], results[False][0],
                               rtol=0.05)


def test_amp_rewrite_after_minimize_tags_backward():
    """rewrite_program_amp after minimize() must reach the __vjp__ ops'
    forward snapshots (review repro: bench --amp tags post-minimize)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.contrib.mixed_precision import rewrite_program_amp

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        loss = fluid.layers.mean(fluid.layers.square_error_cost(
            fluid.layers.fc(x, 1), y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        n = rewrite_program_amp(main)
    tagged_vjp = [op for op in main.desc.global_block.ops
                  if op.type == "__vjp__"
                  and op.attrs.get("fwd_op", {}).get("attrs", {})
                  .get("__amp_bf16__")]
    assert tagged_vjp, "backward mul snapshot not tagged"
    assert n >= 2      # fwd mul + its vjp snapshot

    # and the program still trains
    import numpy as np
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = np.random.RandomState(0).rand(16, 4).astype(np.float32)
    ys = xs.sum(1, keepdims=True).astype(np.float32)
    losses = [float(np.asarray(exe.run(main, feed={"x": xs, "y": ys},
                                       fetch_list=[loss.name])[0]))
              for _ in range(20)]
    assert losses[-1] < losses[0]


def test_bf16_transpiled_interior_stays_bf16():
    """Non-AMP mul/conv outputs follow input dtype (review finding: fp32
    forcing defeated the BF16Transpiler's bf16 interior)."""
    import jax.numpy as jnp
    import jax
    from paddle_tpu.core.registry import get_op, EmitContext
    ctx = EmitContext(base_key=jax.random.PRNGKey(0))
    x = jnp.ones((2, 3), jnp.bfloat16)
    w = jnp.ones((3, 4), jnp.bfloat16)
    out = get_op("mul").emit(ctx, {"X": [x], "Y": [w]}, {})["Out"][0]
    assert out.dtype == jnp.bfloat16


def test_nhwc_layout_rewrite_exact_parity():
    """contrib.layout NHWC rewrite: one full train step (fwd + backward +
    momentum update) is bit-identical to the NCHW program in fp32 — the
    rewrite is attr-only, transposes live inside the tagged emitters and
    gradients mirror the forward layout via the __vjp__ re-trace."""
    import numpy as np
    from paddle_tpu.contrib.layout import rewrite_program_nhwc

    def run_once(rewrite):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 7
        startup.random_seed = 7
        scope = fluid.Scope()
        with fluid.program_guard(main, startup):
            img = layers.data(name="img", shape=[3, 16, 16],
                              dtype="float32")
            lbl = layers.data(name="lbl", shape=[1], dtype="int64")
            c = layers.conv2d(img, num_filters=8, filter_size=3, padding=1)
            b = layers.batch_norm(c, act="relu")
            c2 = layers.conv2d(b, num_filters=8, filter_size=3, padding=1)
            res = layers.elementwise_add(c2, c)          # residual
            p = layers.pool2d(res, pool_type="avg", global_pooling=True)
            logits = layers.fc(p, size=4)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, lbl))
            fluid.optimizer.Momentum(learning_rate=0.01,
                                     momentum=0.9).minimize(loss)
            if rewrite:
                n = rewrite_program_nhwc(main)
                assert n >= 4, n   # conv x2 + bn + pool tagged
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup, scope=scope)
            rng = np.random.RandomState(3)
            feeds = {"img": rng.rand(4, 3, 16, 16).astype(np.float32),
                     "lbl": rng.randint(0, 4, (4, 1)).astype(np.int64)}
            lv, = exe.run(main, feed=feeds, fetch_list=[loss], scope=scope)
            wname = next(op.inputs["Filter"][0]
                         for op in main.desc.global_block.ops
                         if op.type == "conv2d")
            w = np.asarray(scope.find_var(wname))
        return float(np.asarray(lv).reshape(())), w

    l_nchw, w_nchw = run_once(False)
    l_nhwc, w_nhwc = run_once(True)
    assert l_nchw == l_nhwc
    np.testing.assert_array_equal(w_nchw, w_nhwc)


def test_nhwc_layout_squeeze_excitation_parity():
    """The SE gate multiply — elementwise_mul(x [B,C,H,W], gates [B,C],
    axis=0) — stays inside the NHWC region (the emitter re-aims the gate
    to [B,1,1,C]); the rewrite remains bit-exact AND the SE op no longer
    falsifies residency (one full train step, fp32)."""
    import numpy as np
    from paddle_tpu.contrib.layout import rewrite_program_nhwc

    def run_once(rewrite):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 9
        startup.random_seed = 9
        scope = fluid.Scope()
        with fluid.program_guard(main, startup):
            img = layers.data(name="img", shape=[8, 8, 8],
                              dtype="float32")
            lbl = layers.data(name="lbl", shape=[1], dtype="int64")
            c = layers.conv2d(img, num_filters=8, filter_size=3, padding=1)
            b = layers.batch_norm(c, act="relu")
            pool = layers.pool2d(b, pool_type="avg", global_pooling=True)
            sq = layers.fc(pool, size=4, act="relu")
            gates = layers.fc(sq, size=8, act="sigmoid")
            se = layers.elementwise_mul(b, gates, axis=0)
            c2 = layers.conv2d(se, num_filters=8, filter_size=3, padding=1)
            p2 = layers.pool2d(c2, pool_type="avg", global_pooling=True)
            logits = layers.fc(p2, size=4)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, lbl))
            fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
            if rewrite:
                rewrite_program_nhwc(main)
                # the SE multiply got the re-aim tag (its X stayed NHWC)
                assert any(op.attrs.get("__nhwc_bcast_bc__")
                           for op in main.desc.global_block.ops
                           if op.type == "elementwise_mul")
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup, scope=scope)
            rng = np.random.RandomState(5)
            feeds = {"img": rng.rand(2, 8, 8, 8).astype(np.float32),
                     "lbl": rng.randint(0, 4, (2, 1)).astype(np.int64)}
            lv, = exe.run(main, feed=feeds, fetch_list=[loss], scope=scope)
        return float(np.asarray(lv).reshape(()))

    assert run_once(False) == run_once(True)


def test_nhwc_layout_untracked_and_fetch_boundaries():
    """Review regressions: (1) an agnostic op on the raw feed must not
    mark downstream convs in-ready (feed vars are fixed NCHW); (2) a
    trailing-axis broadcast the emitter cannot re-aim forces NCHW; (3)
    fetching an NHWC-resident intermediate returns declared-NCHW data."""
    import numpy as np
    from paddle_tpu.contrib.layout import rewrite_program_nhwc

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data(name="img", shape=[3, 8, 8], dtype="float32")
        s = layers.scale(img, scale=2.0)                 # (1)
        c = layers.conv2d(s, num_filters=4, filter_size=3, padding=1)
        wvec = layers.fill_constant([8], "float32", 0.5)
        a = layers.elementwise_add(c, wvec, axis=-1)     # (2)
        c2 = layers.conv2d(a, num_filters=4, filter_size=3, padding=1)
        p = layers.pool2d(c2, pool_type="avg", global_pooling=True)
        loss = layers.mean(p)
    rewrite_program_nhwc(main)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feeds = {"img": np.ones((2, 3, 8, 8), np.float32)}
    lv, cv = exe.run(main, feed=feeds, fetch_list=[loss, c2])  # (3)
    assert np.isfinite(float(np.asarray(lv).reshape(())))
    assert np.asarray(cv).shape == (2, 4, 8, 8)


def test_nhwc_layout_concat_channel_axis():
    """Inception-style channel concat (axis=1) stays inside the NHWC
    region: the emitter re-aims the concat at the physical last axis and
    results match NCHW."""
    import numpy as np
    from paddle_tpu.contrib.layout import rewrite_program_nhwc

    def run_once(rewrite):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 11
        startup.random_seed = 11
        scope = fluid.Scope()
        with fluid.program_guard(main, startup):
            img = layers.data(name="img", shape=[3, 8, 8],
                              dtype="float32")
            b1 = layers.conv2d(img, num_filters=4, filter_size=1)
            b2 = layers.conv2d(img, num_filters=4, filter_size=3,
                               padding=1)
            cat = layers.concat([b1, b2], axis=1)
            c = layers.conv2d(cat, num_filters=4, filter_size=1)
            p = layers.pool2d(c, pool_type="avg", global_pooling=True)
            loss = layers.mean(p)
            if rewrite:
                n = rewrite_program_nhwc(main)
                assert n >= 5, n     # 3 convs + concat + pool
                cat_ops = [op for op in main.desc.global_block.ops
                           if op.type == "concat"]
                assert cat_ops[0].attrs.get("__nhwc_concat__"), \
                    "concat not kept inside the NHWC region"
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup, scope=scope)
            feeds = {"img": np.random.RandomState(1)
                     .rand(2, 3, 8, 8).astype(np.float32)}
            lv, = exe.run(main, feed=feeds, fetch_list=[loss],
                          scope=scope)
        return float(np.asarray(lv).reshape(()))

    a, b = run_once(False), run_once(True)
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_recompute_rewrite_gradient_parity():
    """contrib.recompute: tagged ops' backward re-runs their forward
    (jax.checkpoint in the __vjp__ re-trace) — one full train step is
    bit-identical with and without the rewrite; the memory effect is
    checkpoint's contract (residuals = op inputs only)."""
    import numpy as np
    from paddle_tpu.contrib.recompute import rewrite_program_recompute

    def build(remat):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 21
        startup.random_seed = 21
        scope = fluid.Scope()
        with fluid.program_guard(main, startup):
            x = layers.data(name="x", shape=[64, 32], dtype="float32")
            y = layers.data(name="y", shape=[1], dtype="int64")
            q = layers.fc(x, size=32, num_flatten_dims=2)
            k = layers.fc(x, size=32, num_flatten_dims=2)
            v = layers.fc(x, size=32, num_flatten_dims=2)
            # [B, T, D] -> [B, 1, T, D] single-head for the fused op
            att = layers.scaled_dot_product_attention(
                layers.unsqueeze(q, axes=[1]),
                layers.unsqueeze(k, axes=[1]),
                layers.unsqueeze(v, axes=[1]))
            pooled = layers.reduce_mean(layers.squeeze(att, axes=[1]),
                                        dim=1)
            logits = layers.fc(pooled, size=4)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
            if remat:
                n = rewrite_program_recompute(main,
                                              op_types=("attention",))
                assert n >= 2          # fwd op + vjp snapshot
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup, scope=scope)
            rng = np.random.RandomState(2)
            feeds = {"x": rng.rand(2, 64, 32).astype(np.float32),
                     "y": rng.randint(0, 4, (2, 1)).astype(np.int64)}
            lv, = exe.run(main, feed=feeds, fetch_list=[loss],
                          scope=scope)
            wname = next(op.inputs["Y"][0]
                         for op in main.desc.global_block.ops
                         if op.type == "mul")     # layers.fc weight
            w = np.asarray(scope.find_var(wname))
        return float(np.asarray(lv).reshape(())), w

    l0, w0 = build(False)
    l1, w1 = build(True)
    assert l0 == l1
    np.testing.assert_array_equal(w0, w1)


def test_memory_usage_estimator():
    """contrib memory_usage (reference: contrib/memory_usage_calc.py) —
    parameters + persistables + an activation band, batch dim resolved."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.contrib import memory_usage

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[256], dtype="float32")
        h = fluid.layers.fc(x, 512)          # W [256,512] + b [512]
        fluid.layers.mean(h)
    u = memory_usage(main, batch_size=64, optimizer_slots=0)
    w_bytes = 256 * 512 * 4 + 512 * 4
    assert u["parameters"] == w_bytes
    # activations include x [64,256] and h [64,512]
    assert u["activations"] >= (64 * 256 + 64 * 512) * 4
    assert u["total_low"] <= u["total_high"]
    # batch scaling: doubling the batch grows activations, not params
    u2 = memory_usage(main, batch_size=128, optimizer_slots=0)
    assert u2["parameters"] == u["parameters"]
    assert u2["activations"] > u["activations"]


def test_transformer_noam_schedule_trains():
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 2
    with fluid.program_guard(main, startup):
        loss, _, feed_specs = models.transformer.build(
            is_train=True, src_vocab=64, tgt_vocab=64, max_len=8,
            d_model=32, d_inner=64, n_head=4, n_layer=1,
            lr_scheduler="noam", warmup=10, lr=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {n: rng.randint(0, 64, [2 if d == -1 else d for d in sh])
            .astype(dt) for n, (sh, dt) in feed_specs.items()}
    vals = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])
            for _ in range(4)]
    assert all(np.isfinite(v) for v in vals)
    assert vals[-1] < vals[0]        # warmup lr tiny but nonzero


# -- contrib high-level Trainer/Inferencer (reference: contrib/trainer.py,
# inferencer.py — the book-notebook "simple API") ---------------------------

def test_contrib_trainer_inferencer_roundtrip(tmp_path):
    import numpy as np

    from paddle_tpu import contrib
    from paddle_tpu.fluid import layers

    def train_func():
        x = layers.data("hx", shape=[4], dtype="float32")
        y = layers.data("hy", shape=[1], dtype="float32")
        pred = layers.fc(x, 1, name="hl")
        return layers.mean(layers.square(pred - y))

    def opt_func():
        return fluid.optimizer.SGD(learning_rate=0.05)

    trainer = contrib.Trainer(train_func, opt_func)
    rng = np.random.RandomState(0)
    wt = rng.rand(4, 1).astype("float32")

    def reader():
        for _ in range(8):
            xb = rng.rand(8, 4).astype("float32")
            yield {"hx": xb, "hy": xb @ wt}

    seen = []

    def handler(ev):
        if isinstance(ev, contrib.high_level.EndStepEvent):
            seen.append(float(np.asarray(ev.metrics[0]).reshape(())))

    trainer.train(num_epochs=3, event_handler=handler, reader=reader)
    assert len(seen) == 24 and seen[-1] < seen[0]
    pdir = str(tmp_path / "hl_params")
    trainer.save_params(pdir)

    def infer_func():
        x = layers.data("hx", shape=[4], dtype="float32")
        return layers.fc(x, 1, name="hl")

    inf = contrib.Inferencer(infer_func, pdir)
    xb = np.ones((2, 4), np.float32)
    (out,) = inf.infer({"hx": xb})
    # parity vs the trained weights applied by hand
    w = np.asarray(trainer.scope.find_var("hl.w_0"))
    b = np.asarray(trainer.scope.find_var("hl.b_0"))
    np.testing.assert_allclose(np.asarray(out), xb @ w + b, rtol=1e-5)


def test_op_freq_statistic():
    from paddle_tpu import contrib
    from paddle_tpu.fluid import layers

    x = layers.data("fx", shape=[4], dtype="float32")
    h = layers.fc(x, 4, act="relu")
    layers.fc(h, 4, act="relu")
    uni, adj = contrib.op_freq_statistic(fluid.default_main_program())
    d = dict(uni)
    assert d.get("mul", 0) >= 2 and d.get("relu", 0) == 2
    assert any("->" in k for k, _ in adj)
