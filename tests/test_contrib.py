"""contrib coverage: BF16 inference transpiler + mixed-precision decorate
(reference: contrib/float16/float16_transpiler.py and the later
fluid.contrib.mixed_precision.decorate capability)."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


def test_bf16_transpiler_fetch_consumed_downstream():
    """The fetched var is ALSO consumed by a later op — the rewrite must
    keep that consumer reading the produced value."""
    from paddle_tpu.contrib.float16 import BF16Transpiler
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[8], dtype="float32")
        hidden = layers.fc(x, size=8, act="relu")
        out = layers.fc(hidden, size=4, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.random.RandomState(0).rand(3, 8).astype(np.float32)
    ref_h, ref_o = exe.run(main, feed={"x": xv},
                           fetch_list=[hidden, out])

    BF16Transpiler().transpile(main, scope=fluid.global_scope(),
                               feed_names=["x"],
                               fetch_names=[hidden.name, out.name])
    h2, o2 = exe.run(main, feed={"x": xv}, fetch_list=[hidden, out])
    assert np.asarray(h2).dtype == np.float32
    assert np.asarray(o2).dtype == np.float32
    np.testing.assert_allclose(np.asarray(o2), np.asarray(ref_o),
                               rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(np.asarray(h2), np.asarray(ref_h),
                               rtol=5e-2, atol=5e-2)


def test_amp_decorate_trains():
    from paddle_tpu.contrib import mixed_precision
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 9
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[10], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = mixed_precision.decorate(
            fluid.optimizer.SGD(learning_rate=0.05),
            init_loss_scaling=2.0 ** 8, use_dynamic_loss_scaling=True,
            incr_every_n_steps=5, decr_every_n_nan_or_inf=2)
        opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    w = rng.rand(10, 1).astype(np.float32)
    losses = []
    for _ in range(30):
        xv = rng.rand(16, 10).astype(np.float32)
        yv = xv @ w
        (l,) = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.5, losses
    scale = np.asarray(fluid.global_scope().find_var("loss_scaling@AMP"))
    assert float(scale.reshape(())) >= 2.0 ** 8  # grew or held, never shrank


def test_amp_decr_every_n_nan_or_inf():
    """A single overflow step must NOT shrink the scale when
    decr_every_n_nan_or_inf=2; two consecutive overflows must."""
    from paddle_tpu.contrib import mixed_precision
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = mixed_precision.decorate(
            fluid.optimizer.SGD(learning_rate=0.0),
            init_loss_scaling=1024.0, use_dynamic_loss_scaling=True,
            incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
            decr_ratio=0.5)
        opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    def run(xv):
        exe.run(main, feed={"x": xv, "y": np.zeros((2, 1), np.float32)},
                fetch_list=[loss])
        return float(np.asarray(
            fluid.global_scope().find_var("loss_scaling@AMP")).reshape(()))

    finite = np.ones((2, 4), np.float32)
    overflow = np.full((2, 4), np.inf, np.float32)
    assert run(finite) == 1024.0
    assert run(overflow) == 1024.0        # first bad step: hold
    assert run(overflow) == 512.0         # second consecutive: shrink
    assert run(overflow) == 512.0         # counter reset after shrink
    assert run(overflow) == 256.0
