"""CTC tests: warpctc vs torch.nn.CTCLoss ground truth + numeric grad;
ctc_align greedy decode (reference: test_warpctc_op.py, test_ctc_align.py)."""

import numpy as np
import torch

from op_test import check_grad, run_single_op


def test_warpctc_matches_torch():
    rng = np.random.RandomState(0)
    b, t, c, s = 3, 8, 5, 3
    logits = rng.randn(b, t, c).astype(np.float32)
    labels = np.array([[1, 2, 1], [3, 3, -1], [4, -1, -1]], np.int32)
    t_lens = np.array([8, 6, 5], np.int32)
    l_lens = np.array([3, 2, 1], np.int32)

    out = run_single_op("warpctc",
                        {"Logits": {"x": logits}, "Label": {"l": labels},
                         "LogitsLength": {"tl": t_lens},
                         "LabelLength": {"ll": l_lens}},
                        attrs={"blank": 0},
                        out_slots=("Loss", "WarpCTCGrad"))
    got = out["__out_Loss_0"].reshape(-1)

    tl = torch.nn.CTCLoss(blank=0, reduction="none")
    tlogits = torch.tensor(logits).permute(1, 0, 2).log_softmax(-1)
    tgt = torch.tensor([[1, 2, 1], [3, 3, 0], [4, 0, 0]], dtype=torch.long)
    expect = tl(tlogits, tgt, torch.tensor(t_lens, dtype=torch.long),
                torch.tensor(l_lens, dtype=torch.long)).numpy()
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-4)


def test_warpctc_grad_numeric():
    rng = np.random.RandomState(1)
    b, t, c = 2, 5, 4
    logits = rng.randn(b, t, c).astype(np.float32)
    labels = np.array([[1, 2], [3, -1]], np.int32)
    t_lens = np.array([5, 4], np.int32)
    l_lens = np.array([2, 1], np.int32)
    check_grad("warpctc",
               {"Logits": {"x": logits}, "Label": {"l": labels},
                "LogitsLength": {"tl": t_lens},
                "LabelLength": {"ll": l_lens}},
               attrs={"blank": 0}, out_slot="Loss",
               extra_out_slots=("WarpCTCGrad",), grad_vars=["x"],
               rtol=2e-2, atol=1e-3)


def test_ctc_align_greedy():
    x = np.array([[0, 1, 1, 0, 2, 2, 0],
                  [3, 0, 3, 3, 0, 0, 0]], np.int32)
    out = run_single_op("ctc_align", {"Input": {"x": x}},
                        attrs={"blank": 0, "merge_repeated": True},
                        out_slots=("Output",))["__out_Output_0"]
    np.testing.assert_array_equal(out[0], [1, 2, -1, -1, -1, -1, -1])
    np.testing.assert_array_equal(out[1], [3, 3, -1, -1, -1, -1, -1])
