"""contrib.slim model-compression framework (reference:
contrib/slim — prune/pruner.py Magnitude/Ratio pruners,
prune_strategy.py, core/compress_pass.py CompressPass orchestration):
pruning masks hold through training, sensitivity scan picks per-param
ratios, Compressor drives the strategy callbacks."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.contrib import slim


def test_ratio_pruner_mask():
    p = slim.RatioPruner({"*": 0.5})
    v = np.arange(1.0, 11.0, dtype=np.float32)   # magnitudes 1..10
    mask = p.prune("w", v)
    assert mask.sum() == 5 and (mask[-5:] == 1).all()
    # per-param override
    p2 = slim.RatioPruner({"w": 0.2, "*": 1.0})
    assert p2.prune("w", v).sum() == 2
    assert p2.prune("other", v).sum() == 10


def test_magnitude_pruner_mask():
    p = slim.MagnitudePruner(0.5)
    v = np.array([-1.0, 0.2, 0.6, -0.4], np.float32)
    np.testing.assert_array_equal(p.prune("w", v), [1, 0, 1, 0])


def _mlp(name_w="slim_w"):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 11
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[8], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        h = layers.fc(x, size=16, act="relu",
                      param_attr=fluid.ParamAttr(name=name_w))
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def _reader(n=8, bs=16, seed=0):
    def r():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            x = rng.rand(bs, 8).astype(np.float32)
            yield {"x": x,
                   "y": (x.sum(1, keepdims=True) * 0.5).astype(np.float32)}
    return r


def test_prune_strategy_sparsity_survives_training():
    main, startup, loss = _mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    strategy = slim.PruneStrategy(slim.RatioPruner({"*": 0.5}),
                                  params=["slim_w"], start_epoch=0,
                                  end_epoch=2)
    comp = slim.Compressor(place=fluid.CPUPlace(), reader=_reader(),
                           epoch=2).add_strategy(strategy)
    comp.run(main, fetch_list=[loss])
    from paddle_tpu.core.scope import global_scope
    w = np.asarray(global_scope().find_var("slim_w"))
    sparsity = (w == 0).mean()
    # the optimizer ran 16 updates; the mask re-applied after each, so
    # exactly half the weights are still zero
    assert abs(sparsity - 0.5) < 0.02, sparsity
    ctx = slim.Context(exe, main, global_scope())
    assert abs(strategy.sparsity(ctx)["slim_w"] - 0.5) < 0.02


def test_pruned_model_still_trains():
    main, startup, loss = _mlp(name_w="slim_w2")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    strategy = slim.PruneStrategy(slim.RatioPruner({"*": 0.5}),
                                  params=["slim_w2"], end_epoch=3)
    comp = slim.Compressor(place=fluid.CPUPlace(), reader=_reader(n=10),
                           epoch=3).add_strategy(strategy)
    (last,) = comp.run(main, fetch_list=[loss])
    # eval on fresh data: pruned model fits the task reasonably
    rng = np.random.RandomState(9)
    x = rng.rand(32, 8).astype(np.float32)
    (l2,) = exe.run(main, feed={"x": x, "y": (x.sum(1, keepdims=True) * 0.5)
                                .astype(np.float32)}, fetch_list=[loss])
    assert float(l2) < 1.0


def test_sensitive_prune_strategy_scan():
    main, startup, loss = _mlp(name_w="slim_w3")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    from paddle_tpu.core.scope import global_scope
    rng = np.random.RandomState(2)
    xv = rng.rand(64, 8).astype(np.float32)
    yv = (xv.sum(1, keepdims=True) * 0.5).astype(np.float32)

    def eval_fn():
        return float(exe.run(main, feed={"x": xv, "y": yv},
                             fetch_list=[loss])[0])

    pruner = slim.RatioPruner({"*": 1.0})
    strategy = slim.SensitivePruneStrategy(
        pruner, params=["slim_w3"], eval_fn=eval_fn,
        candidate_ratios=(0.9, 0.5, 0.1), max_loss_increase=1e9)
    ctx = slim.Context(exe, main, global_scope())
    strategy.on_compress_begin(ctx)
    # unlimited budget -> the most aggressive candidate wins
    assert strategy.chosen["slim_w3"] == 0.1

    strategy2 = slim.SensitivePruneStrategy(
        slim.RatioPruner({"*": 1.0}), params=["slim_w3"],
        eval_fn=eval_fn, candidate_ratios=(0.9, 0.5, 0.1),
        max_loss_increase=-1e9)
    strategy2.on_compress_begin(ctx)
    # impossible budget -> nothing pruned
    assert strategy2.chosen["slim_w3"] == 1.0
