"""Benchmark harness tests: run_bench with a dp mesh on the virtual CPU
devices (the fluid_benchmark --update_method nccl2 path) and the JSON
contract (reference: benchmark/fluid/fluid_benchmark.py train_parallel)."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_run_bench_local_json_contract():
    from bench import run_bench
    res = run_bench("mnist", batch_size=64, steps=3, warmup=1)
    assert set(res) >= {"metric", "value", "unit", "vs_baseline"}
    assert res["unit"] == "images/sec" and res["value"] > 0
    assert "1 chip" in res["metric"]


def test_run_infer_bench_contract():
    from bench import run_infer_bench
    res = run_infer_bench("resnet50", batch_size=1, steps=2, warmup=1)
    assert res["unit"] == "images/sec" and res["value"] > 0
    assert "infer" in res["metric"]
    assert res["vs_baseline"] is not None


def test_run_bench_dp_mesh():
    import jax
    from bench import run_bench
    from paddle_tpu.parallel import make_mesh
    mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])
    res = run_bench("mnist", batch_size=64, steps=3, warmup=1, mesh=mesh)
    assert res["value"] > 0 and np.isfinite(res["value"])
    assert "2 chips" in res["metric"]


def test_aggregate_line_fits_tail_window():
    """The sweep aggregate (the final stdout line) must parse to all rows
    from the driver's tail capture alone — BENCH_r03 lost its head rows
    because the verbose aggregate overflowed the window (round-3 verdict
    item 6). Budget: well under 2 KB for the full 18-row sweep."""
    import json
    from bench import aggregate_line
    rows = []
    units = {"transformer": "tokens/sec", "deepfm": "examples/sec"}
    # keep in lockstep with bench.DEFAULT_BATCH_SIZES (the real sweep)
    from bench import DEFAULT_BATCH_SIZES
    names = sorted(DEFAULT_BATCH_SIZES)
    for m in names:
        rows.append({"metric": f"{m} train throughput (bs128, amp-bf16, "
                               f"1 chip)",
                     "value": 123456.789, "unit": units.get(m, "images/sec"),
                     "vs_baseline": 12.34, "mfu_pct": 38.3,
                     "gflop_per_step": 1234.5})
    for m in ("resnet50", "vgg", "googlenet"):
        rows.append({"metric": f"{m} infer latency-throughput (bs16, "
                               f"1 chip)", "value": 9999.9,
                     "unit": "images/sec", "vs_baseline": None,
                     "mfu_pct": 12.0})
    rows.append({"metric": "resnet50 serving cold-start, AOT-load -> "
                           "first inference (bs16, 1 chip)",
                 "value": 0.898, "unit": "seconds", "vs_baseline": None,
                 "compile_from_source_s": 4.8, "speedup": 5.3})
    agg = aggregate_line(rows, rows[0], len(rows))
    line = json.dumps(agg, separators=(",", ":"))
    assert len(line) < 1500, len(line)
    back = json.loads(line)
    assert len(back["rows"]) == len(names) + 4
    assert back["rows"][-1]["m"] == "resnet50-coldstart"
    assert all({"m", "v", "u"} <= set(r) for r in back["rows"])
    # a failed row keeps its short error
    rows[3]["value"] = None
    rows[3]["error"] = "x" * 500
    rows[-1]["value"] = None          # failed cold-start keeps err too
    rows[-1]["error"] = "y" * 500
    agg2 = aggregate_line(rows, rows[0], len(rows) - 2)
    line2 = json.dumps(agg2, separators=(",", ":"))
    assert len(line2) < 1500
    back2 = json.loads(line2)
    assert back2["rows"][3]["err"] == "x" * 40
    assert back2["rows"][-1]["err"] == "y" * 40
