"""Benchmark harness tests: run_bench with a dp mesh on the virtual CPU
devices (the fluid_benchmark --update_method nccl2 path) and the JSON
contract (reference: benchmark/fluid/fluid_benchmark.py train_parallel)."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_run_bench_local_json_contract():
    from bench import run_bench
    res = run_bench("mnist", batch_size=64, steps=3, warmup=1)
    assert set(res) >= {"metric", "value", "unit", "vs_baseline"}
    assert res["unit"] == "images/sec" and res["value"] > 0
    assert "1 chip" in res["metric"]


def test_run_infer_bench_contract():
    from bench import run_infer_bench
    res = run_infer_bench("resnet50", batch_size=1, steps=2, warmup=1)
    assert res["unit"] == "images/sec" and res["value"] > 0
    assert "infer" in res["metric"]
    assert res["vs_baseline"] is not None


def test_run_bench_dp_mesh():
    import jax
    from bench import run_bench
    from paddle_tpu.parallel import make_mesh
    mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])
    res = run_bench("mnist", batch_size=64, steps=3, warmup=1, mesh=mesh)
    assert res["value"] > 0 and np.isfinite(res["value"])
    assert "2 chips" in res["metric"]
