"""Subprocess body for the master-failover test: drain the shared queue
through MasterClient's reconnect-with-backoff, counting every chunk
actually CONSUMED (trained) — the parent asserts the union across
workers covers the dataset exactly once even though the master is
SIGKILLed and restarted from its snapshot mid-drain.

Accounting note: records are counted when the scan completes, before the
finish report's fate is known. A report whose first delivery landed just
as the master died is resent after reconnect and rejected as a duplicate
(accepted=False) — the chunk was still trained exactly once, by us."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from paddle_tpu import recordio                          # noqa: E402
from paddle_tpu.data.master_service import MasterClient  # noqa: E402


def main():
    client = MasterClient(reconnect_timeout_s=60.0)
    records = []
    completed = []
    while True:
        task = client.get_task()
        if task is None:
            if client.done:
                break
            time.sleep(0.05)
            continue
        got = []
        scanner = recordio.Scanner(task.path, task.chunk_begin,
                                   task.chunk_end)
        try:
            for rec in scanner:
                got.append(rec.decode())
                time.sleep(float(os.environ.get("TRAIN_SLEEP", "0")))
        finally:
            scanner.close()
        client.task_finished(task)
        records.extend(got)
        completed.append(task.id)
    print(json.dumps({"records": records, "completed": completed}))


if __name__ == "__main__":
    main()
