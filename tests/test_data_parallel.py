"""Data-parallel tests over a virtual 8-device CPU mesh.

Capability parity with the reference's ParallelExecutor
convergence-equivalence tests (reference: unittests/
parallel_executor_test_base.py, test_parallel_executor_mnist.py — train the
same model single- vs multi-device and compare losses)."""

import numpy as np
import pytest
import jax

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.parallel import make_mesh


def _build_mlp(seed=5):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    # nonzero seed on the startup program → reproducible initialization
    # across runs (reference Program.random_seed semantics)
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[32], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="int64")
        h = layers.fc(input=x, size=64, act="relu")
        logits = layers.fc(input=h, size=4)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


_PROJ = np.random.RandomState(42).rand(32, 4).astype(np.float32)


def _feeds(step, bs=32):
    rng = np.random.RandomState(100 + step)
    xv = rng.rand(bs, 32).astype(np.float32)
    yv = np.argmax(xv @ _PROJ, axis=1).astype(np.int64)[:, None]
    return {"x": xv, "y": yv}


def test_mesh_construction():
    mesh = make_mesh()
    assert mesh.devices.size == 8  # conftest forces 8 virtual CPU devices
    mesh2 = make_mesh({"dp": 4, "tp": 2})
    assert mesh2.axis_names == ("dp", "tp")


def test_data_parallel_runs_and_converges():
    main, startup, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    compiled = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    losses = []
    for step in range(30):
        (lv,) = exe.run(compiled, feed=_feeds(step), fetch_list=[loss])
        losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0], losses


def test_dp_matches_single_device():
    """Same seeds, same global batch → DP loss curve must match the
    single-device run (the reference's equivalence contract)."""
    scope1 = fluid.Scope()
    main, startup, loss = _build_mlp(seed=9)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope1)
    single = [float(np.asarray(exe.run(main, feed=_feeds(s), scope=scope1,
                                       fetch_list=[loss])[0]))
              for s in range(8)]

    scope2 = fluid.Scope()
    exe.run(startup, scope=scope2)
    compiled = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    parallel = [float(np.asarray(exe.run(compiled, feed=_feeds(s),
                                         scope=scope2,
                                         fetch_list=[loss])[0]))
                for s in range(8)]
    np.testing.assert_allclose(single, parallel, rtol=1e-4, atol=1e-5)


def test_feeds_actually_sharded():
    """The compiled step must shard the batch over the dp axis (8-way)."""
    from paddle_tpu.core.lowering import CompiledBlock
    main, startup, loss = _build_mlp()
    from paddle_tpu.parallel.mesh import DistributeConfig
    mesh = make_mesh()
    dist = DistributeConfig(mesh=mesh, data_axis="dp")
    cb = CompiledBlock(main.desc, 0, ["x", "y"], [loss.name], dist=dist)
    sh = cb._input_shardings()
    from jax.sharding import PartitionSpec as P
    assert sh[2]["x"].spec == P("dp", None)
    assert sh[2]["y"].spec == P("dp", None)
    # params replicate
    for s in sh[0].values():
        assert s.spec == P()


def test_parallel_executor_api():
    """reference: parallel_executor.py:41 API shape."""
    main, startup, loss = _build_mlp(seed=11)
    with fluid.program_guard(main, startup):
        pass
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    pe = fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name,
                                main_program=main)
    l0 = pe.run(fetch_list=[loss.name], feed=_feeds(0))
    l5 = None
    for s in range(10):
        (l5,) = pe.run(fetch_list=[loss.name], feed=_feeds(s))
    assert float(np.asarray(l5)) < float(np.asarray(l0[0]))


def test_tp_param_sharding_compiles():
    """TP capability (absent in the reference, §2 parallelism inventory —
    'optional extension via pjit param sharding'): shard an fc weight over
    a tp axis and run."""
    from paddle_tpu.parallel.mesh import DistributeConfig
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[16], dtype="float32")
        h = layers.fc(input=x, size=32, act="relu",
                      param_attr=fluid.ParamAttr(name="tp_w"))
        loss = layers.mean(h)
    mesh = make_mesh({"dp": 4, "tp": 2})
    dist = DistributeConfig(mesh=mesh, data_axis="dp",
                            param_axes={"tp_w": (None, "tp")})
    compiled = fluid.CompiledProgram(main).with_sharding(dist)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.random.RandomState(0).rand(8, 16).astype(np.float32)
    (out,) = exe.run(compiled, feed={"x": xv}, fetch_list=[loss])
    assert np.isfinite(np.asarray(out)).all()
