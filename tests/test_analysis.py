"""Build-time program verifier (paddle_tpu.analysis): known-bad corpus
asserting rule id, severity, and op provenance per diagnostic; the
all-green pass over the model zoo and book programs; executor
integration via FLAGS_verify_program; the proglint CLI; and the
shape-inference failure taxonomy (reference capability: C++ InferShape +
op-registry validation on append_op, framework/operator.cc:963)."""

import importlib.util
import json
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import analysis, flags, models
from paddle_tpu.analysis import Severity
from paddle_tpu.core import ir
from paddle_tpu.core.shape_inference import abstract_eval_op
from paddle_tpu.fluid import layers


def find(diags, rule):
    return [d for d in diags if d.rule == rule]


def one(diags, rule):
    hits = find(diags, rule)
    assert len(hits) == 1, (rule, [d.format() for d in diags])
    return hits[0]


# -- known-bad corpus --------------------------------------------------------

def test_corpus_dangling_input():
    desc = ir.ProgramDesc()
    b = desc.global_block
    b.add_var(ir.VarDesc(name="y", shape=[4, 4], dtype="float32"))
    b.append_op(ir.OpDesc(type="relu", inputs={"X": ["missing"]},
                          outputs={"Out": ["y"]}))
    d = one(analysis.analyze_program(desc), "dangling-input")
    assert d.severity == Severity.ERROR
    assert (d.block_idx, d.op_index, d.op_type) == (0, 0, "relu")
    assert d.var == "missing"


def test_corpus_unknown_op():
    desc = ir.ProgramDesc()
    b = desc.global_block
    b.add_var(ir.VarDesc(name="x", shape=[2], dtype="float32"))
    b.append_op(ir.OpDesc(type="frobnicate", inputs={"X": ["x"]},
                          outputs={"Out": ["x"]}))
    d = one(analysis.analyze_program(desc), "unknown-op")
    assert d.severity == Severity.ERROR
    assert d.op_type == "frobnicate" and d.op_index == 0


def test_corpus_dtype_drift():
    desc = ir.ProgramDesc()
    b = desc.global_block
    b.add_var(ir.VarDesc(name="x", shape=[2, 3], dtype="float32"))
    b.add_var(ir.VarDesc(name="y", shape=[2, 3], dtype="float64"))
    b.append_op(ir.OpDesc(type="relu", inputs={"X": ["x"]},
                          outputs={"Out": ["y"]}))
    d = one(analysis.analyze_program(desc), "dtype-mismatch")
    assert d.severity == Severity.ERROR
    assert (d.op_index, d.op_type, d.var) == (0, "relu", "y")
    assert d.details["declared"] == "float64"
    assert d.details["inferred"] == "float32"


def test_corpus_shape_drift():
    desc = ir.ProgramDesc()
    b = desc.global_block
    b.add_var(ir.VarDesc(name="x", shape=[2, 3], dtype="float32"))
    b.add_var(ir.VarDesc(name="w", shape=[3, 5], dtype="float32"))
    b.add_var(ir.VarDesc(name="y", shape=[2, 4], dtype="float32"))  # != [2,5]
    b.append_op(ir.OpDesc(type="mul", inputs={"X": ["x"], "Y": ["w"]},
                          outputs={"Out": ["y"]}))
    d = one(analysis.analyze_program(desc), "shape-mismatch")
    assert d.severity == Severity.ERROR
    assert (d.op_index, d.op_type, d.var) == (0, "mul", "y")
    assert d.details["inferred"] == [2, 5]
    assert d.details["declared"] == [2, 4]


def test_corpus_dead_op():
    desc = ir.ProgramDesc()
    b = desc.global_block
    for n in ("x", "y", "z"):
        b.add_var(ir.VarDesc(name=n, shape=[2, 2], dtype="float32"))
    b.append_op(ir.OpDesc(type="relu", inputs={"X": ["x"]},
                          outputs={"Out": ["y"]}))
    b.append_op(ir.OpDesc(type="tanh", inputs={"X": ["x"]},
                          outputs={"Out": ["z"]}))
    diags = analysis.analyze_program(desc, feed_names=["x"],
                                     fetch_names=["y"])
    d = one(diags, "dead-op")
    assert d.severity == Severity.WARNING
    assert (d.op_index, d.op_type) == (1, "tanh")
    # without a fetch set the rule stays quiet
    assert not find(analysis.analyze_program(desc), "dead-op")


def test_corpus_waw_param_hazard():
    desc = ir.ProgramDesc()
    b = desc.global_block
    b.add_var(ir.VarDesc(name="w", shape=[2, 2], dtype="float32",
                         persistable=True, is_parameter=True))
    mk = dict(type="fill_constant", outputs={"Out": ["w"]},
              attrs={"shape": [2, 2], "value": 0.0, "dtype": "float32"})
    b.append_op(ir.OpDesc(**mk))
    b.append_op(ir.OpDesc(**mk))
    d = one(analysis.analyze_program(desc), "waw-param")
    assert d.severity == Severity.ERROR          # no intervening read
    assert d.var == "w" and d.op_index == 1
    assert d.details == {"first_write": 0, "second_write": 1,
                         "intervening_read": False}


def test_corpus_dropout_in_inference():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[8], dtype="float32")
        h = layers.dropout(x, dropout_prob=0.3)
        layers.mean(h)
    infer = main.clone(for_test=True)
    d = one(analysis.analyze_program(infer), "rng-in-inference")
    assert d.severity == Severity.WARNING
    assert d.op_type == "dropout"
    assert d.details["self_gating"] is True
    # train-mode program: quiet
    assert not find(analysis.analyze_program(main), "rng-in-inference")


def test_corpus_sampling_in_inference_not_gated():
    desc = ir.ProgramDesc()
    b = desc.global_block
    b.add_var(ir.VarDesc(name="p", shape=[4, 10], dtype="float32"))
    b.add_var(ir.VarDesc(name="ids", shape=[4], dtype="int64"))
    b.append_op(ir.OpDesc(type="sampling_id", inputs={"X": ["p"]},
                          outputs={"Out": ["ids"]}))
    d = one(analysis.analyze_program(desc, is_test=True),
            "rng-in-inference")
    assert d.details["self_gating"] is False


def test_corpus_def_before_use():
    desc = ir.ProgramDesc()
    b = desc.global_block
    for n in ("x", "y", "z"):
        b.add_var(ir.VarDesc(name=n, shape=[2, 2], dtype="float32"))
    b.append_op(ir.OpDesc(type="relu", inputs={"X": ["y"]},   # y not yet
                          outputs={"Out": ["z"]}))
    b.append_op(ir.OpDesc(type="tanh", inputs={"X": ["x"]},
                          outputs={"Out": ["y"]}))
    d = one(analysis.analyze_program(desc), "def-before-use")
    assert d.severity == Severity.ERROR
    assert (d.op_index, d.var) == (0, "y")
    assert d.details["first_write_index"] == 1


def test_corpus_unfed_input():
    desc = ir.ProgramDesc()
    b = desc.global_block
    for n in ("x", "lbl", "y"):
        b.add_var(ir.VarDesc(name=n, shape=[2, 2], dtype="float32"))
    b.append_op(ir.OpDesc(type="elementwise_add",
                          inputs={"X": ["x"], "Y": ["lbl"]},
                          outputs={"Out": ["y"]}))
    diags = analysis.analyze_program(desc, feed_names=["x"],
                                     fetch_names=["y"])
    d = one(diags, "unfed-input")
    assert d.severity == Severity.ERROR and d.var == "lbl"
    # feeding it silences the rule
    assert not find(analysis.analyze_program(desc, feed_names=["x", "lbl"],
                                             fetch_names=["y"]),
                    "unfed-input")


def _while_program(bind_p: bool):
    """block 1 = while body reading parent var 'p'; bound via x_vars
    only when bind_p."""
    desc = ir.ProgramDesc()
    b0 = desc.global_block
    b0.add_var(ir.VarDesc(name="c", shape=[1], dtype="bool"))
    b0.add_var(ir.VarDesc(name="p", shape=[2, 2], dtype="float32"))
    b0.add_var(ir.VarDesc(name="out_c", shape=[1], dtype="bool"))
    b1 = desc.append_block(parent_idx=0)
    b1.add_var(ir.VarDesc(name="tmp", shape=[2, 2], dtype="float32"))
    b1.append_op(ir.OpDesc(type="relu", inputs={"X": ["p"]},
                           outputs={"Out": ["tmp"]}))
    b1.append_op(ir.OpDesc(type="logical_not", inputs={"X": ["c"]},
                           outputs={"Out": ["c"]}))
    b0.append_op(ir.OpDesc(
        type="while",
        inputs={"Carry": ["c"], "X": (["p"] if bind_p else [])},
        outputs={"Out": ["out_c"]},
        attrs={"sub_block": 1, "cond_var": "c", "carry_vars": ["c"],
               "x_vars": (["p"] if bind_p else [])}))
    return desc


def test_corpus_subblock_unbound_read():
    diags = analysis.analyze_program(_while_program(bind_p=False))
    d = one(diags, "subblock-unbound-read")
    assert d.severity == Severity.ERROR
    assert (d.block_idx, d.var) == (1, "p")
    assert d.details["owner_type"] == "while"
    assert not find(analysis.analyze_program(_while_program(bind_p=True)),
                    "subblock-unbound-read")


def test_corpus_attr_schema():
    desc = ir.ProgramDesc()
    b0 = desc.global_block
    b0.add_var(ir.VarDesc(name="c", shape=[1], dtype="bool"))
    b0.append_op(ir.OpDesc(            # missing cond_var/carry_vars,
        type="while",                  # sub_block out of range
        inputs={"Carry": ["c"]}, outputs={"Out": ["c"]},
        attrs={"sub_block": 7}))
    diags = find(analysis.analyze_program(desc), "attr-schema")
    assert diags and all(d.severity == Severity.ERROR for d in diags)
    msgs = " | ".join(d.message for d in diags)
    assert "cond_var" in msgs and "block 7" in msgs


def test_corpus_grad_pairing():
    desc = ir.ProgramDesc()
    b = desc.global_block
    b.add_var(ir.VarDesc(name="w@GRAD", shape=[2], dtype="float32"))
    d = one(analysis.analyze_program(desc), "grad-pairing")
    assert d.severity == Severity.WARNING
    assert d.details["forward_var"] == "w"


# -- suppressions ------------------------------------------------------------

def test_suppression_per_op_and_per_run():
    desc = ir.ProgramDesc()
    b = desc.global_block
    b.add_var(ir.VarDesc(name="y", shape=[2], dtype="float32"))
    op = b.append_op(ir.OpDesc(type="relu", inputs={"X": ["missing"]},
                               outputs={"Out": ["y"]}))
    assert find(analysis.analyze_program(desc), "dangling-input")
    # per-run
    assert not find(analysis.analyze_program(
        desc, suppress=("dangling-input",)), "dangling-input")
    # per-op attr
    analysis.suppress_op(op, "dangling-input")
    assert not find(analysis.analyze_program(desc), "dangling-input")
    # "*" suppresses everything anchored to the op
    op.attrs["__lint_suppress__"] = ["*"]
    assert not [d for d in analysis.analyze_program(desc)
                if d.op_index == 0]


# -- executor integration (FLAGS_verify_program) -----------------------------

def _corpus_bad_programs():
    """(label, desc, expected rule) — every ERROR-severity corpus
    program, for the build-time rejection sweep."""
    out = []

    desc = ir.ProgramDesc()
    b = desc.global_block
    b.add_var(ir.VarDesc(name="y", shape=[2, 2], dtype="float32"))
    b.append_op(ir.OpDesc(type="relu", inputs={"X": ["missing"]},
                          outputs={"Out": ["y"]}))
    out.append(("dangling_input", desc, "dangling-input"))

    desc = ir.ProgramDesc()
    b = desc.global_block
    b.add_var(ir.VarDesc(name="x", shape=[2, 2], dtype="float32"))
    b.add_var(ir.VarDesc(name="y", shape=[2, 2], dtype="float32"))
    b.append_op(ir.OpDesc(type="frobnicate", inputs={"X": ["x"]},
                          outputs={"Out": ["y"]}))
    out.append(("unknown_op", desc, "unknown-op"))

    desc = ir.ProgramDesc()
    b = desc.global_block
    b.add_var(ir.VarDesc(name="x", shape=[2, 3], dtype="float32"))
    b.add_var(ir.VarDesc(name="y", shape=[2, 3], dtype="float64"))
    b.append_op(ir.OpDesc(type="relu", inputs={"X": ["x"]},
                          outputs={"Out": ["y"]}))
    out.append(("dtype_drift", desc, "dtype-mismatch"))

    desc = ir.ProgramDesc()
    b = desc.global_block
    b.add_var(ir.VarDesc(name="x", shape=[2, 3], dtype="float32"))
    b.add_var(ir.VarDesc(name="w", shape=[3, 5], dtype="float32"))
    b.add_var(ir.VarDesc(name="y", shape=[2, 4], dtype="float32"))
    b.append_op(ir.OpDesc(type="mul", inputs={"X": ["x"], "Y": ["w"]},
                          outputs={"Out": ["y"]}))
    out.append(("shape_drift", desc, "shape-mismatch"))

    desc = ir.ProgramDesc()
    b = desc.global_block
    b.add_var(ir.VarDesc(name="w", shape=[2, 2], dtype="float32",
                         persistable=True, is_parameter=True))
    mk = dict(type="fill_constant", outputs={"Out": ["w"]},
              attrs={"shape": [2, 2], "value": 0.0, "dtype": "float32"})
    b.append_op(ir.OpDesc(**mk))
    b.append_op(ir.OpDesc(**mk))
    out.append(("waw_param", desc, "waw-param"))

    return out


@pytest.mark.parametrize(
    "label,desc,rule",
    _corpus_bad_programs(),
    ids=[label for label, _, _ in _corpus_bad_programs()])
def test_verify_flag_rejects_corpus_at_build(label, desc, rule):
    """Acceptance: with FLAGS_verify_program=1 every known-bad corpus
    program is rejected at CompiledBlock build with a diagnostic naming
    the offending op and rule."""
    from paddle_tpu.core.lowering import CompiledBlock
    fetch = [next(iter(desc.global_block.vars))]
    flags.set("verify_program", True)
    try:
        with pytest.raises(analysis.ProgramVerificationError) as ei:
            CompiledBlock(desc, 0, [], fetch)
        assert rule in str(ei.value)
    finally:
        flags.reset("verify_program")


def test_verify_program_flag_rejects_at_build():
    desc = ir.ProgramDesc()
    b = desc.global_block
    b.add_var(ir.VarDesc(name="y", shape=[2, 2], dtype="float32"))
    b.append_op(ir.OpDesc(type="relu", inputs={"X": ["nope"]},
                          outputs={"Out": ["y"]}))
    from paddle_tpu.core.lowering import CompiledBlock
    flags.set("verify_program", True)
    try:
        with pytest.raises(analysis.ProgramVerificationError) as ei:
            CompiledBlock(desc, 0, [], ["y"])
        msg = str(ei.value)
        assert "dangling-input" in msg and "relu" in msg
    finally:
        flags.reset("verify_program")


def test_verify_program_flag_clean_program_runs():
    flags.set("verify_program", True)
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data(name="x", shape=[4], dtype="float32")
            loss = layers.mean(layers.fc(x, size=3))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        (out,) = exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                         fetch_list=[loss])
        assert np.isfinite(float(out))
    finally:
        flags.reset("verify_program")


def test_build_strategy_verify_knob():
    from paddle_tpu.fluid.compiler import BuildStrategy, CompiledProgram
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        loss = layers.mean(layers.fc(x, size=3))
    # corrupt the program after build: point an op at a missing var
    main.desc.global_block.ops[0].inputs["X"] = ["gone"]
    bs = BuildStrategy()
    bs.verify_program = True
    cp = CompiledProgram(main).with_build_strategy(bs)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    with pytest.raises(analysis.ProgramVerificationError):
        exe.run(cp, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[loss])


def test_analysis_metrics_published():
    from paddle_tpu.observability import metrics as obs_metrics
    desc = ir.ProgramDesc()
    b = desc.global_block
    b.add_var(ir.VarDesc(name="y", shape=[2], dtype="float32"))
    b.append_op(ir.OpDesc(type="frobnicate", outputs={"Out": ["y"]}))
    fam = obs_metrics.counter("paddle_analysis_diagnostics_total",
                              "", ("rule", "severity"))
    before = fam.labels(rule="unknown-op", severity="error").value
    analysis.analyze_program(desc)
    assert fam.labels(rule="unknown-op",
                      severity="error").value == before + 1
    hist = obs_metrics.histogram("paddle_analysis_duration_seconds", "")
    assert hist.labels().count >= 1


# -- shape-inference failure taxonomy (satellite fix) ------------------------

def test_abstract_eval_taxonomy():
    from paddle_tpu.core.registry import OPS, register_op

    @register_op("___test_buggy_op", no_grad=True)
    def _buggy(ctx, ins, attrs):          # noqa: ARG001
        raise TypeError("deliberate emitter bug")

    try:
        b = ir.BlockDesc()
        b.add_var(ir.VarDesc(name="x", shape=[2, 2], dtype="float32"))
        b.add_var(ir.VarDesc(name="y", shape=[2, 2], dtype="float32"))

        res = abstract_eval_op(b, ir.OpDesc(type="no_such_op"))
        assert not res.ok and res.skipped == "unregistered-op"

        res = abstract_eval_op(b, ir.OpDesc(
            type="relu", inputs={"X": ["undeclared"]},
            outputs={"Out": ["y"]}))
        assert not res.ok and res.skipped == "missing-input-shape"

        res = abstract_eval_op(b, ir.OpDesc(
            type="___test_buggy_op", inputs={"X": ["x"]},
            outputs={"Out": ["y"]}))
        assert not res.ok and res.error_type == "TypeError"
        assert "deliberate emitter bug" in res.error

        res = abstract_eval_op(b, ir.OpDesc(
            type="relu", inputs={"X": ["x"]}, outputs={"Out": ["y"]}))
        assert res.ok and res.outputs["y"] == ((2, 2), "float32")
    finally:
        # the registry is process-global and test_op_smoke_sweep asserts
        # exact coverage of it — never leak the fixture op
        OPS.pop("___test_buggy_op", None)


def test_shape_infer_error_surfaces_as_diagnostic():
    desc = ir.ProgramDesc()
    b = desc.global_block
    b.add_var(ir.VarDesc(name="x", shape=[2, 2], dtype="float32"))
    b.add_var(ir.VarDesc(name="y", shape=[2, 2], dtype="float32"))
    b.append_op(ir.OpDesc(type="___test_buggy_op2",
                          inputs={"X": ["x"]}, outputs={"Out": ["y"]}))
    from paddle_tpu.core.registry import OPS, register_op

    @register_op("___test_buggy_op2", no_grad=True)
    def _buggy2(ctx, ins, attrs):         # noqa: ARG001
        raise ValueError("bad broadcast")

    try:
        d = one(analysis.analyze_program(desc), "shape-infer-error")
        assert d.severity == Severity.WARNING
        assert d.op_type == "___test_buggy_op2"
        assert d.details["error_type"] == "ValueError"
    finally:
        OPS.pop("___test_buggy_op2", None)


def test_sparse_embedding_vjp_abstract_eval_regression():
    """Regression (analyzer corpus, satellite fix): the lookup_table
    __vjp__ returns a RowSparseGrad pytree; abstract eval must report
    its dense shape, not crash on the missing .shape attribute — and
    the whole embedding-train program must analyze error-free."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    with fluid.program_guard(main, startup):
        ids = layers.data(name="ids", shape=[1], dtype="int64")
        emb = layers.embedding(ids, size=[20, 8])
        loss = layers.mean(layers.fc(emb, size=2))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    diags = analysis.analyze_program(main, feed_names=["ids"],
                                     fetch_names=[loss.name])
    bad = [d for d in diags if d.severity >= Severity.WARNING]
    assert not bad, [d.format() for d in bad]


def test_dynamic_batch_grad_reshape_regression():
    """Regression (satellite fix): a reshape([-1, V]) between forward
    and loss makes the grad var's -1 mean B*T, not B. The sentinel-space
    fixpoint keeps them distinct, so no false shape-infer-error from the
    __vjp__ cotangent reshape."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4, 6], dtype="float32")
        h = layers.fc(x, size=5, num_flatten_dims=2)      # [-1, 4, 5]
        h2 = layers.reshape(h, shape=[-1, 5])             # [B*4, 5]
        loss = layers.mean(layers.fc(h2, size=1))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    diags = analysis.analyze_program(main, feed_names=["x"],
                                     fetch_names=[loss.name])
    bad = [d for d in diags if d.severity >= Severity.WARNING]
    assert not bad, [d.format() for d in bad]


# -- all-green pass over the model zoo + book programs -----------------------

_MODEL_CFGS = {
    "mnist": {},
    "smallnet": {},
    "deepfm": dict(num_fields=4, vocab_size=100),
    "roofline_probe": dict(d=16, depth=2),
    "machine_translation": {},
    "alexnet": dict(class_dim=10, image_size=64),
    "vgg": dict(class_dim=10, image_size=32),
    "resnet": dict(class_dim=10, image_size=32),
    "se_resnext": dict(class_dim=10, image_size=32),
    "googlenet": dict(class_dim=10, image_size=128),
    "stacked_dynamic_lstm": {},
    "transformer": dict(src_vocab=50, tgt_vocab=50, max_len=8,
                        d_model=16, d_inner=32, n_head=2, n_layer=1,
                        dropout=0.1),
}
_HEAVY = {"alexnet", "vgg", "resnet", "se_resnext", "googlenet",
          "stacked_dynamic_lstm", "transformer"}


def _assert_model_green(name):
    kw = _MODEL_CFGS[name]
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 1
    with fluid.program_guard(main, startup):
        out = getattr(models, name).build(**kw)
    loss, fetches, specs = out[0], out[1] or [], out[2]
    fetch_names = [loss.name] + [getattr(f, "name", str(f))
                                 for f in fetches]
    for program, feeds, fns in ((main, sorted(specs), fetch_names),
                                (startup, [], None)):
        diags = analysis.analyze_program(program, feed_names=feeds,
                                         fetch_names=fns)
        errs = [d for d in diags if d.severity == Severity.ERROR]
        assert not errs, (name, [d.format() for d in errs])


@pytest.mark.parametrize("name", sorted(n for n in _MODEL_CFGS
                                        if n not in _HEAVY))
def test_model_zoo_green(name):
    _assert_model_green(name)


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(_HEAVY))
def test_model_zoo_green_heavy(name):
    _assert_model_green(name)


def _assert_model_green_post_pass(name):
    """The pass-pipeline extension of the zoo sweep: apply the TPU
    rewrite passes, then the full rule catalog over the REWRITTEN
    program must stay error-free (proglint green on every post-pass
    program — the 'every rewritten program re-verified' contract)."""
    from paddle_tpu import passes as tpu_passes
    kw = _MODEL_CFGS[name]
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 1
    with fluid.program_guard(main, startup):
        out = getattr(models, name).build(**kw)
    loss, fetches, specs = out[0], out[1] or [], out[2]
    fetch_names = [loss.name] + [getattr(f, "name", str(f))
                                 for f in fetches]
    tpu_passes.apply_pipeline(main, feed_names=sorted(specs),
                              fetch_names=fetch_names, verify=False)
    diags = analysis.analyze_program(main, feed_names=sorted(specs),
                                     fetch_names=fetch_names)
    errs = [d for d in diags if d.severity == Severity.ERROR]
    assert not errs, (name, [d.format() for d in errs])


@pytest.mark.parametrize("name", sorted(n for n in _MODEL_CFGS
                                        if n not in _HEAVY))
def test_model_zoo_green_post_pass(name):
    _assert_model_green_post_pass(name)


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(_HEAVY))
def test_model_zoo_green_post_pass_heavy(name):
    _assert_model_green_post_pass(name)


def test_book_program_green_word2vec():
    VOCAB, EMB = 20, 8
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 11
    with fluid.program_guard(main, startup):
        words = [layers.data(name=f"w{i}", shape=[1], dtype="int64")
                 for i in range(4)]
        target = layers.data(name="tgt", shape=[1], dtype="int64")
        embs = [layers.embedding(
            w, size=[VOCAB, EMB],
            param_attr=fluid.ParamAttr(name="shared_emb"))
            for w in words]
        hidden = layers.fc(layers.concat(embs, axis=1), size=16,
                           act="relu")
        pred = layers.fc(hidden, size=VOCAB, act="softmax")
        avg = layers.mean(layers.cross_entropy(input=pred, label=target))
        fluid.optimizer.Adam(learning_rate=1e-2).minimize(avg)
    feeds = [f"w{i}" for i in range(4)] + ["tgt"]
    diags = analysis.analyze_program(main, feed_names=feeds,
                                     fetch_names=[avg.name])
    errs = [d for d in diags if d.severity == Severity.ERROR]
    assert not errs, [d.format() for d in errs]


# -- proglint CLI ------------------------------------------------------------

def _proglint():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "proglint.py")
    spec = importlib.util.spec_from_file_location("proglint", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_proglint_saved_model_exit_codes(tmp_path, capsys):
    proglint = _proglint()
    # clean program -> 0
    desc = ir.ProgramDesc()
    b = desc.global_block
    b.add_var(ir.VarDesc(name="x", shape=[2, 2], dtype="float32"))
    b.add_var(ir.VarDesc(name="y", shape=[2, 2], dtype="float32"))
    b.append_op(ir.OpDesc(type="relu", inputs={"X": ["x"]},
                          outputs={"Out": ["y"]}))
    good = tmp_path / "good"
    good.mkdir()
    (good / "__model__.json").write_text(json.dumps(
        {"program": desc.to_dict(), "feed_names": ["x"],
         "fetch_names": ["y"]}))
    assert proglint.main([str(good)]) == 0

    # dangling input -> 1, diagnostic names rule + op
    desc.global_block.ops[0].inputs["X"] = ["missing"]
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "__model__.json").write_text(json.dumps(
        {"program": desc.to_dict(), "feed_names": ["x"],
         "fetch_names": ["y"]}))
    capsys.readouterr()
    assert proglint.main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "dangling-input" in out and "relu" in out

    # JSON output is machine-readable
    assert proglint.main([str(bad), "--json"]) == 1
    rec = json.loads(capsys.readouterr().out.splitlines()[0])
    assert rec["rule"] == "dangling-input"
    assert rec["severity"] == "error"


def test_proglint_list_rules(capsys):
    proglint = _proglint()
    assert proglint.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("dangling-input", "shape-mismatch", "dead-op",
                "waw-param", "rng-in-inference", "unknown-op"):
        assert rid in out


# -- cross-view program contracts (analysis/contracts.py) --------------------

def _decoder_family(modes):
    from paddle_tpu.models import transformer
    return transformer.build_decoder_lm_programs(
        prompt_len=8, max_new=8, vocab=32, d_model=16, d_inner=32,
        n_head=2, n_layer=2, prompt_buckets=(4, 8), n_slots=4, spec_k=3,
        modes=modes)


def test_contracts_full_family_green():
    """The contract the CI gate (proglint --contracts) enforces: the
    whole decoder_lm family — wave, slot, paged and verify views over
    every prompt bucket — passes every cross-view rule."""
    from paddle_tpu.models import transformer
    fam = transformer.contracts_lint_family()
    assert len(fam) == 15
    diags = analysis.verify_family(fam)
    assert diags == [], [d.format() for d in diags]


def test_contract_view_var_drift():
    fam = _decoder_family(("prefill", "decode"))
    fam["decode"][0].desc.global_block.vars["lm_emb"].shape = [33, 16]
    diags = analysis.verify_family(fam)
    assert [(d.rule, d.var) for d in diags] == \
        [("ctr-view-var-drift", "lm_emb")]
    assert diags[0].severity == Severity.ERROR
    assert "drifts across views" in diags[0].message


def test_contract_salt_misalignment():
    fam = _decoder_family(("prefill", "decode"))
    # shift every rng initializer of ONE view by one startup op index —
    # per-index salting means the views would initialize different
    # weights for the "shared" parameters
    ops = fam["decode"][1].desc.global_block.ops
    ops.insert(0, ops.pop())
    diags = analysis.verify_family(fam)
    assert diags and {d.rule for d in diags} == {"ctr-salt-misalignment"}
    assert any(d.var == "lm_emb" for d in diags)


def test_contract_stale_donation_read():
    fam = _decoder_family(("prefill", "decode"))
    # prefill demotes a KV cache that the decode view mutates in place:
    # prefill would then read a local temp, never the donated buffer
    fam["prefill"][0].desc.global_block.vars[
        "lm_cache_k_0"].persistable = False
    diags = analysis.verify_family(fam)
    assert [d.rule for d in diags] == ["ctr-stale-donation-read"]
    d = diags[0]
    assert d.var == "lm_cache_k_0"
    assert d.details["as"] == "a non-persistable temp"
    assert d.details["offending_view"].startswith("prefill")


def test_contract_geometry_drift():
    import dataclasses
    fam = _decoder_family(("prefill", "decode"))
    m = fam["decode"][0]
    m._geometry = dataclasses.replace(m._geometry, cache_len=32)
    diags = analysis.verify_family(fam)
    assert [(d.rule, d.var) for d in diags] == \
        [("ctr-geometry-drift", "cache_len")]


def test_validate_geometry_record():
    from paddle_tpu.analysis.contracts import validate_geometry
    g = validate_geometry("decode_verify_paged", 8, 8, n_slots=4,
                          spec_k=3)
    assert (g.cache_len, g.window, g.page_size) == (16, 4, 4)
    assert g.max_pages == 4 and g.n_pages == 4 * g.max_pages
    assert g.store_dtype == "float32"          # FLAGS default codec
    with pytest.raises(ValueError, match="needs n_slots"):
        validate_geometry("decode_slot", 8, 8)
    with pytest.raises(ValueError, match="must divide"):
        validate_geometry("prefill_paged", 8, 8, n_slots=4, page_size=3)
    with pytest.raises(ValueError, match="verify window"):
        validate_geometry("decode_verify", 8, 8, n_slots=4, spec_k=16)
    with pytest.raises(ValueError, match="not in"):
        validate_geometry("nope", 8, 8)
