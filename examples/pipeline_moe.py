"""Pipeline + expert parallelism from the Program API.

Builds a model whose middle section is a 2-stage fluid.layers.Pipeline
(GPipe over a `pp` mesh axis) feeding a switch mixture-of-experts FFN
(all-to-all over `ep`), trains it for a few steps, and shows the same
program running single-device (sequential lowering, identical math).

Run single-chip:            python examples/pipeline_moe.py
Run on an 8-device mesh:    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
                            PADDLE_TPU_EXAMPLE_MESH=1 python examples/pipeline_moe.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if os.environ.get("PADDLE_TPU_EXAMPLE_MESH"):
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

D = 32


def build():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 7
    startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[D], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        pipe = layers.Pipeline(n_stages=2, n_microbatches=4)
        with pipe.stage(x) as h:
            pipe.set_output(layers.fc(h, D, bias_attr=False, act="tanh"))
        moe_out, aux = layers.switch_moe(pipe.output, n_experts=4,
                                         d_ff=64, capacity_factor=2.0)
        pred = layers.fc(moe_out, 1, bias_attr=False)
        loss = layers.mean(layers.square(pred - y)) \
            + layers.mean(aux) * 0.01
        fluid.optimizer.Adam(learning_rate=3e-3).minimize(loss)
    return main, startup, loss


def main():
    prog, startup, loss = build()

    run_target = prog
    if os.environ.get("PADDLE_TPU_EXAMPLE_MESH"):
        from paddle_tpu.parallel import DistributeConfig, make_mesh
        mesh = make_mesh({"pp": 2, "ep": 2, "dp": 2})
        run_target = fluid.CompiledProgram(prog).with_sharding(
            DistributeConfig(mesh=mesh, data_axis=None, model_axis=None,
                             sp_axis=None, pp_axis="pp", ep_axis="ep"))
        print(f"mesh: {dict(mesh.shape)}")

    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    w = (np.random.RandomState(1).rand(D, 1) / D).astype(np.float32)
    for step in range(40):
        xb = rng.rand(16, D).astype(np.float32)
        (lv,) = exe.run(run_target, feed={"x": xb, "y": xb @ w},
                        fetch_list=[loss])
        if step % 10 == 0 or step == 39:
            print(f"step {step:2d}  loss {float(np.asarray(lv).reshape(())):.4f}")


if __name__ == "__main__":
    main()
