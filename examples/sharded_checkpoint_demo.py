"""Demo: sharded checkpoint + restore-with-resharding.

Run from the repo root:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/sharded_checkpoint_demo.py     # 8-device mesh demo
  python examples/sharded_checkpoint_demo.py         # single-chip (TPU)

Trains an MLP under dp=4 ZeRO (Adam moments sharded over the data axis,
the pserver's sharded-optimizer-state capability), saves only per-device
shards (no full gather), then restores bit-equal under dp=8 and keeps
training — the EDL mesh-reconfiguration loop."""

import os
import shutil
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
# the axon plugin overrides the JAX_PLATFORMS env var; the config API wins
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.core.lowering import CompiledBlock
from paddle_tpu.core.scope import Scope
from paddle_tpu.parallel.mesh import DistributeConfig, make_mesh


def build():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[16], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        pred = layers.fc(layers.fc(x, size=32, act="relu"), size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
    return main, startup, loss


def feeds(step):
    rng = np.random.RandomState(100 + step)
    x = rng.rand(8, 16).astype(np.float32)
    return {"x": x, "y": x.sum(1, keepdims=True) * 0.1}


def main():
    ndev = len(jax.devices())
    save_dp, restore_dp = (4, 8) if ndev >= 8 else (1, 1)
    prog, startup, loss = build()

    def dist(n):
        mesh = make_mesh({"dp": n}, devices=jax.devices()[:n])
        return DistributeConfig(mesh=mesh, data_axis="dp",
                                reduce_strategy="reduce_scatter")

    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    cp = fluid.CompiledProgram(prog).with_sharding(dist(save_dp))
    for s in range(5):
        (lv,) = exe.run(cp, feed=feeds(s), fetch_list=[loss.name],
                        scope=scope)
    print(f"trained 5 steps dp={save_dp} ZeRO, loss "
          f"{float(np.asarray(lv).reshape(())):.4f}")

    names = [vd.name for vd in prog.desc.global_block.vars.values()
             if vd.persistable]
    want = {n: np.asarray(scope.find_var(n)) for n in names}

    d = tempfile.mkdtemp(prefix="sharded_ckpt_")
    try:
        fluid.io.save_vars(None, d, prog, scope=scope, sharded=True)
        shard_files = [f for f in os.listdir(d) if ".s" in f]
        print(f"saved {len(shard_files)} shard files for {len(names)} vars "
              f"(dp={save_dp} writes moments as {save_dp} shards each)")

        scope2 = Scope()
        cb = CompiledBlock(prog.desc, 0, ["x", "y"], [loss.name],
                           dist=dist(restore_dp))
        fluid.io.load_vars(None, d, prog, scope=scope2,
                           sharding_fn=cb.param_sharding)
        ok = all(np.array_equal(np.asarray(scope2.find_var(n)), want[n])
                 for n in names)
        print(f"restore under dp={restore_dp}: bit-equal={ok}")

        cp2 = fluid.CompiledProgram(prog).with_sharding(dist(restore_dp))
        for s in range(5, 10):
            (lv,) = exe.run(cp2, feed=feeds(s), fetch_list=[loss.name],
                            scope=scope2)
        print(f"resumed training dp={restore_dp}, loss "
              f"{float(np.asarray(lv).reshape(())):.4f}")
        print("SHARDED CHECKPOINT:", "OK" if ok else "FAILED")
        return 0 if ok else 1
    finally:
        shutil.rmtree(d, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
