"""LeNet-ish conv net on mnist (reference: book test_recognize_digits.py)."""
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import paddle_tpu
import paddle_tpu.fluid as fluid
from paddle_tpu import dataset


def main():
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        img = fluid.layers.data(name="img", shape=[1, 28, 28],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        c1 = fluid.layers.conv2d(img, 16, 5, padding=2, act="relu")
        p1 = fluid.layers.pool2d(c1, 2, pool_stride=2)
        c2 = fluid.layers.conv2d(p1, 32, 5, padding=2, act="relu")
        p2 = fluid.layers.pool2d(c2, 2, pool_stride=2)
        logits = fluid.layers.fc(fluid.layers.flatten(p2), 10)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)

    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    reader = paddle_tpu.batch(dataset.mnist.train(), batch_size=128)
    for epoch in range(2):
        accs = []
        for batch in reader():
            xs = np.asarray([b[0] for b in batch],
                            np.float32).reshape(-1, 1, 28, 28)
            ys = np.asarray([b[1] for b in batch],
                            np.int64).reshape(-1, 1)
            _, a = exe.run(main_p, feed={"img": xs, "label": ys},
                           fetch_list=[loss.name, acc.name])
            accs.append(float(np.asarray(a).reshape(())))
        print(f"epoch {epoch}: acc {np.mean(accs):.3f}")


if __name__ == "__main__":
    main()
