"""ResNet on cifar-10 with the TPU speed path (reference: book
test_image_classification.py). Demonstrates the two performance
transpilers: bf16 AMP (fp32 master weights) and the NHWC channels-last
layout rewrite — both attr-only, both applied after minimize()."""
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import paddle_tpu
import paddle_tpu.fluid as fluid
from paddle_tpu import dataset
from paddle_tpu.contrib.layout import rewrite_program_nhwc
from paddle_tpu.contrib.mixed_precision import rewrite_program_amp
from paddle_tpu.models.resnet import resnet

BATCH = 128


def main():
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        img = fluid.layers.data(name="img", shape=[3, 32, 32],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        logits = resnet(img, class_dim=10, depth=50)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        acc = fluid.layers.accuracy(input=fluid.layers.softmax(logits),
                                    label=label)
        fluid.optimizer.Momentum(learning_rate=0.01,
                                 momentum=0.9).minimize(loss)
        rewrite_program_amp(main_p)     # bf16 MXU compute
        rewrite_program_nhwc(main_p)    # channels-last residency

    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)

    reader = paddle_tpu.batch(dataset.cifar.train10(), batch_size=BATCH,
                              drop_last=True)
    for epoch in range(5):
        losses, accs = [], []
        for batch in reader():
            xs = np.asarray([b[0] for b in batch], np.float32).reshape(
                -1, 3, 32, 32)
            ys = np.asarray([b[1] for b in batch], np.int64).reshape(-1, 1)
            lv, av = exe.run(main_p, feed={"img": xs, "label": ys},
                             fetch_list=[loss.name, acc.name])
            losses.append(float(np.asarray(lv).reshape(())))
            accs.append(float(np.asarray(av).reshape(())))
        print(f"epoch {epoch}: loss {np.mean(losses):.4f} "
              f"acc {np.mean(accs):.3f}")


if __name__ == "__main__":
    main()
