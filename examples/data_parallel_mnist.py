"""Data-parallel training over every local chip (reference: the
ParallelExecutor/CompiledProgram.with_data_parallel book usage)."""
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import paddle_tpu.fluid as fluid
from paddle_tpu.parallel import DistributeConfig, make_mesh


def main():
    n = len(jax.devices())
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        img = fluid.layers.data(name="img", shape=[784], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        h = fluid.layers.fc(img, 200, act="relu")
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            fluid.layers.fc(h, 10), label))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)

    mesh = make_mesh({"dp": n})
    compiled = fluid.CompiledProgram(main_p).with_sharding(
        DistributeConfig(mesh=mesh, data_axis="dp"))

    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    W = rng.rand(784, 10)
    bs = 64 * n
    for step in range(60):
        xs = rng.rand(bs, 784).astype(np.float32)
        ys = np.argmax(xs @ W, axis=1).astype(np.int64).reshape(-1, 1)
        (lv,) = exe.run(compiled, feed={"img": xs, "label": ys},
                        fetch_list=[loss.name])
        if step % 20 == 0:
            print(f"step {step}: loss {float(np.asarray(lv)):.4f} "
                  f"({n} chip(s), global bs {bs})")


if __name__ == "__main__":
    main()
