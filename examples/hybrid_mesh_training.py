"""Multi-slice hybrid-mesh training: dp over DCN (outermost), tp over
ICI — the tier split declared in the mesh itself (docs/distributed.md).
Runs on an 8-device virtual CPU mesh so it works without multi-slice
hardware:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/hybrid_mesh_training.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8").strip()

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.parallel import DistributeConfig, make_hybrid_mesh


def main():
    mesh = make_hybrid_mesh({"tp": 4}, {"dp": 2})
    print("mesh:", dict(mesh.shape), "axes:", mesh.axis_names)

    x = layers.data("x", shape=[32], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    h = layers.fc(x, 64, act="relu",
                  param_attr=fluid.ParamAttr(name="w1"))
    pred = layers.fc(h, 1)
    loss = layers.mean(layers.square(pred - y))
    fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)

    # w1 column-parallel on the ICI axis; batch sharded on the DCN axis
    dist = DistributeConfig(mesh=mesh, data_axis="dp", model_axis="tp",
                            param_axes={"w1": (None, "tp")})
    compiled = fluid.CompiledProgram(
        fluid.default_main_program()).with_sharding(dist)

    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    wt = rng.randn(32, 1).astype("float32")
    for step in range(40):
        xb = rng.randn(16, 32).astype("float32")
        (lv,) = exe.run(compiled, feed={"x": xb, "y": xb @ wt},
                        fetch_list=[loss])
        if step % 10 == 0 or step == 39:
            print(f"step {step:2d} loss {float(np.asarray(lv)):.4f}")


if __name__ == "__main__":
    main()
