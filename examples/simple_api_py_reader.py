"""The fluid "simple API" + py_reader pipeline — the reference's
book-notebook workflow (contrib.Trainer / contrib.Inferencer) combined
with the in-graph reader protocol (py_reader → read_file → run without
feed → EOFException at epoch end).

Run from the repo root: python examples/simple_api_py_reader.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu
import paddle_tpu.fluid as fluid
from paddle_tpu import contrib
from paddle_tpu.fluid import layers


def main():
    # ---- part 1: the py_reader epoch loop (reference layers/io.py) ----
    reader = layers.py_reader(capacity=16, shapes=[(-1, 8), (-1, 1)],
                              dtypes=["float32", "float32"])
    x, y = layers.read_file(reader)
    pred = layers.fc(x, 1)
    loss = layers.mean(layers.square(pred - y))
    fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)

    rng = np.random.RandomState(0)
    w_true = rng.rand(8, 1).astype("float32")

    def batches():
        r = np.random.RandomState(1)
        for _ in range(16):
            xb = r.rand(32, 8).astype("float32")
            yield (xb, xb @ w_true)

    reader.decorate_paddle_reader(batches)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(fluid.default_startup_program())
    for epoch in range(3):
        reader.start()
        losses = []
        while True:
            try:
                (lv,) = exe.run(fluid.default_main_program(),
                                fetch_list=[loss])
                losses.append(float(np.asarray(lv).reshape(())))
            except fluid.core.EOFException:
                reader.reset()
                break
        print(f"[py_reader] epoch {epoch}: mean loss "
              f"{np.mean(losses):.5f}")

    # ---- part 2: the contrib simple API ------------------------------
    def train_func():
        xv = layers.data("sx", shape=[8], dtype="float32")
        yv = layers.data("sy", shape=[1], dtype="float32")
        p = layers.fc(xv, 1, name="simple_fc")
        return layers.mean(layers.square(p - yv))

    trainer = contrib.Trainer(train_func,
                              lambda: fluid.optimizer.SGD(
                                  learning_rate=0.05))

    def data_reader():
        r = np.random.RandomState(2)
        for _ in range(32):
            xb = r.rand(32, 8).astype("float32")
            yield {"sx": xb, "sy": xb @ w_true}

    def handler(ev):
        if isinstance(ev, contrib.high_level.EndEpochEvent):
            print(f"[simple API] epoch {ev.epoch} done")

    trainer.train(num_epochs=2, event_handler=handler, reader=data_reader)
    trainer.save_params("/tmp/simple_api_params")

    def infer_func():
        xv = layers.data("sx", shape=[8], dtype="float32")
        return layers.fc(xv, 1, name="simple_fc")

    inf = contrib.Inferencer(infer_func, "/tmp/simple_api_params")
    (out,) = inf.infer({"sx": np.ones((2, 8), np.float32)})
    print("[simple API] inferred:", np.asarray(out).reshape(-1))


if __name__ == "__main__":
    main()
