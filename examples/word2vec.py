"""N-gram word embedding model on imikolov (reference: book
test_word2vec.py — 4 context embeddings with a shared table -> fc ->
softmax cross-entropy)."""
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import paddle_tpu
import paddle_tpu.fluid as fluid
from paddle_tpu import dataset

N = 5           # 4 context words predict the 5th
EMB = 32
BATCH = 64


def main():
    word_dict = dataset.imikolov.build_dict()
    vocab = len(word_dict)

    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        words = [fluid.layers.data(name=f"w{i}", shape=[1], dtype="int64")
                 for i in range(N - 1)]
        target = fluid.layers.data(name="target", shape=[1], dtype="int64")
        embs = [fluid.layers.embedding(
                    w, size=[vocab, EMB],
                    param_attr=fluid.ParamAttr(name="shared_emb"))
                for w in words]
        hidden = fluid.layers.fc(fluid.layers.concat(embs, axis=1),
                                 size=128, act="relu")
        pred = fluid.layers.fc(hidden, size=vocab, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=target))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)

    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)

    reader = paddle_tpu.batch(
        dataset.imikolov.train(word_dict, N), batch_size=BATCH,
        drop_last=True)
    for epoch in range(2):
        costs = []
        for batch in reader():
            grams = np.asarray(batch, np.int64)      # [B, 5]
            feed = {f"w{i}": grams[:, i:i + 1] for i in range(N - 1)}
            feed["target"] = grams[:, N - 1:N]
            (c,) = exe.run(main_p, feed=feed, fetch_list=[loss.name])
            costs.append(float(np.asarray(c).reshape(())))
        print(f"epoch {epoch}: ce {np.mean(costs):.4f}")

    # nearest neighbours in the learned embedding space
    emb_table = np.asarray(fluid.global_scope().find_var("shared_emb"))
    q = emb_table[1]
    sims = emb_table @ q / (np.linalg.norm(emb_table, axis=1)
                            * np.linalg.norm(q) + 1e-9)
    print("nearest to token 1:", np.argsort(-sims)[:5].tolist())


if __name__ == "__main__":
    main()
