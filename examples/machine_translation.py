"""Seq2seq with attention + beam-search generation (reference: book
test_machine_translation.py — the RecurrentGradientMachine capability)."""
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import paddle_tpu.fluid as fluid
from paddle_tpu import models


def main():
    vocab = 100
    # train a few steps on the synthetic reversed-sequence task
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = 1
    with fluid.program_guard(main_p, startup):
        loss, fetches, feed_specs = models.machine_translation.build(
            is_train=True, src_vocab=vocab, tgt_vocab=vocab, max_len=8)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    from paddle_tpu import dataset
    print("feeds:", sorted(feed_specs))
    cols = {}
    for s, t, tn in dataset.wmt14.train(vocab)():
        cols.setdefault("src", []).append((s + [1] * 8)[:8])
        cols.setdefault("tgt", []).append((t + [1] * 8)[:8])
        cols.setdefault("tgt_next", []).append((tn + [1] * 8)[:8])
        if len(cols["src"]) == 16:
            break
    col_for_feed = {"src": "src", "tgt_in": "tgt", "tgt_out": "tgt_next"}
    for step in range(30):
        feed = {}
        for name, (shape, dtype) in feed_specs.items():
            feed[name] = np.asarray(cols[col_for_feed[name]], dtype)
        (lv,) = exe.run(main_p, feed=feed, fetch_list=[loss.name])
        if step % 10 == 0:
            print(f"step {step}: loss {float(np.asarray(lv)):.4f}")

    # beam-search generation program
    gen_p, gen_start = fluid.Program(), fluid.Program()
    gen_p.random_seed = 1
    with fluid.program_guard(gen_p, gen_start):
        # wmt14 framing: START=0, END=1 (dataset/wmt14.py)
        models.machine_translation.build(
            is_train=False, src_vocab=vocab, tgt_vocab=vocab, max_len=8,
            beam_size=4, start_id=0, end_id=1)
    print("built beam-search generation program "
          f"({len(gen_p.desc.global_block.ops)} ops)")


if __name__ == "__main__":
    main()
