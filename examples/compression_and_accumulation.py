"""Round-2 feature tour: multi-step device execution, k-step gradient
accumulation (multi_batch_merge capability), magnitude pruning under the
slim Compressor, and per-op device-time attribution.

Run: python examples/compression_and_accumulation.py
"""
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tempfile

import numpy as np
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, profiler
from paddle_tpu.contrib import slim


def build():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 1
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[256], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        h = layers.fc(x, size=512, act="relu",
                      param_attr=fluid.ParamAttr(name="w1"))
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.Momentum(learning_rate=0.02,
                                 momentum=0.9).minimize(loss)
    return main, startup, loss


def batch(rng, bs=128):
    x = rng.rand(bs, 256).astype(np.float32)
    return {"x": x, "y": (x.sum(1, keepdims=True) * 0.1).astype(np.float32)}


def main():
    rng = np.random.RandomState(0)
    main_p, startup, loss = build()

    # k=4 gradient accumulation: the optimizer applies every 4th step on
    # the 4-step mean gradient (effective batch 512 from bs128 feeds)
    fluid.apply_batch_merge(main_p, startup, 4)

    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)

    # 64 micro-steps in ONE device-side dispatch (16 optimizer applies)
    (losses,) = exe.run(main_p, feed=batch(rng), fetch_list=[loss],
                        iterations=64)
    print(f"accumulated training: loss {losses[0]:.4f} -> {losses[-1]:.4f}")

    # prune w1 to 50% sparsity and keep training under the Compressor
    strategy = slim.PruneStrategy(slim.RatioPruner({"*": 0.5}),
                                  params=["w1"], end_epoch=2)
    comp = slim.Compressor(place=fluid.TPUPlace(),
                           reader=lambda: (batch(rng) for _ in range(8)),
                           epoch=2).add_strategy(strategy)
    comp.run(main_p, fetch_list=[loss])
    from paddle_tpu.core.scope import global_scope
    w = np.asarray(global_scope().find_var("w1"))
    print(f"sparsity after pruned training: {(w == 0).mean():.2f}")

    # attribute device time per HLO op for one 32-step window
    trace = tempfile.mkdtemp()
    profiler.start_profiler(trace_dir=trace)
    exe.run(main_p, feed=batch(rng), fetch_list=[loss], iterations=32)
    profiler.stop_profiler(trace_dir=trace)
    profiler.print_device_op_stats(trace, top=8)


if __name__ == "__main__":
    main()
