"""Demo: elastic multi-worker data draining via the shared chunk-lease
master service (reference capability: go/master — EDL trainers share one
etcd-backed task queue; a dead trainer's leases time out and re-issue).

Run from the repo root:  python examples/elastic_master_demo.py

Rank 0 (this process) partitions a RecordIO dataset into chunk tasks and
serves them over JSON/TCP; 3 worker processes drain the queue through
MasterClient; worker 0 is told to die abruptly on its first lease. The
lease times out, the chunk re-issues, and the run ends with every chunk
trained exactly once."""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from paddle_tpu import recordio
from paddle_tpu.data.master import Master
from paddle_tpu.data.master_service import MASTER_ENV, MasterServer


def main():
    work = tempfile.mkdtemp(prefix="elastic_demo_")
    try:
        paths = []
        expected = 0
        for f in range(3):
            p = os.path.join(work, f"part-{f:03d}.recordio")
            with recordio.Writer(p, max_chunk_records=4) as w:
                for c in range(3):
                    for r in range(4):
                        w.write(f"f{f}c{c}r{r}".encode())
                        expected += 1
            paths.append(p)

        master = Master(timeout_s=1.5, failure_max=5)
        master.set_dataset(paths, chunks_per_task=1)
        srv = MasterServer(master)
        print(f"master serving {master.stats()['todo']} chunk tasks "
              f"at {srv.endpoint}")

        bdir = os.path.join(work, "barrier")
        os.makedirs(bdir)
        workers = []
        for i in range(3):
            env = dict(os.environ)
            env[MASTER_ENV] = srv.endpoint
            env["MASTER_BARRIER_DIR"] = bdir
            env["TRAIN_SLEEP"] = "0.1"
            if i == 0:
                env["DIE_AFTER_LEASES"] = "1"   # the victim
            workers.append(subprocess.Popen(
                [sys.executable, "tests/master_worker.py"],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env=env))
        deadline = time.time() + 90
        while len([f for f in os.listdir(bdir)
                   if f.startswith("ready_")]) < 3:
            if time.time() > deadline:
                for w in workers:
                    w.kill()
                    print("worker stderr:", w.communicate()[1][-2000:])
                raise RuntimeError("workers never reached start barrier")
            time.sleep(0.05)
        open(os.path.join(bdir, "go"), "w").close()
        t0 = time.time()

        n_records = 0
        completed = []
        for i, w in enumerate(workers):
            out, err = w.communicate(timeout=120)
            if i == 0:
                print(f"worker 0 (victim) exited rc={w.returncode} "
                      "mid-lease, unreported")
            else:
                res = json.loads(out.strip().splitlines()[-1])
                print(f"worker {i} completed {len(res['completed'])} tasks, "
                      f"{len(res['records'])} records")
                n_records += len(res["records"])
                completed += [tuple(t[1:]) for t in res["completed"]]
        srv.stop()

        s = master.stats()
        uniq = len(set(completed))
        print(f"drained in {time.time() - t0:.1f}s; master stats: {s}")
        print(f"chunks completed {len(completed)} (unique {uniq}), "
              f"records trained {n_records}/{expected}")
        ok = (uniq == len(completed) == s["done"]
              and n_records == expected and s["dropped"] == 0)
        print("ELASTIC DRAIN:", "OK — every chunk trained exactly once"
              if ok else "FAILED")
        return 0 if ok else 1
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
