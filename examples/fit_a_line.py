"""Linear regression on uci_housing (reference: book test_fit_a_line.py)."""
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import paddle_tpu
import paddle_tpu.fluid as fluid
from paddle_tpu import dataset


def main():
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)

    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    reader = paddle_tpu.batch(dataset.uci_housing.train(), batch_size=32)
    for epoch in range(5):
        costs = []
        for batch in reader():
            xs = np.asarray([b[0] for b in batch], np.float32)
            ys = np.asarray([b[1] for b in batch], np.float32).reshape(-1, 1)
            (c,) = exe.run(main_p, feed={"x": xs, "y": ys},
                           fetch_list=[loss.name])
            costs.append(float(np.asarray(c).reshape(())))
        print(f"epoch {epoch}: cost {np.mean(costs):.4f}")

    # save → load → infer round trip (reference: save_inference_model /
    # load_inference_model book pattern)
    fluid.io.save_inference_model("/tmp/fit_a_line_model", ["x"], [pred],
                                  exe, main_program=main_p)
    scope = fluid.Scope()
    infer_prog, feed_names, fetch_names = fluid.io.load_inference_model(
        "/tmp/fit_a_line_model", exe, scope=scope)
    sample = np.asarray(next(dataset.uci_housing.test()())[0],
                        np.float32).reshape(1, 13)
    (out,) = exe.run(infer_prog, feed={feed_names[0]: sample},
                     fetch_list=fetch_names, scope=scope)
    print(f"reloaded model prediction: {float(np.asarray(out).reshape(())):.3f}")


if __name__ == "__main__":
    main()
