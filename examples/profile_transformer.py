"""Device-time attribution for the transformer bench config — the
docs/performance.md accounting loop. Run from repo root on TPU:
    python examples/profile_transformer.py [--max-len 64] [--top 25]
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-len", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--steps", type=int, default=32)
    ap.add_argument("--no-fused", action="store_true")
    args = ap.parse_args()

    import paddle_tpu.fluid as fluid
    from paddle_tpu import models
    from paddle_tpu.contrib.mixed_precision import rewrite_program_amp
    from paddle_tpu.fluid import profiler

    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = 1
    with fluid.program_guard(main_p, startup):
        loss, _, feed_specs = models.transformer.build(
            is_train=True, src_vocab=32000, tgt_vocab=32000,
            max_len=args.max_len, fused_attention=not args.no_fused)
        rewrite_program_amp(main_p)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)

    rng = np.random.RandomState(0)
    B = args.batch_size
    feed = {n: rng.randint(1, 31999, [B if d == -1 else d for d in sh])
            .astype("int64") for n, (sh, dt) in feed_specs.items()}
    feeds = [feed] * args.steps

    # warm up twice (multi-step recompile on 2nd call — SKILL.md)
    for _ in range(2):
        (lv,) = exe.run(main_p, feed=feeds, fetch_list=[loss.name],
                        iterations=args.steps,
                        stacked_feed=list(feed_specs))
        float(np.asarray(lv).reshape(-1)[-1])

    import time
    trace_dir = tempfile.mkdtemp(prefix="tf_trace_")
    profiler.start_profiler(trace_dir=trace_dir)
    t0 = time.perf_counter()
    (lv,) = exe.run(main_p, feed=feeds, fetch_list=[loss.name],
                    iterations=args.steps, stacked_feed=list(feed_specs))
    float(np.asarray(lv).reshape(-1)[-1])
    dt = time.perf_counter() - t0
    profiler.stop_profiler(trace_dir=trace_dir)

    # bench.py convention: tokens/step = batch * max_len (single-sided)
    toks = B * args.max_len * args.steps
    print(f"\n== {args.steps} steps in {dt:.3f}s = "
          f"{dt / args.steps * 1e3:.2f} ms/step, "
          f"{toks / dt:,.0f} tokens/sec ==\n")
    profiler.print_device_op_stats(trace_dir, top=args.top)


if __name__ == "__main__":
    main()
