"""Benchmark harness entry point.

Mirrors the reference's fluid_benchmark CLI capability
(reference: benchmark/fluid/fluid_benchmark.py:139 train_parallel — reports
images/sec or words/sec averaged over steps) on TPU.

DEFAULT (no --model): the FULL sweep — one JSON line per model row (14
train + 3 infer + 1 serving cold-start) as each finishes, then one
compact aggregate JSON line
{"metric": "full sweep ...", "value": <headline resnet50 img/s>,
 "unit": ..., "vs_baseline": N, "mfu_pct": N, "rows": [...]}
whose rows[] carry the whole table with mfu_pct filled per row.
`--model X` runs one row; `--headline` is the resnet50-only shortcut.

Headline config: ResNet-50 train bs=128 amp-bf16 nhwc — the BASELINE.md
north-star row (ResNet-50 MFU on v5e). vs_baseline is img/s over the
reference's published 2S-Xeon MKL number (81.69 img/s,
IntelOptimizedPaddle.md:39-46). mfu_pct uses analytic model FLOPs at
2 FLOPs/MAC with backward = 2x forward (paddle_tpu/utils/flops.py) over
the chip's peak bf16 FLOP/s; while/scan sub-blocks count body x trips.

Timing runs device-side: exe.run(..., iterations=chunk) scans the whole
training step in one dispatch (core/lowering.py run_steps), so host/tunnel
dispatch cost — which scales with the number of parameter buffers — is
excluded by construction, and the numbers are stable run to run.

Run: python bench.py [--model resnet50|alexnet|transformer|...]
                     [--batch-size N] [--steps CHUNK]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np


METRICS_SNAPSHOT_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "bench_metrics.json")


def _write_metrics_snapshot(model_name: str, kind: str, nsteps: int,
                            dt: float, examples_per_step, tokens_per_step,
                            mfu, flops_per_step=None, passes=None):
    """Observability satellite: publish the measured window into the
    runtime gauges (steps/s, examples/s, tokens/s, MFU) and merge the
    full registry dump into bench_metrics.json next to this script —
    every bench row leaves a telemetry snapshot alongside the
    BENCH_*.json result, so future rounds read counters (retries,
    checkpoint CRCs, queue stalls) without re-running anything."""
    try:
        from paddle_tpu.observability import metrics as obs_metrics
        from paddle_tpu.observability import runtime as obs_runtime
        # rates computed from the measured window directly (NOT through
        # StepStats.record: with observability flags on the executor
        # already counted these steps into paddle_steps_total, and the
        # process-default ring holds warmup/compile samples). The
        # throughput/MFU gauges are set to the window's values so the
        # registry dump below carries them.
        if mfu is None and flops_per_step:
            # off-TPU the spec-sheet lookup knows no peak, but the
            # FLAGS_peak_flops override (runtime.mfu_ratio honors it)
            # still yields a real MFU — same contract as steps.jsonl
            mfu = obs_runtime.mfu_ratio(flops_per_step,
                                        dt / max(nsteps, 1))
        steps_per_s = nsteps / dt if dt > 0 else 0.0
        obs_runtime.STEP_TIME.set(dt / max(nsteps, 1))
        obs_runtime.STEPS_PER_S.set(steps_per_s)
        obs_runtime.EXAMPLES_PER_S.set(
            (examples_per_step or 0) * steps_per_s)
        obs_runtime.TOKENS_PER_S.set((tokens_per_step or 0) * steps_per_s)
        if mfu is not None:
            obs_runtime.MFU.set(mfu)
        try:
            with open(METRICS_SNAPSHOT_PATH) as f:
                merged = json.load(f)
        except (OSError, ValueError):
            merged = {}
        # which IR passes fired for this row, and whether the autotune
        # cache served the build deterministically (hit/miss counters;
        # zero measurements is the CI contract — passes/autotune.py)
        from paddle_tpu.passes import autotune as _autotune
        merged[f"{model_name}-{kind}"] = {
            "steps_per_s": round(steps_per_s, 4),
            "examples_per_s": round(
                (examples_per_step or 0) * steps_per_s, 2),
            "tokens_per_s": round(
                (tokens_per_step or 0) * steps_per_s, 2),
            "mfu": mfu,
            "passes": list(passes or []),
            "autotune_lookups": _autotune.lookup_counts(),
            "autotune_measurements": _autotune.measurement_count(),
            "registry": obs_metrics.default_registry().snapshot(),
        }
        # HBM picture at snapshot time (compiled gauges + census live in
        # the registry dump above; this block adds the structured
        # top-buffers/watermark view the memdump and /memory route share)
        try:
            from paddle_tpu.observability import memory as obs_mem
            merged[f"{model_name}-{kind}"]["memory"] = obs_mem.dump_section()
        except Exception:
            pass
        tmp = METRICS_SNAPSHOT_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(merged, f, indent=1, sort_keys=True)
        os.replace(tmp, METRICS_SNAPSHOT_PATH)
    except Exception:
        pass    # telemetry must never fail a bench row


ALEXNET_K40M_IMG_S = 425.0      # benchmark/README.md:33-38, bs256
VGG19_XEON_IMG_S = 28.46        # IntelOptimizedPaddle.md:29-36, bs64
                                # (our model is VGG16 — ~18% fewer FLOPs;
                                # treat vs_baseline as indicative only)

DEFAULT_BATCH_SIZES = {"alexnet": 256, "resnet50": 128,
                       "transformer": 32, "transformer_long": 2,
                       "transformer_big": 16,
                       "mnist": 2048, "stacked_dynamic_lstm": 64,
                       "vgg": 64, "se_resnext": 64,
                       "machine_translation": 64,
                       "deepfm": 2048, "googlenet": 128, "smallnet": 512,
                       "roofline_probe": 8192}
RESNET50_XEON_IMG_S = 81.69     # IntelOptimizedPaddle.md:39-46, bs64
GOOGLENET_K40M_IMG_S = 128 / 1.149   # benchmark/README.md:44-49, bs128
                                     # 1149 ms/batch → ~111.4 img/s
SMALLNET_K40M_IMG_S = 512 / 0.063039  # benchmark/README.md:52-57, bs512
                                      # 63.039 ms/batch → ~8122 img/s


# device-side steps per dispatch (exe.run iterations=N): sized so one
# chunk runs ~1-2s on a v5e chip — the per-dispatch host/tunnel cost
# (~0.3 ms per param buffer) disappears into the chunk
DEFAULT_CHUNKS = {"alexnet": 128, "resnet50": 32, "transformer": 32,
                  "transformer_big": 16,
                  "transformer_long": 32, "mnist": 512,
                  "stacked_dynamic_lstm": 128, "vgg": 16, "se_resnext": 32,
                  "machine_translation": 128, "deepfm": 512,
                  # googlenet: XLA's compile of LONG scans over the
                  # inception graph is pathological (>18 min at 64);
                  # 8 compiles in ~30 s and the window still spans 64+
                  # device steps
                  "googlenet": 8, "smallnet": 512, "roofline_probe": 16}


def _time_chunks(run_chunk, fence, min_seconds=3.0, min_chunks=2,
                 max_chunks=8, warmup=2):
    """Time repeated multi-step chunks. `run_chunk()` dispatches one chunk
    of device-side steps and returns a handle; `fence(handle)` forces the
    result back to the host (block_until_ready is a no-op on the axon
    platform, so a small D2H fetch is the only fence). Chunks repeat until
    the window exceeds `min_seconds` or `max_chunks` — dispatch is async,
    so the wall clock alone would let a cheap-dispatch model enqueue an
    unbounded backlog that the single closing fence must drain; the chunk
    cap bounds that. The fence is paid once per WINDOW, so no fence-cost
    subtraction/clamp is needed (round-1 advisor finding on the old
    hardcoded 0.105 s clamp). Returns (n_chunks, seconds, fenced value)."""
    # ≥2 fenced warmup chunks: the first compiles against the startup
    # arrays' layouts; its outputs can carry different XLA layouts, so the
    # second call may specialize (recompile) once more — both must finish
    # before the window opens or a ~20s compile lands inside the timing
    for _ in range(max(2, warmup)):
        fence(run_chunk())
    t0 = time.time()
    n = 0
    last = None
    while (n < min_chunks
           or (time.time() - t0 < min_seconds and n < max_chunks)):
        last = run_chunk()
        n += 1
    val = fence(last)
    return n, time.time() - t0, val


def _device_batch(exe, feed_specs, batch_size, seed=0, int_ranges=None,
                  stack_int=0):
    """Synthetic device-resident batch. stack_int > 0 gives every INT
    feed a leading [stack_int] axis with DISTINCT values per step (fed
    via exe.run(stacked_feed=[names])): a resident batch with fixed
    labels gets memorized within ~60 steps and the loss hits exact 0 →
    log(0) blowups in bf16; fresh labels/ids per scan step keep the
    measurement honest at negligible cost (int feeds are small)."""
    import jax
    rng = np.random.RandomState(seed)
    feeds = {}
    for name, (shape, dtype) in feed_specs.items():
        shape = [batch_size if d == -1 else d for d in shape]
        if dtype.startswith("int"):
            lo, hi = (int_ranges or {}).get(name, (0, 10))
            if stack_int:
                shape = [stack_int] + shape
            arr = rng.randint(lo, hi, size=shape).astype(dtype)
        else:
            arr = rng.rand(*shape).astype(dtype)
        feeds[name] = jax.device_put(arr, exe.device)
    return feeds


def _apply_tpu_passes(program, model_name, batch_size, passes_spec,
                      is_test, feed_names, fetch_names, scope=None):
    """Apply the IR-pass pipeline to a bench program BEFORE the amp/nhwc
    attr rewrites (so they tag the fused ops). `passes_spec` is None
    (committed per-model winner from the autotune table, or the
    defaults), "none" (control arm — zero passes), or a comma list of
    explicit pass names. Returns the applied names; the rewritten
    program was re-verified by paddle_tpu.analysis."""
    if passes_spec == "none":
        return []
    from paddle_tpu import passes as tpu_passes
    names = [p for p in passes_spec.split(",") if p] if passes_spec \
        else None
    return tpu_passes.apply_pipeline(
        program, scope=scope, names=names,
        model=None if names else model_name,
        batch_size=batch_size, is_test=is_test,
        feed_names=feed_names, fetch_names=fetch_names)


def run_bench(model_name: str, batch_size: int, steps: int, warmup: int = 5,
              amp: bool = False, mesh=None, nhwc: bool = True,
              batch_merge: int = 0, passes_spec: str = None):
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    builders = {
        "alexnet": (models.alexnet.build, {}, "images/sec",
                    ALEXNET_K40M_IMG_S),
        "resnet50": (models.resnet.build, {}, "images/sec",
                     RESNET50_XEON_IMG_S),
        "mnist": (models.mnist.build, {}, "images/sec", None),
        # T=256: the realistic Transformer-base WMT sequence length
        # (round-3 verdict: T=64 was a toy config that inflated tok/s and
        # understated attention cost); bs32 keeps tokens/step at 8192
        "transformer": (models.transformer.build,
                        {"max_len": 256, "src_vocab": 32000,
                         "tgt_vocab": 32000, "fused_attention": True},
                        "tokens/sec", None),
        # long-context config: d_head 128 routes attention through the
        # Pallas flash kernels (fwd + blockwise bwd)
        # the MFU-ceiling demonstrator (round-3 verdict item 3): an
        # arithmetic intensity that clears the v5e ridge (~240 FLOP/byte)
        # — d_model 1024 / d_inner 4096 / T 512, fused attention block +
        # fused-CE head, h=8 so d_head=128 fills the MXU lanes
        "transformer_big": (models.transformer.build,
                            {"max_len": 512, "src_vocab": 32000,
                             "tgt_vocab": 32000, "d_model": 1024,
                             "d_inner": 4096, "n_head": 8, "n_layer": 6,
                             "fused_attention": True, "fused_head": True},
                            "tokens/sec", None),
        "transformer_long": (models.transformer.build,
                             {"max_len": 2048, "src_vocab": 8000,
                              "tgt_vocab": 8000, "d_model": 1024,
                              "d_inner": 2048, "n_head": 8, "n_layer": 2,
                              "fused_attention": True},
                             "tokens/sec", None),
        "stacked_dynamic_lstm": (models.stacked_dynamic_lstm.build,
                                 {"max_len": 100}, "words/sec", None),
        "vgg": (models.vgg.build, {}, "images/sec", VGG19_XEON_IMG_S),
        "se_resnext": (models.se_resnext.build, {}, "images/sec", None),
        "machine_translation": (models.machine_translation.build,
                                {"src_vocab": 10000, "tgt_vocab": 10000,
                                 "max_len": 32}, "words/sec", None),
        "deepfm": (models.deepfm.build, {}, "examples/sec", None),
        "googlenet": (models.googlenet.build, {}, "images/sec",
                      GOOGLENET_K40M_IMG_S),
        "smallnet": (models.smallnet.build, {}, "images/sec",
                     SMALLNET_K40M_IMG_S),
        # synthetic high-AI fc stack: the measured MFU-ceiling anchor
        # (models/roofline_probe.py docstring; round-3 verdict weak #1)
        "roofline_probe": (models.roofline_probe.build, {}, "examples/sec",
                           None),
    }
    # valid ranges for integer feeds (labels in-class, seq_lens >= 1)
    int_ranges = {
        "stacked_dynamic_lstm": {"words": (0, 5000), "label": (0, 2),
                                 "seq_lens": (1, 101)},
    }.get(model_name)
    build_fn, kw, unit, baseline = builders[model_name]

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 1
    with fluid.program_guard(main, startup):
        loss, _, feed_specs = build_fn(is_train=True, **kw)
        applied_passes = _apply_tpu_passes(
            main, model_name, batch_size, passes_spec, is_test=False,
            feed_names=sorted(feed_specs), fetch_names=[loss.name])
        if amp:
            from paddle_tpu.contrib.mixed_precision import rewrite_program_amp
            rewrite_program_amp(main)
        if nhwc:
            from paddle_tpu.contrib.layout import rewrite_program_nhwc
            rewrite_program_nhwc(main)
    if batch_merge and batch_merge > 1:
        # k-step gradient accumulation (multi_batch_merge_pass capability:
        # fluid/batch_merge.py) — optimizer applies every k-th step on the
        # k-step mean gradient
        fluid.apply_batch_merge(main, startup, batch_merge)

    run_target = main
    n_chips = 1
    if mesh is not None:
        # dp mesh over the requested chips — XLA emits the collectives the
        # reference's nccl2/pserver update methods provided
        from paddle_tpu.parallel import DistributeConfig
        run_target = fluid.CompiledProgram(main).with_sharding(
            DistributeConfig(mesh=mesh, data_axis="dp"))
        n_chips = mesh.size

    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    chunk = max(2, steps if steps else DEFAULT_CHUNKS.get(model_name, 32))
    feeds = _device_batch(exe, feed_specs, batch_size,
                          int_ranges=int_ranges, stack_int=chunk)
    int_names = sorted(n for n, (sh, dt) in feed_specs.items()
                       if dt.startswith("int"))

    # one dispatch per CHUNK of device-side steps (exe.run iterations=N —
    # the lax.scan hot loop); float feeds are resident, int feeds (labels
    # /ids) are fresh per step (see _device_batch); the loss comes back
    # stacked [chunk], and a single D2H fetch per window is the fence
    def run_chunk():
        return exe.run(run_target, feed=feeds, fetch_list=[loss],
                       iterations=chunk, stacked_feed=int_names,
                       return_numpy=False)[0]

    def fence(handle):
        return np.asarray(handle)

    nchunks, dt, losses = _time_chunks(run_chunk, fence, warmup=warmup)
    nsteps = nchunks * chunk

    per_step = batch_size
    if unit in ("tokens/sec", "words/sec"):
        if "seq_lens" in feeds:
            # count actual words, not padded positions (the reference's
            # LoD word count, fluid_benchmark.py train_parallel); the
            # stacked int feed carries [chunk] batches — average per step
            sl = np.asarray(feeds["seq_lens"])
            per_step = int(sl.sum() // (chunk if "seq_lens" in int_names
                                        else 1))
        else:
            per_step = batch_size * kw.get("max_len", 64)
    value = per_step * nsteps / dt

    assert np.all(np.isfinite(losses)), "loss went non-finite"

    # DP scaling: the mesh arm's per-chip throughput over a single-chip
    # reference arm at the per-chip batch (the v5e-64 ≥90% headline,
    # ROADMAP item 1; tools/spmd_bench.py sweeps the full curve).
    # Single-chip rows report None — the column only means something
    # when a mesh actually ran.
    dp_scaling_pct = None
    if mesh is not None and n_chips > 1:
        try:
            from paddle_tpu.core.scope import Scope as _Scope
            ref_bs = max(batch_size // n_chips, 1)
            exe1 = fluid.Executor(fluid.TPUPlace())
            scope1 = _Scope()
            exe1.run(startup, scope=scope1)
            feeds1 = _device_batch(exe1, feed_specs, ref_bs,
                                   int_ranges=int_ranges, stack_int=chunk)

            def run_ref():
                return exe1.run(main, feed=feeds1, fetch_list=[loss],
                                iterations=chunk, stacked_feed=int_names,
                                return_numpy=False, scope=scope1)[0]

            n1, dt1, _ = _time_chunks(run_ref, fence, min_seconds=1.5,
                                      warmup=2)
            ref_rate = ref_bs * n1 * chunk / dt1     # examples/s, 1 chip
            mesh_rate = batch_size * nsteps / dt     # examples/s, n chips
            if ref_rate > 0:
                dp_scaling_pct = mesh_rate / (n_chips * ref_rate) * 100
        except Exception:
            dp_scaling_pct = None

    # MFU: analytic model FLOPs (2 FLOPs/MAC, backward = 2x forward —
    # paddle_tpu.utils.flops docstring spells out the convention; XLA's own
    # compiled-executable cost analysis agrees within ~3% on ResNet-50)
    # over the attached chip's peak bf16 FLOP/s. None off-TPU.
    from paddle_tpu.utils import flops as flops_mod
    mfu = flops_mod.mfu(main, batch_size, dt / nsteps * n_chips,
                        device=exe.device)

    # roofline twin for embedding-bound programs: gather-scatter HBM
    # bytes per step over the chip's peak bandwidth (None when the
    # program has no lookup/pool ops, e.g. the conv models)
    gather_bytes = flops_mod.program_gather_bytes(main, batch_size)
    gather_bps = (gather_bytes / (dt / nsteps * n_chips)
                  if gather_bytes else None)
    peak_hbm = flops_mod.device_peak_hbm(exe.device)
    bw_pct = (gather_bps / peak_hbm * 100
              if gather_bps and peak_hbm else None)

    # compiled peak-HBM twin to mfu_pct: XLA memory_analysis() on the
    # exact executable the timing loop dispatched (same compile-cache
    # key), as a fraction of the chip's HBM CAPACITY — None off-TPU
    # unless FLAGS_hbm_bytes pins a capacity
    peak_hbm_bytes = hbm_pct = None
    try:
        main.desc._obs_name = model_name
        cb = exe._compiled(run_target, sorted(feeds), [loss.name], False)
        mem = cb.analyzed_memory(
            fluid.global_scope(), feeds, iterations=chunk,
            stacked=sorted(set(int_names)) if int_names else False)
        if mem:
            peak_hbm_bytes = int(mem["peak_bytes"])
            cap = flops_mod.device_hbm_bytes(exe.device)
            if cap:
                hbm_pct = peak_hbm_bytes / cap * 100
    except Exception:
        pass

    _write_metrics_snapshot(
        model_name, "train", nsteps, dt, batch_size,
        per_step if unit in ("tokens/sec", "words/sec") else None, mfu,
        flops_per_step=flops_mod.program_flops(main, batch_size),
        passes=applied_passes)

    return {
        "metric": f"{model_name} train throughput (bs{batch_size}"
                  f"{', amp-bf16' if amp else ''}, {n_chips} chip"
                  f"{'s' if n_chips > 1 else ''})",
        "value": round(float(value), 2),
        "unit": unit,
        "vs_baseline": round(float(value / baseline), 2) if baseline else None,
        "mfu_pct": round(mfu * 100, 1) if mfu is not None else None,
        "dp_scaling_pct": (round(dp_scaling_pct, 1)
                           if dp_scaling_pct is not None else None),
        "peak_hbm_bytes": peak_hbm_bytes,
        "hbm_pct": round(hbm_pct, 1) if hbm_pct is not None else None,
        "gather_bytes_per_s": (round(gather_bps, 0)
                               if gather_bps is not None else None),
        "bw_pct": round(bw_pct, 1) if bw_pct is not None else None,
        "gflop_per_step": round(
            flops_mod.program_flops(main, batch_size) / 1e9, 1),
        "passes": applied_passes,
    }


RESNET50_XEON_INFER_IMG_S = 217.69  # IntelOptimizedPaddle.md:81-88, bs16
VGG19_XEON_INFER_IMG_S = 75.07      # IntelOptimizedPaddle.md:71-78, bs1
GOOGLENET_XEON_INFER_IMG_S = 600.94  # IntelOptimizedPaddle.md:91-98, bs16


def run_infer_bench(model_name: str, batch_size: int, steps: int,
                    warmup: int = 5, amp: bool = True, nhwc: bool = True,
                    passes_spec: str = None):
    """Inference throughput through the deployment path: build is_test
    graph -> save_inference_model -> AnalysisPredictor load (+BN-fold IR
    rewrite) -> timed forward passes (reference capability:
    inference/api/analysis_predictor.cc; baseline rows
    IntelOptimizedPaddle.md infer tables)."""
    import tempfile
    import jax
    import jax.numpy as jnp
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models
    from paddle_tpu.inference import AnalysisConfig, create_paddle_predictor

    nets = {
        "resnet50": (lambda im: models.resnet.resnet(im, 1000, depth=50,
                                                     is_train=False),
                     RESNET50_XEON_INFER_IMG_S),
        "vgg": (lambda im: models.vgg.vgg16(im, 1000, is_train=False),
                VGG19_XEON_INFER_IMG_S),
        "googlenet": (lambda im: models.googlenet.googlenet(
            im, 1000, is_train=False)[0], GOOGLENET_XEON_INFER_IMG_S),
    }
    if model_name not in nets:
        raise ValueError(f"--infer supports {sorted(nets)}, "
                         f"not {model_name!r}")
    net_fn, baseline = nets[model_name]
    image_size = 224

    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = 1
    with fluid.program_guard(main_p, startup):
        img = fluid.layers.data(name="data",
                                shape=[3, image_size, image_size],
                                dtype="float32")
        prob = fluid.layers.softmax(net_fn(img))
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)

    with tempfile.TemporaryDirectory() as tmp:
        fluid.io.save_inference_model(tmp, ["data"], [prob], exe,
                                      main_program=main_p)
        config = AnalysisConfig()
        config.model_dir = tmp
        predictor = create_paddle_predictor(config)

    program = predictor._program
    pexe, scope = predictor._exe, predictor._scope
    fetch = predictor._fetch_names
    applied_passes = _apply_tpu_passes(
        program, model_name, batch_size, passes_spec, is_test=True,
        feed_names=["data"], fetch_names=list(fetch), scope=scope)
    if amp:
        from paddle_tpu.contrib.mixed_precision import rewrite_program_amp
        rewrite_program_amp(program)
    if nhwc:
        from paddle_tpu.contrib.layout import rewrite_program_nhwc
        rewrite_program_nhwc(program)

    # DIFFERENT image batch per scan step, generated on device: a
    # stateless forward over a resident batch is loop-invariant — XLA
    # computes it once and the "throughput" reads 8x past the roofline.
    # Each step also fetches its probs (stacked) so no step is DCE'd;
    # only the fence pays the tunnel D2H.
    chunk = max(2, steps if steps else 64)
    x = jax.random.uniform(
        jax.random.key(0),
        (chunk, batch_size, 3, image_size, image_size), jnp.float32)
    feeds = {"data": x}

    def run_chunk():
        return pexe.run(program, feed=feeds, fetch_list=fetch, scope=scope,
                        return_numpy=False, iterations=chunk,
                        stacked_feed=True)[0]

    def fence(handle):
        return np.asarray(handle)

    nchunks, dt, out = _time_chunks(run_chunk, fence, warmup=warmup)
    nsteps = nchunks * chunk
    assert np.all(np.isfinite(out)) and out.shape == (chunk, batch_size, 1000)
    value = batch_size * nsteps / dt
    from paddle_tpu.utils import flops as flops_mod
    mfu = flops_mod.mfu(program, batch_size, dt / nsteps, device=pexe.device)
    _write_metrics_snapshot(model_name, "infer", nsteps, dt, batch_size,
                            None, mfu,
                            flops_per_step=flops_mod.program_flops(
                                program, batch_size),
                            passes=applied_passes)
    return {
        "metric": f"{model_name} infer throughput (bs{batch_size}"
                  f"{', amp-bf16' if amp else ''}, 1 chip)",
        "value": round(float(value), 2),
        "unit": "images/sec",
        "vs_baseline": round(float(value / baseline), 2) if baseline else None,
        "mfu_pct": round(mfu * 100, 1) if mfu is not None else None,
        "passes": applied_passes,
    }


def run_coldstart_bench(model_name: str = "resnet50",
                        batch_size: int = 16):
    """Serving cold-start: load->first-inference latency with the
    persisted AOT executable vs recompile-from-source (reference:
    analysis_predictor.cc model-load path starts serving from a
    deserialized program; Predictor.save_compiled/load_compiled give the
    TPU analogue by serializing the compiled XLA executable next to the
    StableHLO export)."""
    import tempfile
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models
    from paddle_tpu.inference import AnalysisConfig, create_paddle_predictor

    if model_name != "resnet50":
        raise ValueError("--coldstart benchmarks the resnet50 serving "
                         f"path; {model_name!r} has no cold-start row")
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = 1
    with fluid.program_guard(main_p, startup):
        img = fluid.layers.data(name="data", shape=[3, 224, 224],
                                dtype="float32")
        prob = fluid.layers.softmax(models.resnet.resnet(
            img, 1000, depth=50, is_train=False))
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)

    rng = np.random.RandomState(0)
    batch = {"data": rng.rand(batch_size, 3, 224, 224).astype(np.float32)}

    with tempfile.TemporaryDirectory() as tmp:
        fluid.io.save_inference_model(tmp, ["data"], [prob], exe,
                                      main_program=main_p)
        config = AnalysisConfig()
        config.model_dir = tmp

        def make_pred():
            # same amp-bf16 + NHWC serving config as the infer rows (the
            # fp32-NCHW resnet compile is pathologically slow on this
            # stack and is not a config anyone serves)
            pred = create_paddle_predictor(config)
            from paddle_tpu.contrib.mixed_precision import \
                rewrite_program_amp
            from paddle_tpu.contrib.layout import rewrite_program_nhwc
            rewrite_program_amp(pred._program)
            rewrite_program_nhwc(pred._program)
            return pred

        # path A: compile from source at first inference
        pred_a = make_pred()
        t0 = time.time()
        out_a = pred_a.run(batch)
        t_compile = time.time() - t0
        pred_a.save_compiled(tmp, batch)

        # path B: deserialize the persisted executable, no compiler
        pred_b = make_pred()
        t0 = time.time()
        assert pred_b.load_compiled(tmp)
        out_b = pred_b.run(batch)
        t_aot = time.time() - t0
        np.testing.assert_allclose(out_a[0], out_b[0], rtol=2e-3,
                                   atol=2e-3)   # bf16 serving config

    return {
        "metric": f"{model_name} serving cold-start, AOT-load -> first "
                  f"inference (bs{batch_size}, 1 chip)",
        "value": round(t_aot, 3), "unit": "seconds",
        "vs_baseline": None,
        "compile_from_source_s": round(t_compile, 3),
        "speedup": round(t_compile / t_aot, 1) if t_aot else None,
    }


def aggregate_line(rows, head, n_ok):
    """The sweep aggregate is the FINAL stdout line and must survive the
    driver's tail-window capture (round-3 verdict item 6: BENCH_r03
    physically lost its head rows to truncation) — so rows[] is COMPACT:
    short name, value, unit, mfu. The verbose per-row lines with
    vs_baseline/gflop_per_step were already printed as each model
    finished."""
    compact = []
    for r in rows:
        if "cold-start" in r["metric"]:
            c = {"m": r["metric"].split()[0] + "-coldstart",
                 "v": r.get("value"), "u": r.get("unit")}
            if r.get("value") is None:
                c["err"] = (r.get("error") or "?")[:40]
            compact.append(c)
            continue
        name = r["metric"].split(" train ")[0].split(" infer")[0]
        kind = "infer" if (" infer" in r["metric"]
                           or "deploy" in r["metric"]) else "train"
        c = {"m": name if kind == "train" else f"{name}-infer",
             "v": (round(r["value"], 1)
                   if r.get("value") is not None else None),
             "u": r.get("unit")}
        if r.get("mfu_pct") is not None:
            c["mfu"] = r["mfu_pct"]
        if r.get("dp_scaling_pct") is not None:
            c["dp"] = r["dp_scaling_pct"]
        if r.get("bw_pct") is not None:
            c["bw"] = r["bw_pct"]
        if r.get("hbm_pct") is not None:
            c["hbm"] = r["hbm_pct"]
        if r.get("value") is None:
            c["err"] = (r.get("error") or "?")[:40]
        compact.append(c)
    return {
        "metric": f"full sweep ({n_ok}/{len(rows)} rows; headline: "
                  f"{head['metric']})",
        "value": head.get("value"), "unit": head.get("unit"),
        "vs_baseline": head.get("vs_baseline"),
        "mfu_pct": head.get("mfu_pct"), "rows": compact}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None,
                    choices=["alexnet", "resnet50", "roofline_probe",
                             "transformer",
                             "transformer_big", "transformer_long", "mnist",
                             "stacked_dynamic_lstm", "vgg", "se_resnext",
                             "machine_translation", "deepfm", "googlenet",
                             "smallnet"])
    ap.add_argument("--batch-size", type=int, default=None)
    ap.add_argument("--steps", type=int, default=None,
                    help="device-side steps per dispatch chunk "
                         "(default: per-model table)")
    ap.add_argument("--batch-merge", type=int, default=0,
                    help="k-step gradient accumulation (the reference's "
                         "multi_batch_merge_pass capability)")
    ap.add_argument("--passes", default=None, metavar="P1,P2|none",
                    help="IR-pass pipeline for the row: default is the "
                         "committed autotune winner for the model (or "
                         "the static pipeline); 'none' disables (the "
                         "A/B control arm tools/autotune.py uses); a "
                         "comma list applies exactly those passes")
    ap.add_argument("--no-passes", dest="passes", action="store_const",
                    const="none", help="alias for --passes none")
    ap.add_argument("--all", nargs="?", const="", default=None,
                    metavar="M1,M2",
                    help="sweep every model (or a comma list) printing one "
                         "JSON line each; failures print an error line "
                         "and the sweep continues")
    ap.add_argument("--headline", action="store_true",
                    help="run only the headline resnet50 row (the pre-r3 "
                         "default; the default is now the full sweep)")
    ap.add_argument("--coldstart", action="store_true",
                    help="serving cold-start row: AOT executable load vs "
                         "recompile-from-source (resnet50)")
    ap.add_argument("--infer", action="store_true",
                    help="benchmark the deployment/inference path "
                         "(save_inference_model -> AnalysisPredictor)")
    ap.add_argument("--amp", dest="amp", action="store_true", default=True,
                    help="bf16 MXU compute (fp32 master weights) — default")
    ap.add_argument("--no-amp", dest="amp", action="store_false")
    ap.add_argument("--no-nhwc", dest="nhwc", action="store_false",
                    default=True, help="disable the channels-last layout "
                    "rewrite (contrib.layout)")
    ap.add_argument("--check", nargs="?", const="", default=None,
                    metavar="BASELINE_JSON",
                    help="perf-regression gate: re-run a row subset and "
                         "fail (exit 1) if any row regresses more than "
                         "--check-tolerance below the committed aggregate "
                         "(default baseline: the newest BENCH_r*.json; "
                         "accepts the driver artifact or a raw aggregate "
                         "line)")
    ap.add_argument("--check-models", default="mnist,transformer",
                    metavar="M1,M2",
                    help="rows to re-measure for --check (compact "
                         "aggregate names; suffix -infer for deployment "
                         "rows). Default: two fast always-runnable rows")
    ap.add_argument("--check-tolerance", type=float, default=0.08,
                    help="allowed fractional shortfall per row before "
                         "--check fails (default 0.08 — run-to-run "
                         "variance on the tunnel is ~±5%%)")
    ap.add_argument("--chips", type=int, default=0,
                    help="train over a dp mesh of this many chips (one "
                         "SPMD dispatch, docs/performance.md 'SPMD "
                         "execution'); the row gains dp_scaling_pct vs "
                         "an inline single-chip reference arm. 0 "
                         "(default) keeps the single-chip row")
    args = ap.parse_args()

    def run_one_subprocess(m, infer=False, coldstart=False):
        # one subprocess per model: a fresh backend per run keeps a
        # pathological compile (googlenet-style) or OOM from taking
        # the whole sweep down. Every non-sweep flag forwards.
        cmd = [sys.executable, __file__, "--model", m]
        if not args.amp:
            cmd.append("--no-amp")
        if not args.nhwc:
            cmd.append("--no-nhwc")
        if args.passes:
            cmd += ["--passes", args.passes]
        if infer:
            cmd.append("--infer")
        if coldstart:
            cmd.append("--coldstart")
        if args.batch_size:
            cmd += ["--batch-size", str(args.batch_size)]
        if args.steps:
            cmd += ["--steps", str(args.steps)]
        if args.batch_merge:
            cmd += ["--batch-merge", str(args.batch_merge)]
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=1200)
            lines = [l for l in r.stdout.splitlines()
                     if l.startswith("{")]
            ok = r.returncode == 0 and lines
            err = r.stderr[-300:]
        except subprocess.TimeoutExpired:
            ok, err = False, "timeout after 1200s"
        if ok:
            row = json.loads(lines[-1])
        else:
            kind = ("serving cold-start" if coldstart
                    else "infer" if infer else "train")
            row = {"metric": f"{m} {kind} throughput", "value": None,
                   "unit": None, "vs_baseline": None, "error": err}
        print(json.dumps(row), flush=True)
        return row

    import subprocess
    if args.check is not None:
        # Perf-regression gate (round-4 VERDICT #8): round-5 edits must
        # not trade one row for another unnoticed. Re-measures each
        # requested row fresh (subprocess = fresh backend) and compares
        # against the committed aggregate's same-named compact row.
        if not args.check:            # default: newest COMMITTED round —
            # a fresh uncommitted sweep artifact must never become its
            # own baseline (the gate would compare the run to itself)
            import os
            repo = os.path.dirname(os.path.abspath(__file__))
            try:
                tracked = subprocess.run(
                    ["git", "ls-files", "BENCH_r*.json"], cwd=repo,
                    capture_output=True, text=True, check=True
                ).stdout.split()
            except (OSError, subprocess.CalledProcessError):
                import glob                   # non-git checkout fallback
                tracked = sorted(os.path.basename(p) for p in
                                 glob.glob(os.path.join(repo,
                                                        "BENCH_r*.json")))
            if not tracked:
                ap.error("--check: no committed BENCH_r*.json baseline")
            args.check = os.path.join(repo, sorted(tracked)[-1])
        with open(args.check) as f:
            base = json.load(f)
        base_rows = (base.get("parsed") or base).get("rows") or []
        by_name = {r["m"]: r for r in base_rows}
        regressions, checked = [], 0
        for name in [m for m in args.check_models.split(",") if m]:
            ref = by_name.get(name)
            if ref is None or ref.get("v") is None:
                print(json.dumps({"check": name, "status": "no-baseline"}),
                      flush=True)
                continue
            if name.endswith("-coldstart"):
                m, kw = name[:-len("-coldstart")], {"coldstart": True}
            elif name.endswith("-infer"):
                m, kw = name[:-len("-infer")], {"infer": True}
            else:
                m, kw = name, {}
            row = run_one_subprocess(m, **kw)
            v = row.get("value")
            checked += 1
            if v is None:
                regressions.append(name)
                status = "ERROR"
                ratio = None
            else:
                ratio = round(v / ref["v"], 3)
                # latency-unit rows (cold-start seconds) regress UP
                lower_better = (ref.get("u") or "").startswith("second")
                ok_row = (v <= ref["v"] * (1.0 + args.check_tolerance)
                          if lower_better else
                          v >= ref["v"] * (1.0 - args.check_tolerance))
                status = "ok" if ok_row else "REGRESSION"
                if not ok_row:
                    regressions.append(name)
            print(json.dumps({"check": name, "value": v,
                              "baseline": ref["v"], "ratio": ratio,
                              "status": status}), flush=True)
        print(json.dumps({
            "metric": f"perf-check vs {args.check} "
                      f"(tol {args.check_tolerance:.0%})",
            "value": checked - len(regressions), "unit": f"of {checked} "
            f"rows ok", "vs_baseline": None,
            "regressions": regressions}))
        # a gate that measured nothing (all names missed the baseline)
        # must fail loudly, not report success
        sys.exit(1 if (regressions or checked == 0) else 0)
    if args.all is not None:
        models_ = ([m for m in args.all.split(",") if m] if args.all
                   else sorted(DEFAULT_BATCH_SIZES))
        for m in models_:
            run_one_subprocess(m, infer=args.infer)
        return
    if args.model is None and not args.headline and not args.infer \
            and not args.coldstart:
        # DEFAULT: the FULL sweep — every train model plus the three
        # deployment-path rows, one JSON line each as they finish, then
        # one aggregate line (driver schema + rows[]) so the driver
        # artifact substantiates the whole table (round-2 verdict item 2;
        # reference: fluid_benchmark.py:139 reports every model).
        # headline first: if the harness ever truncates the sweep, the
        # most important rows are already on stdout
        order = ["resnet50", "transformer"] + [
            m for m in sorted(DEFAULT_BATCH_SIZES)
            if m not in ("resnet50", "transformer")]
        rows = [run_one_subprocess(m) for m in order]
        rows += [run_one_subprocess(m, infer=True)
                 for m in ("resnet50", "vgg", "googlenet")]
        rows.append(run_one_subprocess("resnet50", coldstart=True))
        head = next((r for r in rows if r.get("value") is not None
                     and r["metric"].startswith("resnet50 train")),
                    next((r for r in rows if r.get("value") is not None),
                         rows[0]))
        n_ok = sum(1 for r in rows if r.get("value") is not None)
        print(json.dumps(aggregate_line(rows, head, n_ok),
                         separators=(",", ":")))
        return
    if args.model is None:
        args.model = "resnet50"
    if args.coldstart:
        print(json.dumps(run_coldstart_bench(args.model or "resnet50",
                                             args.batch_size or 16)))
        return
    if args.infer:
        infer_bs = {"resnet50": 16, "vgg": 1, "googlenet": 16}
        if args.model not in infer_bs:
            ap.error(f"--infer supports {sorted(infer_bs)}; "
                     f"{args.model!r} has no deployment-path benchmark")
        bs = args.batch_size or infer_bs[args.model]
        result = run_infer_bench(args.model, bs, args.steps, amp=args.amp,
                                 nhwc=args.nhwc, passes_spec=args.passes)
    else:
        bs = args.batch_size or DEFAULT_BATCH_SIZES[args.model]
        mesh = None
        if args.chips and args.chips > 1:
            import jax
            from paddle_tpu.parallel import make_mesh
            mesh = make_mesh({"dp": args.chips},
                             devices=jax.devices()[:args.chips])
        result = run_bench(args.model, bs, args.steps, amp=args.amp,
                           nhwc=args.nhwc, batch_merge=args.batch_merge,
                           passes_spec=args.passes, mesh=mesh)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
