"""paddle_tpu.data — host-side input pipeline + dataset zoo.

Replaces the reference's in-graph reader-op stack (operators/reader/:
create_py_reader_op.cc, buffered_reader.cc double-buffering, blocking_queue.h;
python layers/io.py py_reader :485) with a host prefetcher that overlaps
CPU batch prep + H2D transfer with TPU compute — the TPU-idiomatic shape of
the same capability.
"""

from paddle_tpu.data.pipeline import DataLoader, PyReader
from paddle_tpu.data.datafeed import (AsyncExecutor, DataFeedDesc,
                                      MultiSlotDataFeed)
from paddle_tpu.data.master_service import (MASTER_ENV, MasterClient,
                                            MasterServer)

__all__ = ["AsyncExecutor", "DataFeedDesc", "DataLoader", "MASTER_ENV",
           "MasterClient", "MasterServer", "MultiSlotDataFeed", "PyReader"]
