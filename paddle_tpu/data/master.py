"""Elastic data master: chunk-lease task dispatch over RecordIO datasets
(reference: the Go EDL master, go/master/service.go — partition :106,
GetTask :366, TaskFinished :410, TaskFailed :455, failureMax :341,
snapshot/recover :207/:166; client go/master/client.go).

The C++ state machine lives in csrc/master.cc; this wrapper partitions
datasets into chunk-range tasks, and `task_reader` drives the
lease → scan → finish loop a trainer runs. A worker that dies mid-task
simply never reports; the lease times out and the task is re-issued to a
surviving worker — elasticity without etcd (snapshots cover master
crashes; multi-host serving can front this with any RPC layer while the
JAX coordination service owns liveness)."""

from __future__ import annotations

import ctypes
import time
from dataclasses import dataclass
from typing import Iterator, List, Optional

from paddle_tpu import recordio
from paddle_tpu.core import native


@dataclass
class Task:
    id: int
    epoch: int      # lease epoch: stale reports onto a re-issued lease
                    # of the same task are rejected (master.cc)
    path: str
    chunk_begin: int
    chunk_end: int


class Master:
    """Task queue with lease timeout + retry + failure-max drop."""

    def __init__(self, timeout_s: float = 60.0, failure_max: int = 3):
        if not native.available():
            raise native.NativeUnavailable("master requires native runtime")
        import threading
        self._h = native.lib().ptpu_master_new(float(timeout_s),
                                               int(failure_max))
        self._snap_lock = threading.Lock()

    def set_dataset(self, paths: List[str], chunks_per_task: int = 1):
        """Partition RecordIO files into chunk-range tasks
        (reference: service.go:106 partition)."""
        for p in paths:
            n = recordio.num_chunks(p)
            for b in range(0, max(n, 1), chunks_per_task):
                native.lib().ptpu_master_add_task(
                    self._h, p.encode(), b, min(b + chunks_per_task, n))

    def add_task(self, path: str, chunk_begin: int, chunk_end: int):
        native.lib().ptpu_master_add_task(self._h, path.encode(),
                                          chunk_begin, chunk_end)

    def get_task(self) -> Optional[Task]:
        """None = nothing leasable right now (retry) ; raises StopIteration
        semantics via `done` property instead."""
        cap = 1024
        while True:
            buf = ctypes.create_string_buffer(cap)
            r = native.lib().ptpu_master_get_task(self._h, buf, cap)
            if r == -2:                  # task path longer than the buffer
                cap *= 8
                continue
            if r != 1:
                return None
            tid, epoch, path, b, e = buf.value.decode().split("|")
            return Task(int(tid), int(epoch), path, int(b), int(e))

    def task_finished(self, task: "Task") -> bool:
        return native.lib().ptpu_master_task_finished(
            self._h, task.id, task.epoch) == 0

    def task_failed(self, task: "Task") -> bool:
        return native.lib().ptpu_master_task_failed(
            self._h, task.id, task.epoch) == 0

    @property
    def done(self) -> bool:
        lib = native.lib()
        return (lib.ptpu_master_num_todo(self._h) == 0
                and lib.ptpu_master_num_pending(self._h) == 0
                and lib.ptpu_master_num_done(self._h) > 0)

    def stats(self) -> dict:
        lib = native.lib()
        return {"todo": lib.ptpu_master_num_todo(self._h),
                "pending": lib.ptpu_master_num_pending(self._h),
                "done": lib.ptpu_master_num_done(self._h),
                "dropped": lib.ptpu_master_num_dropped(self._h)}

    def snapshot(self, path: str):
        """Atomic AND ordered: writes a unique tmp file and rename()s it
        over ``path`` (a crash mid-write can never leave a torn snapshot
        as the recovery source — the etcd analogue's writes were atomic
        per key). The capture+replace pair is serialized under a Python
        lock: without it, two ThreadingTCPServer handler threads could
        replace the file out of capture order and an OLDER snapshot —
        missing an already-acked report — could end up newest, silently
        rolling back the persist-before-reply guarantee.

        The previous snapshot rotates to ``path + ".prev"`` first: the
        rename makes a torn ``path`` impossible from THIS writer, but a
        dying disk / external truncation can still corrupt the newest
        file in place — recovery (:meth:`MasterServer`) then falls back
        to the newest snapshot that passes :func:`verify_snapshot`."""
        import os
        import threading

        from paddle_tpu.utils import faults
        with self._snap_lock:
            faults.inject("master.snapshot")   # chaos: disk trouble here
            tmp = f"{path}.tmp{os.getpid()}_{threading.get_ident()}"
            if native.lib().ptpu_master_snapshot(self._h, tmp.encode()) != 0:
                raise IOError(f"snapshot to {tmp!r} failed")
            try:
                os.replace(path, path + ".prev")
            except OSError:
                pass                       # first snapshot: nothing to keep
            os.replace(tmp, path)

    def recover(self, path: str):
        if native.lib().ptpu_master_recover(self._h, path.encode()) != 0:
            raise IOError(f"recover from {path!r} failed")

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            try:
                native.lib().ptpu_master_free(h)
            except Exception:
                pass
            self._h = None


def verify_snapshot(path: str) -> bool:
    """Structural integrity check of a master snapshot file WITHOUT
    loading it into a state machine. The C++ ``Recover`` parses with
    ``operator>>`` and silently stops at the first short record — a
    snapshot truncated mid-record (torn write, dying disk) would
    otherwise recover to a state that LOOKS healthy but lost tasks.
    This is the guard :class:`MasterServer` runs before trusting a
    candidate file:

    * header: ``ptpu_master_v1|v2`` + 4 (v1) / 5 (v2) integer fields;
    * every record line: ``todo|pending id path begin end failures``
      (+ ``lease_epoch`` on v2), integers where integers belong;
    * the record count must equal ``total - done`` — the queue
      invariant a truncation breaks even when it cuts at a line
      boundary.
    """
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            text = f.read()
    except OSError:
        return False
    lines = [ln for ln in text.splitlines() if ln.strip()]
    if not lines:
        return False
    head = lines[0].split()
    if head[0] == "ptpu_master_v1":
        version, want_head, want_rec = 1, 5, 6
    elif head[0] == "ptpu_master_v2":
        version, want_head, want_rec = 2, 6, 7
    else:
        return False
    if len(head) != want_head:
        return False
    try:
        _next_id, done, total, _dropped = (int(x) for x in head[1:5])
    except ValueError:
        return False
    if version == 2:
        try:
            int(head[5])
        except ValueError:
            return False
    records = 0
    for ln in lines[1:]:
        parts = ln.split()
        if len(parts) != want_rec or parts[0] not in ("todo", "pending"):
            return False
        try:
            for idx in ((1, 3, 4, 5, 6) if version == 2
                        else (1, 3, 4, 5)):
                int(parts[idx])
        except ValueError:
            return False
        records += 1
    # the queue invariant: everything not done is on disk as a record
    return records == total - done


def task_reader(master: Master, poll_interval: float = 0.05,
                fail_injector=None) -> Iterator[bytes]:
    """The trainer-side loop (reference: go/master/client.go NextRecord):
    lease a task, scan its chunk range, report finished; on scan error
    report failed. `fail_injector(task) -> bool` lets tests kill a task
    mid-flight (the reference tests kill processes; SURVEY §5)."""
    while True:
        task = master.get_task()
        if task is None:
            if master.done:
                return
            time.sleep(poll_interval)
            continue
        scanner = None
        try:
            if fail_injector is not None and fail_injector(task):
                continue          # simulate worker death: never report
            scanner = recordio.Scanner(task.path, task.chunk_begin,
                                       task.chunk_end)
            for rec in scanner:
                yield rec
        except Exception:
            master.task_failed(task)
            continue
        finally:
            if scanner is not None:
                scanner.close()
        # Delivery is AT-LEAST-ONCE, like the reference (the Go client
        # yields records as it scans; go/master/client.go NextRecord): if
        # consuming a chunk takes longer than the lease, the finish below
        # is rejected as stale (master.cc expires with timer semantics)
        # and the chunk re-issues to another worker — re-trained rather
        # than lost. Size leases for the slowest chunk, not the average.
        master.task_finished(task)
