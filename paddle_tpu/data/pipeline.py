"""Prefetching input pipeline.

Capability parity with the reference's py_reader + double_buffer
(reference: python/paddle/fluid/layers/io.py:485 py_reader,
operators/reader/buffered_reader.cc, blocking_queue.h): a producer thread
converts numpy batches and issues async H2D `device_put`s into a bounded
queue, so the next batch's transfer overlaps the current step's compute —
double-buffering without reader ops in the graph.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, Iterable, Optional

import numpy as np

from paddle_tpu.observability import metrics as _metrics

# input-pipeline telemetry (docs/observability.md): the queue-depth
# gauge says whether the pipeline is producer- or consumer-bound at a
# glance; the stall counters attribute the imbalance (producer blocked
# on a full queue vs. trainer waiting on an empty one). The `loader`
# label separates concurrent pipelines (train vs eval) — pass
# DataLoader(name=...); unnamed loaders share the "default" child.
QUEUE_DEPTH = _metrics.gauge(
    "paddle_data_queue_depth",
    "Prefetch-queue occupancy after the last put/get",
    labelnames=("loader",))
BATCHES_PRODUCED = _metrics.counter(
    "paddle_data_batches_produced_total",
    "Batches converted + enqueued by DataLoader produce threads",
    labelnames=("loader",))
PRODUCER_STALL = _metrics.counter(
    "paddle_data_producer_stall_seconds_total",
    "Seconds produce threads spent blocked on a full queue "
    "(consumer-bound pipeline)", labelnames=("loader",))
CONSUMER_WAIT = _metrics.counter(
    "paddle_data_consumer_wait_seconds_total",
    "Seconds consumers spent blocked on an empty queue "
    "(producer-bound pipeline)", labelnames=("loader",))


class DataLoader:
    """Iterate feed dicts with device-side prefetch.

    loader = DataLoader(feed_names, reader, capacity=2)
    for feeds in loader:         # feeds values are on-device jax.Arrays
        exe.run(main, feed=feeds, fetch_list=[...])
    """

    _END = object()

    def __init__(self, feed_names, batch_reader: Callable[[], Iterable],
                 capacity: int = 2, device=None, feeder=None,
                 name: Optional[str] = None):
        """``name`` tags this loader's telemetry (the ``loader`` label
        on the paddle_data_* metrics) — a short tag like "train"/"eval",
        so concurrent pipelines don't share one gauge."""
        self.feed_names = list(feed_names)
        self.batch_reader = batch_reader
        self.capacity = capacity
        self.device = device
        self.feeder = feeder
        self.name = name or "default"

    def _convert(self, batch) -> Dict[str, object]:
        import jax
        if isinstance(batch, dict):
            arrays = batch
        elif self.feeder is not None:
            arrays = self.feeder.feed(batch)
        else:
            cols = list(zip(*batch))
            arrays = {n: np.asarray(c) for n, c in zip(self.feed_names, cols)}
        if self.device is not None:
            return {k: jax.device_put(v, self.device)
                    for k, v in arrays.items()}
        return {k: jax.device_put(v) for k, v in arrays.items()}

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.capacity)
        exc: list = []

        depth = QUEUE_DEPTH.labels(loader=self.name)
        produced = BATCHES_PRODUCED.labels(loader=self.name)
        stalled = PRODUCER_STALL.labels(loader=self.name)
        waited = CONSUMER_WAIT.labels(loader=self.name)

        def produce():
            try:
                for b in self.batch_reader():
                    item = self._convert(b)
                    t0 = time.perf_counter()
                    q.put(item)
                    stall = time.perf_counter() - t0
                    if stall > 1e-4:      # actually blocked, not a no-op
                        stalled.inc(stall)
                    produced.inc()
                    depth.set(q.qsize())
            except Exception as e:  # surfaced on the consumer side
                exc.append(e)
            finally:
                q.put(self._END)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            t0 = time.perf_counter()
            item = q.get()
            wait = time.perf_counter() - t0
            if wait > 1e-4:
                waited.inc(wait)
            depth.set(q.qsize())
            if item is self._END:
                if exc:
                    raise exc[0]
                return
            yield item


class PyReader:
    """API-parity shim for fluid.layers.py_reader users
    (reference: io.py:485): decorate_paddle_reader + start()/reset() +
    iteration, backed by DataLoader."""

    def __init__(self, feed_list, capacity: int = 2, use_double_buffer=True,
                 iterable: bool = True):
        self.feed_vars = list(feed_list)
        self.capacity = capacity
        self._reader = None
        self._loader: Optional[DataLoader] = None

    def decorate_paddle_reader(self, reader, places=None):
        from paddle_tpu.fluid.data_feeder import DataFeeder
        feeder = DataFeeder(self.feed_vars)
        names = [v if isinstance(v, str) else v.name for v in self.feed_vars]
        self._loader = DataLoader(names, reader, capacity=self.capacity,
                                  feeder=feeder)

    decorate_sample_list_generator = decorate_paddle_reader

    def decorate_batch_generator(self, reader, places=None):
        names = [v if isinstance(v, str) else v.name for v in self.feed_vars]
        self._loader = DataLoader(names, reader, capacity=self.capacity)

    def start(self):
        self._iter = iter(self._loader)

    def reset(self):
        self._iter = None

    def __iter__(self):
        return iter(self._loader)
