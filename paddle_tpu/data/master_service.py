"""Network service for the chunk-lease master: N trainer processes share
one task queue (reference: go/master/service.go — the master is an RPC
service trainers dial; GetTask :366, TaskFinished :410, TaskFailed :455;
clients discover it via etcd, go/master/etcd_client.go).

TPU-native shape: the C++ lease/timeout/retry state machine (csrc/
master.cc, wrapped by data/master.py) is hosted on rank 0 behind a tiny
line-oriented JSON-over-TCP protocol — the one place a control-plane RPC
stack survives on a TPU pod (SURVEY §5 comm backend note). Discovery is
the repo's existing cluster convention instead of etcd: workers read
``PADDLE_MASTER`` (or are handed the address), the same way
``PADDLE_COORDINATOR`` carries the JAX coordination service address.

Protocol (one JSON object per line, one reply line per request):

    -> {"method": "get_task"}
    <- {"ok": true, "task": {"id": 3, "epoch": 7, "path": "...",
                             "chunk_begin": 0, "chunk_end": 2}}
       | {"ok": true, "task": null, "done": false}    retry later
       | {"ok": true, "task": null, "done": true}     queue drained
    -> {"method": "task_finished", "id": 3, "epoch": 7}
    <- {"ok": true, "accepted": true}    (false = stale lease epoch)
    -> {"method": "task_failed", "id": 3, "epoch": 7}
    -> {"method": "stats"} / {"method": "snapshot", "path": "..."}
    -> {"method": "ping"}

A worker that dies mid-lease simply stops talking; its lease expires in
the C++ state machine and the task re-issues to a surviving worker — the
EDL elasticity loop, now actually shared across OS processes.

The MASTER side is elastic too (go/master/service.go:165 recover from
etcd + etcd_client.go:191 clients watch-and-re-dial): construct
``MasterServer(snapshot_path=...)`` and every accepted lease/report is
persisted before its reply; a killed master restarted on the same
endpoint recovers the queue with pending leases intact, and
``MasterClient`` rides the outage via reconnect-with-backoff
(tests/test_master_failover.py).
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
import time
import uuid
from typing import Optional, Tuple

from paddle_tpu.observability import lock_witness
from paddle_tpu.data.master import Master, Task, verify_snapshot
from paddle_tpu.distributed.resilience import RetryError, RetryPolicy
from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.observability import trace_context as tctx
from paddle_tpu.utils import faults

MASTER_ENV = "PADDLE_MASTER"

# chunk-lease control-plane telemetry (docs/observability.md). The
# `cause` label on failed-back leases distinguishes the reaper's
# dead-worker failback, the persist-failure failback, and a client's own
# task_failed report — the second witness chaos tests assert against.
LEASES_GRANTED = _metrics.counter(
    "paddle_master_leases_granted_total",
    "Chunk leases issued by get_task")
LEASES_FAILED_BACK = _metrics.counter(
    "paddle_master_leases_failed_back_total",
    "Leases returned to the queue before finishing",
    labelnames=("cause",))      # reaped | persist_error | report
TASKS_FINISHED = _metrics.counter(
    "paddle_master_tasks_finished_total",
    "task_finished reports accepted")
STALE_REPORTS = _metrics.counter(
    "paddle_master_stale_reports_total",
    "task_finished/task_failed reports rejected by the lease-epoch check")
WORKERS_REAPED = _metrics.counter(
    "paddle_master_workers_reaped_total",
    "Workers whose heartbeat went silent past the timeout")
HEARTBEATS = _metrics.counter(
    "paddle_master_heartbeats_total", "Heartbeat RPCs handled")
HEARTBEAT_AGE = _metrics.gauge(
    "paddle_master_heartbeat_age_seconds",
    "Oldest registered worker's heartbeat age, sampled by the reaper "
    "tick (0 with no registered workers)")
SNAPSHOT_PERSIST = _metrics.histogram(
    "paddle_master_snapshot_persist_seconds",
    "Durable-queue snapshot latency (persist-before-reply path)")
SNAPSHOT_FALLBACK = _metrics.counter(
    "paddle_master_snapshot_fallback_total",
    "Recoveries that skipped a corrupt newest snapshot and fell back "
    "to the rotated .prev (torn-write tolerance)")


class MasterUnavailableError(ConnectionError):
    """The master endpoint could not be reached within the client's retry
    budget. Carries ``endpoint`` and ``attempts`` so a dying worker's log
    says exactly what it dialed and how hard it tried (the opaque
    ``ConnectionRefusedError`` it replaces said neither)."""

    def __init__(self, endpoint: str, attempts: int, elapsed_s: float,
                 last: BaseException):
        super().__init__(
            f"master at {endpoint} unavailable after {attempts} "
            f"attempt(s) over {elapsed_s:.2f}s (last error: {last!r})")
        self.endpoint = endpoint
        self.attempts = attempts


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        master: Master = self.server.master  # type: ignore[attr-defined]
        while True:
            try:
                line = self.rfile.readline()
            except (ConnectionError, OSError):
                return
            if not line:
                return
            try:
                req = json.loads(line)
                # adopt the worker's trace context so this RPC's span
                # (and anything it triggers — snapshot persists) parents
                # under the worker's span in the merged trace
                ctx = tctx.extract(req)
                with tctx.activate(ctx if ctx is not None
                                   else tctx.current()):
                    with tctx.span("master." + str(req.get("method")),
                                   worker=str(req.get("worker") or "")):
                        resp = self._dispatch(master, req, self.server)
            except Exception as e:  # malformed request: report, keep serving
                resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            try:
                self.wfile.write((json.dumps(resp) + "\n").encode())
                self.wfile.flush()
            except (ConnectionError, OSError, BrokenPipeError):
                return

    @staticmethod
    def _persist(master: Master, server) -> None:
        """Durability point: called after every accepted state change,
        BEFORE the reply is sent — an acked lease/report is always in
        the snapshot a restarted master recovers from (the reference
        persists each state change to etcd the same way,
        go/master/service.go:207)."""
        sp = getattr(server, "snapshot_path", None)
        if sp:
            t0 = time.perf_counter()
            master.snapshot(sp)
            # successful persists only: a failed snapshot is accounted by
            # the persist_error failback counter, not the latency curve
            SNAPSHOT_PERSIST.observe(time.perf_counter() - t0)

    @staticmethod
    def _touch_worker(server, wid: str, add_lease=None, drop_lease=None,
                      register=False):
        """Refresh a worker in the heartbeat registry, optionally
        recording/clearing a lease in the same critical section (so the
        reaper can never observe a registered worker without its fresh
        lease). Only a ``heartbeat`` request REGISTERS a worker
        (``register=True``): merely carrying a worker_id on get_task must
        not opt a client into reaping, because a worker silently training
        a long chunk is indistinguishable from a dead one — reap-by-
        silence is only safe for workers that promised to keep beating
        (start_heartbeat runs in a background thread, so long chunks
        don't go silent). Returns False when the server was built without
        heartbeat reaping, the request was anonymous, or the worker is
        not (yet) registered."""
        reg = getattr(server, "workers", None)
        if reg is None or not wid:
            return False
        with server.workers_lock:
            rec = reg.get(wid)
            if rec is None:
                if not register:
                    return False
                rec = reg[wid] = {"last": 0.0, "leases": set()}
            rec["last"] = time.monotonic()
            if add_lease is not None:
                rec["leases"].add(add_lease)
            if drop_lease is not None:
                rec["leases"].discard(drop_lease)
            return True

    @staticmethod
    def _dispatch(master: Master, req: dict, server=None) -> dict:
        method = req.get("method")
        wid = str(req.get("worker") or "")
        if method == "get_task":
            t = master.get_task()
            if t is None:
                _Handler._touch_worker(server, wid)
                return {"ok": True, "task": None, "done": master.done}
            try:
                _Handler._persist(master, server)   # the new lease
            except Exception:
                # the worker will never see this lease — fail it back to
                # the queue NOW instead of stranding the chunk for a
                # full lease window (disk trouble must not stall drains)
                master.task_failed(t)
                LEASES_FAILED_BACK.labels(cause="persist_error").inc()
                raise
            _Handler._touch_worker(server, wid, add_lease=(t.id, t.epoch))
            LEASES_GRANTED.inc()
            return {"ok": True, "done": False,
                    "task": {"id": t.id, "epoch": t.epoch, "path": t.path,
                             "chunk_begin": t.chunk_begin,
                             "chunk_end": t.chunk_end}}
        if method in ("task_finished", "task_failed"):
            t = Task(int(req["id"]), int(req["epoch"]), "", 0, 0)
            fn = (master.task_finished if method == "task_finished"
                  else master.task_failed)
            accepted = bool(fn(t))
            if accepted:
                _Handler._persist(master, server)
                (TASKS_FINISHED if method == "task_finished"
                 else LEASES_FAILED_BACK.labels(cause="report")).inc()
            else:
                STALE_REPORTS.inc()
            _Handler._touch_worker(server, wid, drop_lease=(t.id, t.epoch))
            return {"ok": True, "accepted": accepted}
        if method == "heartbeat":
            # liveness signal — the one request that REGISTERS a worker
            # for reaping: lets the reaper re-issue a silent worker's
            # leases well before the full lease timeout (the reference
            # only discovers dead workers by lease expiry,
            # go/master checkTimeoutFunc)
            HEARTBEATS.inc()
            return {"ok": True, "beat": _Handler._touch_worker(
                server, wid, register=True)}
        if method == "workers":
            reg = getattr(server, "workers", None)
            if reg is None:
                return {"ok": True, "workers": None}
            now = time.monotonic()
            with server.workers_lock:
                return {"ok": True, "workers": {
                    w: {"age_s": now - rec["last"],
                        "leases": len(rec["leases"])}
                    for w, rec in reg.items()}}
        if method == "stats":
            s = master.stats()
            s["done_flag"] = master.done
            return {"ok": True, "stats": s}
        if method == "snapshot":
            # The wire protocol is unauthenticated: a client-chosen
            # server-side path would be an arbitrary-file-write primitive
            # on the master host. Snapshots land under the directory the
            # SERVER configured (basename of the client's path only);
            # with no snapshot_root the method is disabled — the hosting
            # process can always call master.snapshot() directly.
            root = getattr(server, "snapshot_root", None)
            if root is None:
                return {"ok": False, "error":
                        "snapshot over the wire is disabled: construct "
                        "MasterServer(snapshot_root=dir) to enable it, "
                        "or snapshot from the hosting process"}
            fname = os.path.basename(
                str(req.get("path", ""))) or "master_snapshot.json"
            path = os.path.join(root, fname)
            master.snapshot(path)
            return {"ok": True, "path": path}
        if method == "ping":
            return {"ok": True, "pong": True}
        return {"ok": False, "error": f"unknown method {method!r}"}


class MasterServer:
    """Host a Master behind the JSON/TCP protocol (rank-0 side).

        m = Master(timeout_s=2.0)
        m.set_dataset(files)
        srv = MasterServer(m)          # serves on an ephemeral port
        os.environ[MASTER_ENV] = srv.endpoint
        ... spawn workers ...
        srv.stop()
    """

    def __init__(self, master: Master, host: str = "127.0.0.1",
                 port: int = 0, snapshot_root: Optional[str] = None,
                 snapshot_path: Optional[str] = None,
                 heartbeat_timeout_s: Optional[float] = None,
                 reap_interval_s: Optional[float] = None):
        """``snapshot_root``: directory wire-requested snapshots are
        confined to (clients name only the file). None (default)
        disables the wire ``snapshot`` method entirely.

        ``snapshot_path``: the served queue's durability file — the etcd
        analogue (reference: go/master/service.go:165 the master
        recovers its state from the etcd snapshot on start, :207 it
        persists each state change). When set: at construction the
        master RECOVERS from the newest snapshot that passes
        ``verify_snapshot`` — the file itself, or the rotated ``.prev``
        when the newest was torn mid-record (a restarted master resumes
        the drain in place — pending leases survive with their epochs,
        so in-flight workers' reports are still accepted exactly-once);
        every accepted lease/report is then snapshotted back atomically
        before its reply is sent.

        ``heartbeat_timeout_s``: enable the worker heartbeat registry —
        clients that REGISTER by heartbeating (``MasterClient.
        start_heartbeat()``; a worker_id alone does not opt in) and then
        go silent for longer than this have their outstanding leases
        failed back to the queue by a background reaper, re-issuing the
        chunks well before the C++ lease timeout fires. The lease epoch
        keeps this safe: if the "dead" worker was merely slow, its late
        report is rejected as stale — a chunk is never counted twice.
        Workers that never beat keep pure lease-expiry semantics.
        ``reap_interval_s`` defaults to a quarter of the heartbeat
        timeout."""
        self.master = master
        if snapshot_root is not None:
            os.makedirs(snapshot_root, exist_ok=True)
        if snapshot_path:
            self._recover_newest_verified(master, snapshot_path)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

            def server_bind(self):
                # SO_REUSEPORT set explicitly (socketserver's
                # allow_reuse_port attr only works on py3.11+): lets a
                # restarted master rebind the advertised port through a
                # held PortReservation (paddle_tpu.utils.net) immediately
                try:
                    self.socket.setsockopt(socket.SOL_SOCKET,
                                           socket.SO_REUSEPORT, 1)
                except (AttributeError, OSError):
                    pass    # platform without SO_REUSEPORT
                super().server_bind()

        self._server = _Server((host, port), _Handler)
        self._server.master = master  # type: ignore[attr-defined]
        self._server.snapshot_root = snapshot_root  # type: ignore
        self._server.snapshot_path = snapshot_path  # type: ignore
        self._hb_timeout = heartbeat_timeout_s
        self._reap_stop = threading.Event()
        self._reaper: Optional[threading.Thread] = None
        if heartbeat_timeout_s is not None:
            self._server.workers = {}  # type: ignore[attr-defined]
            self._server.workers_lock = lock_witness.make_lock(  # type: ignore
                "MasterServer.workers_lock")
            self._reap_interval = (reap_interval_s
                                   if reap_interval_s is not None
                                   else heartbeat_timeout_s / 4.0)
        else:
            self._server.workers = None  # type: ignore[attr-defined]
        if snapshot_path:
            # durable from the very first moment served — a crash before
            # the first report must still recover the full queue
            master.snapshot(snapshot_path)
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True)
        self._thread.start()
        if heartbeat_timeout_s is not None:
            self._reaper = threading.Thread(target=self._reap_loop,
                                            daemon=True)
            self._reaper.start()

    @staticmethod
    def _recover_newest_verified(master: Master, snapshot_path: str):
        """Recover from the NEWEST snapshot that passes
        :func:`verify_snapshot`: the primary file first, then the
        rotated ``.prev`` (one snapshot behind — the newest state every
        in-flight lease was acked against before the torn write). A
        torn newest + verified .prev counts a fallback; candidates that
        exist but all fail verification raise instead of silently
        serving a fresh (empty) queue over a durable-looking path."""
        candidates = [snapshot_path, snapshot_path + ".prev"]
        existing = [p for p in candidates if os.path.exists(p)]
        if not existing:
            return                       # cold start: nothing durable yet
        for i, p in enumerate(existing):
            if verify_snapshot(p):
                master.recover(p)
                if i > 0:
                    SNAPSHOT_FALLBACK.inc()
                    from paddle_tpu.observability import flight_recorder
                    flight_recorder.note(
                        "snapshot_fallback", corrupt=existing[0],
                        recovered_from=p)
                return
        raise IOError(
            f"no verifiable master snapshot among {existing}: refusing "
            f"to serve an empty queue over a durable path (delete the "
            f"files to start fresh)")

    def _reap_loop(self):
        """Fail the outstanding leases of workers whose heartbeat went
        silent — the chunk re-issues to a survivor immediately instead of
        stranding for the full lease window. Epoch checks make a racing
        late report stale, never double-counted."""
        while not self._reap_stop.wait(self._reap_interval):
            now = time.monotonic()
            dead = []
            with self._server.workers_lock:
                oldest = 0.0
                for wid, rec in list(self._server.workers.items()):
                    age = now - rec["last"]
                    if age > self._hb_timeout:
                        dead.append((wid, set(rec["leases"])))
                        del self._server.workers[wid]
                    elif age > oldest:
                        oldest = age
            HEARTBEAT_AGE.set(oldest)
            changed = False
            for wid, leases in dead:
                WORKERS_REAPED.inc()
                for tid, epoch in leases:
                    if self.master.task_failed(Task(tid, epoch, "", 0, 0)):
                        changed = True
                        LEASES_FAILED_BACK.labels(cause="reaped").inc()
            if changed and getattr(self._server, "snapshot_path", None):
                try:
                    self.master.snapshot(self._server.snapshot_path)
                except Exception:
                    pass   # next accepted report persists the state

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address[:2]

    @property
    def endpoint(self) -> str:
        host, port = self.address
        return f"{host}:{port}"

    def stop(self):
        self._reap_stop.set()
        if self._reaper is not None:
            self._reaper.join(timeout=5)
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


class MasterClient:
    """Trainer-side proxy with the same duck interface as ``Master``, so
    ``task_reader(MasterClient(...))`` is the multi-worker form of the
    single-process loop (reference: go/master/client.go dials the service
    and calls GetTask/TaskFinished/TaskFailed over net/rpc).

    One persistent connection per client; on socket failure every call
    reconnects with exponential backoff until ``reconnect_timeout_s``
    elapses — a master that dies and is restarted from its snapshot on
    the same endpoint (MasterServer(snapshot_path=...)) looks like a
    brief outage to workers, the analogue of the reference clients
    watching the master's etcd key and re-dialing the new address
    (go/master/etcd_client.go:191 watchKey).
    """

    def __init__(self, endpoint: Optional[str] = None,
                 timeout_s: float = 30.0,
                 reconnect_timeout_s: float = 60.0,
                 max_attempts: int = 256,
                 retry_policy: Optional[RetryPolicy] = None,
                 worker_id: Optional[str] = None):
        """``max_attempts``/``reconnect_timeout_s`` bound the retry budget
        (whichever exhausts first raises :class:`MasterUnavailableError`);
        ``retry_policy`` overrides both with a fully custom policy.

        ``worker_id`` stamps every request with this client's identity;
        the first :meth:`heartbeat` (see :meth:`start_heartbeat`) then
        REGISTERS it in the server's reaping registry so a server built
        with ``heartbeat_timeout_s`` re-issues this worker's leases
        quickly if it goes silent. An id without beats — or no id at
        all — keeps pure lease-expiry semantics."""
        endpoint = endpoint or os.environ.get(MASTER_ENV)
        if not endpoint:
            raise ValueError(
                f"no master endpoint: pass one or set {MASTER_ENV}")
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = timeout_s
        self._reconnect_timeout = reconnect_timeout_s
        self._retry = retry_policy or RetryPolicy(
            max_attempts=max_attempts, base_delay_s=0.05, max_delay_s=1.0,
            deadline_s=reconnect_timeout_s,
            retryable=(ConnectionError, OSError, json.JSONDecodeError))
        self.worker_id = worker_id
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_retry: Optional[RetryPolicy] = None
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._lock = lock_witness.make_lock("MasterClient._lock")
        self._last_done = False   # done flag from the last get_task reply
        self._polled = False

    # -- wire ------------------------------------------------------------
    def _connect(self, timeout: Optional[float] = None):
        self._close_sock()
        s = socket.create_connection(
            self._addr, timeout=self._timeout if timeout is None
            else min(timeout, self._timeout))
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        self._rfile = s.makefile("rb")

    def _close_sock(self):
        for obj in (self._rfile, self._sock):
            if obj is not None:
                try:
                    obj.close()
                except OSError:
                    pass
        self._sock = self._rfile = None

    def _call(self, req: dict, idempotent: bool = True,
              retry: Optional[RetryPolicy] = None,
              op_timeout_s: Optional[float] = None) -> dict:
        """One request/reply, retried under the client's
        :class:`RetryPolicy` (exponential backoff, full jitter, bounded
        by both attempt count and ``reconnect_timeout_s``). A spent
        budget raises :class:`MasterUnavailableError` naming the endpoint
        and attempt count instead of the opaque socket error it used to.

        Delivery is AT-LEAST-ONCE for every method, including the report
        RPCs (``idempotent`` is kept for signature stability): a resend
        whose original did land is rejected by the lease-epoch check and
        surfaces as ``accepted: false`` — the same benign answer a stale
        report gets, and one every caller already tolerates (the chunk
        is either already done or will re-issue). Application at the
        master is therefore at-most-once, and with the server's persist
        -before-reply ordering an acked report is never lost across a
        master restart."""
        if self.worker_id and "worker" not in req:
            req = dict(req, worker=self.worker_id)

        def attempt():
            try:
                if self._sock is None:
                    self._connect(timeout=op_timeout_s)
                if op_timeout_s is not None:
                    # bound THIS op's socket waits (heartbeats: a beat
                    # against a blackholed master must not hold the
                    # client lock for the full timeout_s)
                    self._sock.settimeout(op_timeout_s)
                faults.inject("master.rpc.send")
                self._sock.sendall((json.dumps(req) + "\n").encode())
                faults.inject("master.rpc.recv")
                line = self._rfile.readline()
                if not line:
                    raise ConnectionError("master closed connection")
                resp = json.loads(line)
                if op_timeout_s is not None:
                    # the connection is shared: restore the default
                    # timeout for whatever RPC reuses it next
                    self._sock.settimeout(self._timeout)
            except (ConnectionError, OSError, json.JSONDecodeError):
                self._close_sock()    # next attempt re-dials
                raise
            if not resp.get("ok"):
                # a server-side error is not a connectivity problem:
                # surface it immediately (non-retryable)
                raise RuntimeError(f"master error: {resp.get('error')}")
            return resp

        # one client span per LOGICAL call (retries included); the
        # traceparent is injected while it is current, so master-side
        # spans — heartbeats included — parent under this worker's span
        with tctx.client_span("master." + str(req.get("method"))):
            tctx.inject(req)
            with self._lock:
                try:
                    return (retry or self._retry).call(
                        attempt, what=str(req.get("method")))
                except RetryError as e:
                    raise MasterUnavailableError(
                        f"{self._addr[0]}:{self._addr[1]}", e.attempts,
                        e.elapsed_s, e.__cause__) from e.__cause__

    # -- Master duck interface ------------------------------------------
    def get_task(self) -> Optional[Task]:
        # Retried after connection loss even though a lost-reply retry can
        # strand the first lease: the orphan simply expires and Requeue
        # counts one failure — identical to how the reference accounts a
        # timed-out lease (go/master checkTimeoutFunc increments
        # NumFailure), so a dropped reply behaves like a briefly-dead
        # worker rather than crashing this one.
        resp = self._call({"method": "get_task"})
        self._last_done = bool(resp.get("done"))
        self._polled = True
        t = resp.get("task")
        if t is None:
            return None
        return Task(t["id"], t["epoch"], t["path"],
                    t["chunk_begin"], t["chunk_end"])

    def task_finished(self, task: Task) -> bool:
        return bool(self._call({"method": "task_finished", "id": task.id,
                                "epoch": task.epoch},
                               idempotent=False)["accepted"])

    def task_failed(self, task: Task) -> bool:
        return bool(self._call({"method": "task_failed", "id": task.id,
                                "epoch": task.epoch},
                               idempotent=False)["accepted"])

    @property
    def done(self) -> bool:
        # every get_task reply carries the done flag — reuse it instead of
        # a second round trip per idle poll; fall back to a stats RPC only
        # before the first poll
        if self._polled:
            return self._last_done
        return bool(self._call({"method": "stats"})["stats"]["done_flag"])

    def stats(self) -> dict:
        s = self._call({"method": "stats"})["stats"]
        s.pop("done_flag", None)
        return s

    def snapshot(self, path: str):
        """Ask the server to snapshot its queue. Only ``basename(path)``
        is honored, under the server's configured snapshot_root —
        disabled unless the server was built with one."""
        self._call({"method": "snapshot", "path": path})

    def ping(self) -> bool:
        try:
            return bool(self._call({"method": "ping"}).get("pong"))
        except Exception:
            return False

    # -- liveness ---------------------------------------------------------
    def heartbeat(self) -> bool:
        """One liveness beat to the server's worker registry (requires a
        ``worker_id``; a server without heartbeat reaping replies
        ``beat: false`` and the beat is a harmless ping). Beats get a
        near-zero retry budget AND a ~1s socket timeout on purpose: a
        beat must never hold the client lock for the full connect/read
        budget during an outage (blackholed master included) — losing
        one is fine, the next tick replaces it."""
        if not self.worker_id:
            self.worker_id = uuid.uuid4().hex
        if self._hb_retry is None:
            self._hb_retry = RetryPolicy(
                max_attempts=2, base_delay_s=0.01, max_delay_s=0.05,
                deadline_s=1.0,
                retryable=(ConnectionError, OSError,
                           json.JSONDecodeError))
        return bool(self._call({"method": "heartbeat"},
                               retry=self._hb_retry,
                               op_timeout_s=1.0).get("beat"))

    def start_heartbeat(self, interval_s: float = 1.0):
        """Beat in the background until :meth:`close`. The FIRST beat is
        sent synchronously so the registration precedes any lease this
        worker takes afterwards — a lease leased before the worker is
        registered is invisible to the reaper (it falls back to plain
        lease-expiry). Subsequent beats are best-effort: one lost to a
        master outage is replaced by the next tick (the reaper tolerates
        gaps up to its heartbeat timeout)."""
        if self._hb_thread is not None:
            return
        if not self.worker_id:
            self.worker_id = uuid.uuid4().hex
        try:
            self.heartbeat()          # register before the first lease
        except Exception:
            pass                      # master briefly away: next tick

        def loop():
            while not self._hb_stop.wait(interval_s):
                try:
                    self.heartbeat()
                except Exception:
                    pass

        self._hb_thread = threading.Thread(target=loop, daemon=True)
        self._hb_thread.start()

    def workers(self) -> Optional[dict]:
        """Heartbeat registry snapshot ({worker_id: {age_s, leases}}), or
        None when the server runs without heartbeat reaping."""
        return self._call({"method": "workers"}).get("workers")

    def close(self):
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None
        with self._lock:
            self._close_sock()
