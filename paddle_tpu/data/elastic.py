"""Elastic training loop: chunk-lease data master + async checkpoint-restart
(reference: the v2/EDL capability — go/master/service.go task leasing with
timeout/retry + etcd snapshot/recover, go/pserver checkpointing; SURVEY §5
'failure detection / elastic recovery': the TPU-idiomatic replacement is
coordination-service health + checkpoint-restart, with the chunk-lease
master preserved for input-pipeline elasticity).

`ElasticTrainer.run()` is restartable: on every (re)start it recovers the
master's task queue snapshot and the latest complete model checkpoint, so a
crashed worker resumes exactly where the surviving state says — finished
chunks are never re-trained, leased-but-unfinished chunks are re-issued
after their lease times out (service.go:366 GetTask / :455 TaskFailed
semantics)."""

from __future__ import annotations

import os
import time
from typing import Callable, List, Optional

from paddle_tpu.data.master import Master


class ElasticTrainer:
    """Restartable chunk-driven training loop."""

    def __init__(self, work_dir: str, paths: Optional[List[str]] = None,
                 chunks_per_task: Optional[int] = None,
                 lease_timeout_s: Optional[float] = None,
                 checkpoint_every: int = 1, max_to_keep: int = 3,
                 master=None, poll_interval_s: float = 0.05,
                 max_poll_interval_s: float = 1.0):
        """``master=None`` (single-worker): an in-process Master owning
        the queue, recovered from/snapshotted to work_dir. ``master=``
        a MasterClient (or any Master duck): MULTI-WORKER mode — N
        elastic trainers drain the one served queue (reference: EDL
        trainers share the go/master service); queue durability then
        belongs to the process hosting the MasterServer — construct it
        with ``snapshot_path=`` and it persists every accepted
        lease/report and recovers on restart (master failover,
        tests/test_master_failover.py) — so this worker skips queue
        snapshots and only writes model checkpoints.

        Each worker must own its model Scope (EDL trainers own their
        replica; shared state belongs on a pserver): two workers
        training against ONE scope race the step's buffer donation
        against the checkpoint's device-to-host reads (measured: TPU
        backend InvalidArgument on the donated array).

        DURABILITY PROTOCOL (multi-worker): task_finished is reported
        when the chunk is TRAINED, before this worker's async checkpoint
        of it is durable — so worker-local checkpoints alone cannot
        carry the never-lose-an-update invariant the single-owner mode
        orders explicitly (snapshot-after-_COMPLETE below). Multi-worker
        model durability must live on the shared parameter plane, which
        survives any worker's death: an AsyncPServer (the reference's
        answer — go/pserver holds the updates the moment gradients
        apply; tests/test_edl_integration.py), or sync-dp where every
        worker holds identical state and any survivor's checkpoint is
        the model's. Worker-local checkpoints here are restart
        accelerators, not the source of truth.

        ``poll_interval_s``/``max_poll_interval_s``: the idle poll when
        nothing is leasable starts at the former and backs off
        exponentially (capped at the latter), resetting on every granted
        lease — a worker waiting out other workers' leases doesn't spin
        the master at a fixed cadence. Worker-loop knobs, so they remain
        valid together with ``master=``."""
        # None-sentinel defaults so EXPLICITLY passing a queue-config arg
        # together with master= always raises — even if the value happens
        # to equal the single-worker default
        if master is not None and not (
                paths is None and chunks_per_task is None
                and lease_timeout_s is None):
            raise ValueError(
                "ElasticTrainer(master=...) uses the served queue: "
                "paths/chunks_per_task/lease_timeout_s belong to the "
                "process hosting the MasterServer, not this worker")
        paths = () if paths is None else paths
        chunks_per_task = 1 if chunks_per_task is None else chunks_per_task
        lease_timeout_s = 60.0 if lease_timeout_s is None else lease_timeout_s
        from paddle_tpu.fluid.io import AsyncCheckpointer
        self.work_dir = work_dir
        os.makedirs(work_dir, exist_ok=True)
        self._snap_path = os.path.join(work_dir, "master_snapshot.json")
        self._poll_s = float(poll_interval_s)
        self._max_poll_s = max(float(max_poll_interval_s), self._poll_s)
        self._sleep = time.sleep     # injectable for deterministic tests
        self._owns_master = master is None
        if master is not None:
            self.master = master
        else:
            # a crash between Master.snapshot(tmp) and the checkpointer's
            # _promote leaks master_snapshot.json.tmp<serial> files — at
            # startup no save is in flight, so any survivor is garbage
            import glob
            for orphan in glob.glob(glob.escape(self._snap_path) + ".tmp*"):
                try:
                    os.remove(orphan)
                except OSError:
                    pass
            self.master = Master(timeout_s=lease_timeout_s)
            if os.path.exists(self._snap_path):
                # resume: finished chunks stay finished, leases reset
                self.master.recover(self._snap_path)
            else:
                real = [p for p in paths if os.path.exists(p)]
                if real:
                    self.master.set_dataset(real, chunks_per_task)
                # logical shard names (non-file work units) become
                # 1-chunk tasks
                for p in paths:
                    if p not in real:
                        self.master.add_task(p, 0, 1)
        self.ckpt = AsyncCheckpointer(os.path.join(work_dir, "ckpt"),
                                      max_to_keep=max_to_keep)
        self.checkpoint_every = checkpoint_every
        self._serial = (self.ckpt.serials() or [-1])[-1]

    def restore_model(self, executor=None, main_program=None,
                      scope=None) -> Optional[int]:
        """Load the latest complete checkpoint, if any."""
        if self.ckpt.serials():
            return self.ckpt.restore(executor, main_program=main_program,
                                     scope=scope)
        return None

    def run(self, train_chunk: Callable, main_program=None, scope=None):
        """train_chunk(task) -> None; called once per leased task. The
        model checkpoint + master snapshot are written after every
        `checkpoint_every` finished tasks, checkpoint serialization off the
        training thread."""
        stats = self.master.stats()
        if stats["todo"] + stats["pending"] + stats["done"] == 0:
            return        # nothing to train (empty task list) — not done-able
        done_since_ckpt = 0
        idle_s = self._poll_s
        while not self.master.done:
            task = self.master.get_task()
            if task is None:
                # nothing leasable right now (all leased elsewhere or
                # awaiting timeout) — in-process single worker: just stop
                # if also nothing pending. Capped exponential backoff:
                # long waits (another worker's lease expiring) shouldn't
                # poll the master at the granted-lease cadence
                if self.master.done:
                    break
                self._sleep(idle_s)
                idle_s = min(idle_s * 2, self._max_poll_s)
                continue
            idle_s = self._poll_s       # work granted: reset the backoff
            try:
                train_chunk(task)
            except Exception:
                self.master.task_failed(task)
                raise
            self.master.task_finished(task)
            done_since_ckpt += 1
            if done_since_ckpt >= self.checkpoint_every:
                self._serial += 1
                if not self._owns_master:
                    # external (served) master: checkpoint the model only;
                    # queue durability is the master host's job
                    self.ckpt.save(self._serial,
                                   main_program=main_program, scope=scope)
                    done_since_ckpt = 0
                    continue
                # the queue snapshot must only become durable AFTER the
                # model checkpoint it corresponds to (else a crash between
                # them marks chunks done whose weight updates were lost).
                # Capture the queue state NOW to a temp file; the rename to
                # the live path runs on the checkpointer's thread after the
                # _COMPLETE marker — strict ordering with no training stall.
                # per-serial temp file: the previous save's background
                # thread may still be about to promote its own snapshot
                tmp = f"{self._snap_path}.tmp{self._serial}"
                self.master.snapshot(tmp)

                def _promote(tmp=tmp):
                    os.replace(tmp, self._snap_path)

                self.ckpt.save(self._serial, main_program=main_program,
                               scope=scope, on_complete=_promote)
                done_since_ckpt = 0
        self.ckpt.wait()
        if self._owns_master:
            self.master.snapshot(self._snap_path)
