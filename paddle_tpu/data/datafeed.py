"""MultiSlot DataFeed + AsyncExecutor-style file-fed training
(reference: framework/data_feed.h:49,224 MultiSlotDataFeed + data_feed.proto
slot schema; framework/async_executor.cc RunFromFile with
ExecutorThreadWorker file sharding, executor_thread_worker.h:136).

Native worker threads (csrc/paddle_tpu_native.cc MultiSlotFeed) parse
slotted text files into batches behind a blocking queue; Python converts
each wire batch to the padded-[B,T]+seq_lens LoD form and feeds the
compiled step. The reference ran one interpreter per thread; on TPU the
chip is the serial resource, so N parse threads + 1 device loop is the
idiomatic shape (parsing overlaps device execution)."""

from __future__ import annotations

import ctypes
import struct
from typing import Dict, List, Optional

import numpy as np

from paddle_tpu.core import native


class DataFeedDesc:
    """Slot schema (reference: data_feed.proto / python DataFeedDesc).
    slots: list of dicts {name, type: "uint64"|"float32", dense: bool,
    max_len: padding target for sparse slots (default: batch max)}."""

    def __init__(self, slots: List[dict], batch_size: int = 32):
        self.slots = slots
        self.batch_size = batch_size

    def _wire_desc(self) -> str:
        parts = []
        for s in self.slots:
            ty = "f32" if s.get("type", "uint64").startswith("float") \
                else "u64"
            parts.append(f"{s['name']}:{ty}:{int(bool(s.get('dense')))}")
        return ";".join(parts)


class MultiSlotDataFeed:
    """Iterate batches parsed from slotted text files by native threads."""

    def __init__(self, desc: DataFeedDesc, filelist: List[str],
                 nthreads: int = 2, queue_capacity: int = 8):
        if not native.available():
            raise native.NativeUnavailable(
                "MultiSlotDataFeed requires the native runtime")
        self._desc = desc
        self._h = native.lib().ptpu_feed_new(
            desc._wire_desc().encode(), desc.batch_size, queue_capacity)
        for f in filelist:
            native.lib().ptpu_feed_add_file(self._h, f.encode())
        self._nthreads = nthreads
        self._started = False

    def __iter__(self):
        if self._h is None:
            raise RuntimeError(
                "MultiSlotDataFeed is single-pass: the native feed was "
                "already consumed/closed — construct a new one per epoch "
                "(the reference DataFeed is likewise re-created per pass)")
        if self._started:
            raise RuntimeError("MultiSlotDataFeed already iterating")
        native.lib().ptpu_feed_start(self._h, self._nthreads)
        self._started = True
        out = ctypes.POINTER(ctypes.c_char)()
        try:
            while True:
                n = native.lib().ptpu_feed_next(self._h, ctypes.byref(out))
                if n < 0:
                    break
                yield self._parse(native.take_buffer(out, n))
        finally:
            # runs on exhaustion AND on generator close (early break/GC):
            # joins worker threads and frees the native handle
            h, self._h = self._h, None
            native.lib().ptpu_feed_free(h)

    def _parse(self, wire: bytes) -> Dict[str, np.ndarray]:
        """Wire batch -> {slot: padded array (+ slot__lens for sparse)}."""
        off = 0
        (n_slots,) = struct.unpack_from("<I", wire, off)
        off += 4
        batch = {}
        max_lens = {s["name"]: s.get("max_len") for s in self._desc.slots}
        dense = {s["name"]: bool(s.get("dense")) for s in self._desc.slots}
        for _ in range(n_slots):
            (name_len,) = struct.unpack_from("<I", wire, off)
            off += 4
            name = wire[off:off + name_len].decode()
            off += name_len
            dtype = wire[off]
            off += 1
            (rows,) = struct.unpack_from("<I", wire, off)
            off += 4
            lens = np.frombuffer(wire, "<u4", rows, off).astype(np.int32)
            off += 4 * rows
            (total,) = struct.unpack_from("<Q", wire, off)
            off += 8
            if dtype == 0:
                vals = np.frombuffer(wire, "<i8", total, off)
                off += 8 * total
            else:
                vals = np.frombuffer(wire, "<f4", total, off)
                off += 4 * total
            if dense[name]:
                width = lens[0] if rows else 0
                batch[name] = vals.reshape(rows, width)
            else:
                # ragged -> padded [B, T] + lens (the LoD form)
                T = int(max_lens[name] or (lens.max() if rows else 1) or 1)
                arr = np.zeros((rows, T), dtype=vals.dtype)
                pos = 0
                for r, l in enumerate(lens):
                    k = min(int(l), T)
                    arr[r, :k] = vals[pos:pos + k]
                    pos += int(l)
                batch[name] = arr
                batch[name + "__lens"] = np.minimum(lens, T).astype(np.int32)
        return batch


class AsyncExecutor:
    """reference: fluid.AsyncExecutor (python/paddle/fluid/async_executor.py
    → framework/async_executor.cc). run() trains a program from slotted
    text files: native threads parse; the device loop consumes. The PSlib
    parameter-server integration (InitServer/InitWorker) is delivered by
    mesh-sharded params instead (see paddle_tpu.parallel)."""

    def __init__(self, place=None):
        from paddle_tpu.core.executor import Executor, TPUPlace
        self._exe = Executor(place or TPUPlace())

    def run(self, program, data_feed: DataFeedDesc, filelist: List[str],
            thread_num: int = 2, fetch: Optional[List] = None,
            feed_mapping: Optional[Dict[str, str]] = None,
            scope=None, debug: bool = False):
        """feed_mapping: {program feed name: slot name or slot__lens}."""
        fetch = fetch or []
        fetch_names = [getattr(v, "name", v) for v in fetch]
        feed_it = MultiSlotDataFeed(data_feed, filelist, thread_num)
        results = []
        for batch in feed_it:
            if feed_mapping:
                feed = {dst: batch[src]
                        for dst, src in feed_mapping.items()}
            else:
                feed = {k: v for k, v in batch.items()
                        if not k.endswith("__lens")}
            vals = self._exe.run(program, feed=feed,
                                 fetch_list=fetch_names, scope=scope)
            if fetch_names:
                results.append([np.asarray(v) for v in vals])
            if debug and results:
                print(f"async_executor batch {len(results)}: "
                      f"{[float(v.reshape(-1)[0]) for v in results[-1]]}")
        return results
