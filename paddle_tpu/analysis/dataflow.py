"""Dataflow analyses: liveness, write hazards, RNG determinism.

These rules look at how values flow through the op list rather than at
individual op well-formedness:

- ``dead-op`` / ``unused-output`` — liveness against the declared fetch
  set, mirroring exactly what ``lowering.analyze_block`` will prune;
- ``waw-param`` — write-after-write hazards on parameters outside the
  optimizer-apply ops (a param clobbered by two non-optimizer writes is
  almost always a transpiler/pass bug);
- ``unfed-input`` — a live op reads a non-persistable var that is
  neither fed nor produced (the exact case ``CompiledBlock`` dies on
  with a RuntimeError at dispatch);
- ``rng-in-inference`` — ``step_key``-consuming ops (dropout, sampling)
  in an ``is_test`` program make inference nondeterministic across
  steps.
"""

from __future__ import annotations

from typing import Dict, Tuple

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity
from paddle_tpu.analysis.rules import (SKIPPED_OPS, AnalysisContext,
                                       register_rule)
from paddle_tpu.core.registry import get_op, has_op

# ops consuming EmitContext.step_key (fresh randomness per executed
# step). `gated` ops disable their randomness themselves under
# is_test (ctx.is_test or the is_test attr); ungated ops draw random
# bits in inference programs unconditionally.
_RNG_OPS: Dict[str, bool] = {          # type -> self-gates on is_test
    "dropout": True,
    "fused_attention_block": True,
    "attention": True,
    "nce": False,
    "sampling_id": False,
    "random_crop": False,
    "generate_proposal_labels": False,
    "rpn_target_assign": False,
}


def _is_optimizer_apply(op_type: str) -> bool:
    """True for the optimizer-apply emitters (ops/optimizer_ops.py) —
    the one family allowed to rewrite parameters in place."""
    if not has_op(op_type):
        return False
    mod = getattr(get_op(op_type).emit, "__module__", "")
    return mod.endswith(".optimizer_ops")


@register_rule("dead-op", Severity.WARNING,
               "op contributes to no fetch and writes no persistable "
               "state — lowering prunes it silently; if it was meant to "
               "run, a fetch or persistable flag is missing",
               category="dataflow")
def _dead_op(ctx: AnalysisContext):
    live = ctx.live_ops()
    if live is None:                       # fetch set unknown: skip
        return
    block = ctx.program.global_block
    for oi, op in enumerate(block.ops):
        if op.type in SKIPPED_OPS or oi in live:
            continue
        yield Diagnostic(
            rule="dead-op", severity=Severity.WARNING,
            message=f"op {op.type!r} is dead for fetches "
                    f"{list(ctx.fetch_names)}: its outputs "
                    f"{op.output_names()} reach no fetch and update no "
                    f"persistable var",
            block_idx=0, op_index=oi, op_type=op.type)


# output slots that are auxiliary by op convention (the reference emits
# them for the grad op or for optional metrics; consumers routinely
# ignore them) — not worth an unused-output finding
_AUX_OUTPUT_SLOTS: Dict[str, Tuple[str, ...]] = {
    "batch_norm": ("SavedMean", "SavedVariance"),
    "dropout": ("Mask",),
    "softmax_with_cross_entropy": ("Softmax",),
    "accuracy": ("Correct", "Total"),
    "top_k": ("Indices",),
    "linear_chain_crf": ("Alpha", "EmissionExps", "TransitionExps"),
    "nce": ("SampleLogits", "SampleLabels"),
    "chunk_eval": ("Precision", "Recall", "F1-Score", "NumInferChunks",
                   "NumLabelChunks", "NumCorrectChunks"),
    "layer_norm": ("Mean", "Variance"),
    "dynamic_lstm": ("Cell", "LastHidden", "LastCell"),
    "dynamic_gru": ("LastHidden",),
    "sequence_pool": ("MaxIndex",),
    "cos_sim": ("XNorm", "YNorm"),
    "hierarchical_sigmoid": ("PreOut",),
}


@register_rule("unused-output", Severity.INFO,
               "a live op output is never read, fetched, or persisted — "
               "harmless (XLA drops it) but often a sign of a wrong "
               "slot name", category="dataflow")
def _unused_output(ctx: AnalysisContext):
    live = ctx.live_ops()
    if live is None:
        return
    fetches = set(ctx.fetch_names)
    for oi in sorted(live):
        op = ctx.program.global_block.ops[oi]
        for slot, names in op.outputs.items():
            if slot in _AUX_OUTPUT_SLOTS.get(op.type, ()):
                continue
            for n in names:
                if n in fetches or ctx.readers[0].get(n):
                    continue
                if any(n in r for r in ctx.readers):
                    continue               # read from a sub-block
                vd = ctx.resolve(0, n)
                if vd is None or vd.persistable:
                    continue
                yield Diagnostic(
                    rule="unused-output", severity=Severity.INFO,
                    message=f"output slot {slot!r} var {n!r} is never "
                            f"consumed",
                    block_idx=0, op_index=oi, op_type=op.type, var=n)


@register_rule("waw-param", Severity.ERROR,
               "a parameter is written more than once by non-optimizer "
               "ops — the earlier write is clobbered (ERROR when no "
               "read intervenes, WARNING otherwise)",
               category="dataflow")
def _waw_param(ctx: AnalysisContext):
    for bi, block in enumerate(ctx.program.blocks):
        for name, vd in block.vars.items():
            if not vd.is_parameter:
                continue
            writes = [(i, block.ops[i]) for i in ctx.writers[bi].get(name, ())
                      if block.ops[i].type not in SKIPPED_OPS
                      and not _is_optimizer_apply(block.ops[i].type)]
            if len(writes) < 2:
                continue
            reads = ctx.readers[bi].get(name, [])
            for (i0, op0), (i1, op1) in zip(writes, writes[1:]):
                intervening = any(i0 < r <= i1 for r in reads)
                yield Diagnostic(
                    rule="waw-param",
                    severity=(Severity.WARNING if intervening
                              else Severity.ERROR),
                    message=f"parameter {name!r} written by op {i0} "
                            f"({op0.type!r}) is overwritten by op {i1} "
                            f"({op1.type!r})"
                            + (" with an intervening read"
                               if intervening else
                               " with no intervening read — the first "
                               "write is dead"),
                    block_idx=bi, op_index=i1, op_type=op1.type, var=name,
                    details={"first_write": i0, "second_write": i1,
                             "intervening_read": intervening})


@register_rule("unfed-input", Severity.ERROR,
               "a live op reads a non-persistable var that is neither "
               "fed nor produced by an earlier op — CompiledBlock "
               "raises at dispatch (\"neither fed nor initialized\")",
               category="dataflow")
def _unfed_input(ctx: AnalysisContext):
    live = ctx.live_ops()
    if live is None or ctx.feed_names is None:
        return
    block = ctx.program.global_block
    seen = set()
    for oi in sorted(live):
        op = block.ops[oi]
        for n in op.input_names():
            if n in ctx.feed_names or n in seen:
                continue
            writes = ctx.writers[0].get(n, [])
            if any(w < oi for w in writes):
                continue
            vd = ctx.resolve(0, n)
            if vd is None or vd.persistable:
                continue                   # dangling-input / scope var
            seen.add(n)
            yield Diagnostic(
                rule="unfed-input", severity=Severity.ERROR,
                message=f"var {n!r} is consumed by live op {oi} "
                        f"({op.type!r}) but is not in the feed list "
                        f"{sorted(ctx.feed_names)}, not persistable, "
                        f"and not produced earlier",
                block_idx=0, op_index=oi, op_type=op.type, var=n)


def _rng_active(op) -> bool:
    """Does this op actually draw step randomness given its attrs?"""
    t = op.type
    if t == "dropout":
        return not op.attrs.get("is_test") \
            and float(op.attrs.get("dropout_prob", 0.5)) > 0.0
    if t in ("fused_attention_block", "attention"):
        p = op.attrs.get("dropout_prob", op.attrs.get("dropout", 0.0))
        return not op.attrs.get("is_test") and float(p or 0.0) > 0.0
    if t == "nce":
        return op.attrs.get("seed") is None
    return True


@register_rule("rng-in-inference", Severity.WARNING,
               "a step_key-consuming op (dropout/sampling) appears in "
               "an is_test program — inference output varies across "
               "steps unless the op self-gates", category="dataflow")
def _rng_in_inference(ctx: AnalysisContext):
    if not ctx.is_test:
        return
    for bi, block in enumerate(ctx.program.blocks):
        for oi, op in enumerate(block.ops):
            gated = _RNG_OPS.get(op.type)
            if gated is None or not _rng_active(op):
                continue
            if gated:
                msg = (f"{op.type!r} is declared in train mode inside an "
                       f"is_test program; lowering forces it off "
                       f"(ctx.is_test), but the program should declare "
                       f"is_test=True explicitly")
            else:
                msg = (f"{op.type!r} draws fresh randomness every step — "
                       f"inference results will not be reproducible")
            yield Diagnostic(
                rule="rng-in-inference", severity=Severity.WARNING,
                message=msg, block_idx=bi, op_index=oi, op_type=op.type,
                details={"self_gating": bool(gated)})
