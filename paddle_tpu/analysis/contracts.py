"""Cross-view program contracts: one scope, many executables, one truth.

The decoder_lm serving family emits 8+ program views (full, prefill@P,
prefill/decode_slot, prefill/decode_paged, decode_verify[_paged]) that
all dispatch against ONE scope — the weights, KV pools and page pools
are shared state. Nothing in the per-program verifier can see the
hazards that live BETWEEN views: a persistable whose shape/dtype drifts
across builders, a startup whose rng-salted initializers slid to
different op indices (two views would disagree on the weights they
"share"), a buffer donated in-place by one view while a sibling still
treats it as a local temp, or geometry constants (n_slots, page_size,
spec_k, prompt buckets) copy-pasted out of sync.

Two surfaces:

- :func:`validate_geometry` — THE geometry record. Every decoder_lm
  view builder normalizes and validates its constants through this one
  function (satellite: the ad-hoc checks formerly inlined in
  ``models/transformer.py``) and stamps the resulting
  :class:`GeometryRecord` on the program, where the family verifier
  cross-checks it.
- :func:`verify_family` — given ``{key: (main, startup, feed_specs,
  fetch_name)}`` (the :func:`build_decoder_lm_programs` shape), run the
  cross-view contract rules and return ``Diagnostic`` records:

  ========================  =================================================
  rule                      contract
  ========================  =================================================
  ctr-view-var-drift        every shared persistable agrees on shape/dtype/
                            persistable/sharding mark across views
  ctr-salt-misalignment     rng-bearing startup initializers for shared
                            params sit at the same startup op index (rng is
                            salted per index — drift = different weights)
  ctr-stale-donation-read   a var mutated-in-place (donated state) by one
                            view is persistable scope state in EVERY sibling
                            that touches it — never a local temp or feed
                            (which would read a stale or freed buffer)
  ctr-geometry-drift        all views' stamped GeometryRecords agree, and
                            each view's feeds/pools are consistent with its
                            record (page_table width, K+1 window, slot count)
  ========================  =================================================

CLI: ``tools/proglint.py --contracts`` (default family:
``paddle_tpu.models.transformer:contracts_lint_family``). Checks are
counted in ``paddle_analysis_contract_checks_total{check}``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Tuple

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity
from paddle_tpu.analysis.rules import register_rule

DECODER_LM_MODES = ("full", "prefill", "decode", "prefill_slot",
                    "decode_slot", "prefill_paged", "decode_paged",
                    "decode_verify", "decode_verify_paged")

_KV_CODECS = ("none", "bf16", "int8")
_STORE_DTYPES = {"none": "float32", "bf16": "bfloat16", "int8": "int8"}


def declare_metrics():
    """Get-or-create the contract-check counter (also called from the
    exporters' catalog preregistration so a scrape shows it at zero)."""
    from paddle_tpu.observability import metrics as obs_metrics
    return obs_metrics.counter(
        "paddle_analysis_contract_checks_total",
        "cross-view program-contract checks performed (geometry "
        "normalizations and family-verifier rule runs)", ("check",))


def _count(check: str):
    try:
        declare_metrics().labels(check=check).inc()
    except Exception:
        pass


# ---------------------------------------------------------------------------
# the geometry record
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class GeometryRecord:
    """Normalized serving-geometry constants for ONE decoder_lm view.

    All derived values (cache_len default, paged pool sizing, the codec
    storage dtype, the verify window) come out of
    :func:`validate_geometry` — view builders consume this record
    instead of re-deriving, so the constants cannot drift apart."""

    mode: str
    prompt_len: int
    max_new: int
    cache_len: int
    n_slots: Optional[int] = None
    spec_k: Optional[int] = None          # verify views only
    page_size: Optional[int] = None       # paged views only
    n_pages: Optional[int] = None
    max_pages: Optional[int] = None       # pages of one worst-case slot
    kv_codec: Optional[str] = None
    store_dtype: Optional[str] = None

    @property
    def window(self) -> Optional[int]:
        """K+1: the verify window width, when this is a verify view."""
        return None if self.spec_k is None else self.spec_k + 1

    def as_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in (
            "mode", "prompt_len", "max_new", "cache_len", "n_slots",
            "spec_k", "page_size", "n_pages", "max_pages", "kv_codec",
            "store_dtype")}

    # fields every view of one family must agree on (prompt_len varies
    # per bucket; spec_k/page fields compare where present)
    SHARED_FIELDS = ("cache_len", "n_slots", "spec_k", "page_size",
                     "n_pages", "kv_codec")


def validate_geometry(mode: str, prompt_len: int, max_new: int,
                      cache_len: Optional[int] = None,
                      n_slots: Optional[int] = None,
                      page_size: Optional[int] = None,
                      n_pages: Optional[int] = None,
                      kv_codec: Optional[str] = None,
                      spec_k: Optional[int] = None) -> GeometryRecord:
    """Validate + normalize one view's geometry constants; raises
    ``ValueError`` with the same contracts the view builders used to
    enforce inline. The single source of truth for defaults: cache_len
    (prompt_len + max_new), spec_k (4), page_size (4), n_pages (the
    contiguous pool's capacity) and kv_codec (FLAGS_kv_cache_codec)."""
    _count("geometry")
    if mode not in DECODER_LM_MODES:
        raise ValueError(f"decoder_lm mode {mode!r} not in "
                         f"{DECODER_LM_MODES}")
    if (mode.endswith("_slot") or mode.endswith("_paged")
            or mode.startswith("decode_verify")) and not n_slots:
        raise ValueError(f"mode {mode!r} needs n_slots")
    prompt_len = int(prompt_len)
    max_new = int(max_new)
    cache_len = int(cache_len) if cache_len else prompt_len + max_new
    if prompt_len > cache_len:
        raise ValueError(f"prompt_len {prompt_len} > cache_len "
                         f"{cache_len}")
    n_slots = int(n_slots) if n_slots else None

    if mode.startswith("decode_verify"):
        # verify-window geometry: K >= 1 (K = 0 is plain decode — use
        # decode_slot/decode_paged), and the K+1 window must fit the
        # generated region it could commit into
        spec_k = int(spec_k) if spec_k else 4
        if spec_k < 1:
            raise ValueError(f"spec_k {spec_k} < 1 — the verify view "
                             f"needs at least one drafted token")
        if spec_k + 1 > cache_len - prompt_len + 1:
            raise ValueError(
                f"spec_k {spec_k}: the K+1={spec_k + 1} verify window "
                f"exceeds the generated region "
                f"(cache_len {cache_len} - prompt_len {prompt_len})")
    else:
        spec_k = int(spec_k) if spec_k else None

    max_pages = store_dtype = None
    if mode.endswith("_paged"):
        from paddle_tpu import flags as _flags
        page_size = int(page_size) if page_size else 4
        if cache_len % page_size:
            raise ValueError(f"page_size {page_size} must divide "
                             f"cache_len {cache_len}")
        max_pages = cache_len // page_size
        n_pages = int(n_pages) if n_pages else int(n_slots) * max_pages
        if n_pages < max_pages:
            raise ValueError(f"n_pages {n_pages} < one slot's span "
                             f"{max_pages} — no request could admit")
        kv_codec = (kv_codec if kv_codec is not None
                    else _flags.get("kv_cache_codec")) or "none"
        if kv_codec not in _KV_CODECS:
            raise ValueError(f"kv_codec {kv_codec!r} not in "
                             f"{_KV_CODECS}")
        store_dtype = _STORE_DTYPES[kv_codec]
    else:
        page_size = n_pages = kv_codec = None

    return GeometryRecord(
        mode=mode, prompt_len=prompt_len, max_new=max_new,
        cache_len=cache_len, n_slots=n_slots, spec_k=spec_k,
        page_size=page_size, n_pages=n_pages, max_pages=max_pages,
        kv_codec=kv_codec, store_dtype=store_dtype)


# ---------------------------------------------------------------------------
# the family verifier
# ---------------------------------------------------------------------------

@dataclass
class _View:
    key: str
    desc: Any                       # ir.ProgramDesc of the main program
    startup: Any                    # ir.ProgramDesc of the startup
    feed_specs: Dict[str, Any]
    fetch_name: Optional[str]
    geometry: Optional[GeometryRecord]
    sig: Any = None                 # lowering.BlockSignature


class FamilyContext:
    """What every contract rule reads: the de-aliased views of one
    program family plus their block signatures (state vs const vs feed
    classification — ``lowering.analyze_block``, no lowering or
    execution involved). Rules registered in the shared catalog no-op
    when handed the per-program ``AnalysisContext`` instead."""

    def __init__(self, family: Dict[str, tuple]):
        from paddle_tpu.core.lowering import analyze_block
        self.views: List[_View] = []
        seen_ids = set()
        for key, (main, startup, feed_specs, fetch_name) in \
                family.items():
            if id(main) in seen_ids:       # bucket aliases ("prefill" ->
                continue                   # "prefill@P_max")
            seen_ids.add(id(main))
            desc = main.desc if hasattr(main, "desc") else main
            sdesc = (startup.desc if hasattr(startup, "desc")
                     else startup)
            geom = getattr(main, "_geometry", None)
            v = _View(key=key, desc=desc, startup=sdesc,
                      feed_specs=dict(feed_specs or {}),
                      fetch_name=fetch_name, geometry=geom)
            try:
                v.sig = analyze_block(
                    desc.global_block, sorted(v.feed_specs),
                    [fetch_name] if fetch_name else [])
            except Exception:
                v.sig = None
            self.views.append(v)


def _var_spec(v) -> Tuple:
    shape = tuple(int(d) for d in (v.shape or []))
    return (shape, v.dtype, bool(v.persistable),
            bool((v.attrs or {}).get("__sharded__")))


@register_rule(
    "ctr-view-var-drift", Severity.ERROR,
    "a persistable shared across program views disagrees on shape/"
    "dtype/persistable/sharding mark between views", category="contracts")
def rule_view_var_drift(ctx) -> Iterable[Diagnostic]:
    if not isinstance(ctx, FamilyContext):
        return
    _count("view-var-drift")
    by_name: Dict[str, List[Tuple[str, Tuple]]] = {}
    for v in ctx.views:
        for name, vd in v.desc.global_block.vars.items():
            if vd.persistable:
                by_name.setdefault(name, []).append((v.key,
                                                     _var_spec(vd)))
    for name, specs in sorted(by_name.items()):
        if len(specs) < 2:
            continue
        distinct = {}
        for key, spec in specs:
            distinct.setdefault(spec, []).append(key)
        if len(distinct) > 1:
            rendered = "; ".join(
                f"{spec[0]}/{spec[1]}"
                f"{'/sharded' if spec[3] else ''}"
                f" in {sorted(keys)}"
                for spec, keys in distinct.items())
            yield Diagnostic(
                rule="ctr-view-var-drift", severity=Severity.ERROR,
                message=f"shared persistable {name!r} drifts across "
                        f"views: {rendered}",
                var=name,
                details={"views": {k: list(map(str, s))
                                   for s, ks in distinct.items()
                                   for k in ks}})


def _rng_inits(startup_desc) -> Dict[str, Tuple[int, str]]:
    """param name -> (startup op index, op type) for rng-bearing
    initializer ops (the per-index salt makes the index part of the
    weight's identity)."""
    out: Dict[str, Tuple[int, str]] = {}
    for i, op in enumerate(startup_desc.global_block.ops):
        if "random" not in op.type:
            continue
        for name in op.output_names():
            out.setdefault(name, (i, op.type))
    return out


@register_rule(
    "ctr-salt-misalignment", Severity.ERROR,
    "a shared parameter's rng initializer sits at different startup op "
    "indices across views — per-index rng salting would give the views "
    "different weights", category="contracts")
def rule_salt_misalignment(ctx) -> Iterable[Diagnostic]:
    if not isinstance(ctx, FamilyContext):
        return
    _count("salt-alignment")
    per_view = [(v.key, _rng_inits(v.startup)) for v in ctx.views
                if v.startup is not None]
    names: Dict[str, List[Tuple[str, Tuple[int, str]]]] = {}
    for key, inits in per_view:
        for name, where in inits.items():
            names.setdefault(name, []).append((key, where))
    for name, sites in sorted(names.items()):
        if len(sites) < 2:
            continue
        distinct = sorted({w for _k, w in sites})
        if len(distinct) > 1:
            rendered = "; ".join(
                f"op {w[0]} ({w[1]}) in "
                f"{sorted(k for k, w2 in sites if w2 == w)}"
                for w in distinct)
            yield Diagnostic(
                rule="ctr-salt-misalignment", severity=Severity.ERROR,
                message=f"rng initializer for shared param {name!r} is "
                        f"salted differently across views: {rendered}",
                var=name,
                details={"sites": {k: list(map(str, w))
                                   for k, w in sites}})


@register_rule(
    "ctr-stale-donation-read", Severity.ERROR,
    "a var mutated in place (donated state) by one view is a local "
    "temp or feed in a sibling view — the sibling reads a stale or "
    "freed buffer instead of the shared scope state",
    category="contracts")
def rule_stale_donation_read(ctx) -> Iterable[Diagnostic]:
    if not isinstance(ctx, FamilyContext):
        return
    _count("donation-coherence")
    state_in: Dict[str, List[str]] = {}
    for v in ctx.views:
        if v.sig is None:
            continue
        for name in v.sig.state_names:
            state_in.setdefault(name, []).append(v.key)
    for name, owners in sorted(state_in.items()):
        for v in ctx.views:
            if v.key in owners:
                continue
            blk = v.desc.global_block
            referenced = any(
                name in op.input_names() or name in op.output_names()
                for op in blk.ops)
            if not referenced:
                continue
            vd = blk.vars.get(name)
            as_feed = name in v.feed_specs
            as_temp = vd is not None and not vd.persistable
            if as_feed or as_temp:
                how = "a feed" if as_feed else "a non-persistable temp"
                yield Diagnostic(
                    rule="ctr-stale-donation-read",
                    severity=Severity.ERROR,
                    message=f"{name!r} is donated state (mutated in "
                            f"place) in view(s) {sorted(owners)} but "
                            f"{how} in view {v.key!r} — that view "
                            f"never observes the in-place update",
                    var=name,
                    details={"state_views": sorted(owners),
                             "offending_view": v.key, "as": how})


@register_rule(
    "ctr-geometry-drift", Severity.ERROR,
    "the views' stamped GeometryRecords disagree, or a view's feeds/"
    "pools are inconsistent with its own record", category="contracts")
def rule_geometry_drift(ctx) -> Iterable[Diagnostic]:
    if not isinstance(ctx, FamilyContext):
        return
    _count("geometry-drift")
    stamped = [(v.key, v.geometry) for v in ctx.views
               if v.geometry is not None]
    # cross-view agreement on the shared fields
    for fieldname in GeometryRecord.SHARED_FIELDS:
        values: Dict[Any, List[str]] = {}
        for key, g in stamped:
            val = getattr(g, fieldname)
            if val is not None:
                values.setdefault(val, []).append(key)
        if len(values) > 1:
            rendered = "; ".join(f"{val} in {sorted(keys)}"
                                 for val, keys in values.items())
            yield Diagnostic(
                rule="ctr-geometry-drift", severity=Severity.ERROR,
                message=f"geometry constant {fieldname!r} drifts "
                        f"across views: {rendered}",
                var=fieldname,
                details={str(v): sorted(k) for v, k in values.items()})
    # per-view internal consistency: record vs declared feeds
    for key, g in stamped:
        v = next(vv for vv in ctx.views if vv.key == key)
        pt = v.feed_specs.get("page_table")
        if pt is not None and g.page_size:
            width = int(pt[0][1])
            want = g.cache_len // g.page_size
            if width != want:
                yield Diagnostic(
                    rule="ctr-geometry-drift", severity=Severity.ERROR,
                    message=f"view {key!r}: page_table feed width "
                            f"{width} != cache_len/page_size "
                            f"({g.cache_len}/{g.page_size}={want})",
                    var="page_table", details={"view": key})
        tok = v.feed_specs.get("tok")
        if g.mode.startswith("decode_verify") and tok is not None:
            k1 = int(tok[0][1])
            if g.window is not None and k1 != g.window:
                yield Diagnostic(
                    rule="ctr-geometry-drift", severity=Severity.ERROR,
                    message=f"view {key!r}: tok window width {k1} != "
                            f"spec_k+1 ({g.window})",
                    var="tok", details={"view": key})
        if g.n_slots and tok is not None and (
                g.mode.startswith("decode_verify")
                or g.mode.endswith("_slot") and g.mode != "prefill_slot"
                or g.mode == "decode_paged"):
            s = int(tok[0][0])
            if s != g.n_slots:
                yield Diagnostic(
                    rule="ctr-geometry-drift", severity=Severity.ERROR,
                    message=f"view {key!r}: tok slot dim {s} != "
                            f"n_slots {g.n_slots}",
                    var="tok", details={"view": key})


_CONTRACT_RULES = (
    rule_view_var_drift,
    rule_salt_misalignment,
    rule_stale_donation_read,
    rule_geometry_drift,
)


def verify_family(family: Dict[str, tuple]) -> List[Diagnostic]:
    """Run every cross-view contract rule over one program family
    (``{key: (main, startup, feed_specs, fetch_name)}``) and return
    the diagnostics, errors first."""
    import time as _time
    t0 = _time.perf_counter()
    ctx = FamilyContext(family)
    diags: List[Diagnostic] = []
    for rule in _CONTRACT_RULES:
        diags.extend(rule(ctx))
    diags.sort(key=lambda d: (-int(d.severity), d.rule, d.var or ""))
    from paddle_tpu.analysis.rules import _publish_metrics
    _publish_metrics(diags, _time.perf_counter() - t0)
    return diags
