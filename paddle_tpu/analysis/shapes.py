"""Whole-program shape/dtype checker.

Fixpoints :func:`core.shape_inference.abstract_eval_op` across every
block (sub-blocks resolve parent-scope vars through the ancestor chain,
and control-flow ops trace their sub-blocks because the program handle
is threaded through), compares every inferred output against its
declared ``VarDesc``, and reports each drift with op provenance. The
``-1`` dynamic-batch sentinel is threaded by the inference machinery and
treated as wildcard in comparisons.

This is the build-time analogue of the reference running C++ InferShape
over the whole program per execution (operator.cc:963) — except
mismatches become diagnostics naming the producing op instead of
exceptions at step time.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity
from paddle_tpu.analysis.rules import (SKIPPED_OPS, AnalysisContext,
                                       register_rule)
from paddle_tpu.core import ir
from paddle_tpu.core.shape_inference import (_SENTINEL, _from_abstract,
                                             abstract_eval_op)

_MAX_PASSES = 4


def _is_dynamic(d: int) -> bool:
    """True for the -1 batch marker and its sentinel-space multiples
    (B, B*T, ... — batch-derived, unknowable statically)."""
    return d == -1 or (d >= _SENTINEL and d % _SENTINEL == 0)


def _shapes_compatible(declared, inferred_raw) -> bool:
    """Declared VarDesc shape vs sentinel-space inferred shape: dynamic
    dims on either side are wildcards, concrete dims must agree."""
    if declared is None:
        return True
    if len(declared) != len(inferred_raw):
        return False
    for d, i in zip(declared, inferred_raw):
        if _is_dynamic(d) or _is_dynamic(i):
            continue
        if int(d) != int(i):
            return False
    return True


def _norm_dtype(dt: str) -> str:
    try:
        return str(jnp.dtype(dt))
    except TypeError:
        return str(dt)


def _check_program_shapes(ctx: AnalysisContext) -> List[Diagnostic]:
    """One fixpoint run over all blocks; cached on the context so the
    three rules below share it."""
    cached = getattr(ctx, "_shape_diags", None)
    if cached is not None:
        return cached
    program = ctx.program
    # (block_idx, name) -> VarDesc synthesized from inference, in
    # SENTINEL SPACE (batch-derived dims stay as sentinel multiples so B
    # and B*T remain distinguishable downstream — a grad var declared
    # [-1, V] whose value is really [B*T, V] must not re-collapse);
    # consulted before the declared symbol table so later passes and
    # later ops see refined shapes
    inferred_vars: Dict[Tuple[int, str], ir.VarDesc] = {}

    def make_lookup(block_idx: int):
        chain = ctx.ancestor_chain(block_idx)

        def lookup(name: str) -> Optional[ir.VarDesc]:
            for b in chain:
                hit = inferred_vars.get((b, name))
                if hit is not None:
                    return hit
                block = program.block(b)
                if block.has_var(name):
                    vd = block.var(name)
                    if vd.shape is not None:
                        return vd
                    # declared but shapeless: keep walking only if an
                    # ancestor could shadow it — it can't, so report the
                    # declared desc (inference will skip on it)
                    return vd
            return None
        return lookup

    results: Dict[Tuple[int, int], object] = {}
    for _ in range(_MAX_PASSES):
        changed = False
        for bi, block in enumerate(program.blocks):
            lookup = make_lookup(bi)
            for oi, op in enumerate(block.ops):
                if op.type in SKIPPED_OPS:
                    continue
                res = abstract_eval_op(block, op, lookup=lookup,
                                       is_test=ctx.is_test,
                                       program=program, raw_dims=True)
                results[(bi, oi)] = res
                if not res.ok:
                    continue
                for name, (shape, dtype) in res.outputs.items():
                    vd = ir.VarDesc(name=name, shape=list(shape),
                                    dtype=_norm_dtype(dtype))
                    # refine only when inference disagrees with what the
                    # lookup already resolves (declared VarDesc included)
                    # — storing an identical desc would force a full
                    # re-evaluation pass for nothing
                    prev = lookup(name)
                    if prev is not None and prev.shape == vd.shape \
                            and _norm_dtype(prev.dtype) == vd.dtype:
                        continue
                    inferred_vars[(bi, name)] = vd
                    changed = True
        if not changed:
            break

    diags: List[Diagnostic] = []
    for (bi, oi), res in sorted(results.items()):
        block = program.block(bi)
        op = block.ops[oi]
        if res.error is not None:
            diags.append(Diagnostic(
                rule="shape-infer-error", severity=Severity.WARNING,
                message=f"abstract evaluation of op {op.type!r} failed "
                        f"with {res.error_type}: {res.error} — likely an "
                        f"emitter bug or malformed attrs (benign "
                        f"concrete-value cases are skipped, not "
                        f"reported)",
                block_idx=bi, op_index=oi, op_type=op.type,
                details={"error_type": res.error_type}))
            continue
        if not res.ok:
            continue
        for name, (shape, dtype) in res.outputs.items():
            vd = ctx.resolve(bi, name)
            if vd is None:
                continue                   # dangling-output covers this
            if not _shapes_compatible(vd.shape, shape):
                shown = list(_from_abstract(shape))
                diags.append(Diagnostic(
                    rule="shape-mismatch", severity=Severity.ERROR,
                    message=f"op {op.type!r} produces {name!r} with "
                            f"shape {shown} but the VarDesc "
                            f"declares {vd.shape}",
                    block_idx=bi, op_index=oi, op_type=op.type, var=name,
                    details={"declared": vd.shape,
                             "inferred": shown}))
            decl_dt, inf_dt = _norm_dtype(vd.dtype), _norm_dtype(dtype)
            if decl_dt != inf_dt:
                diags.append(Diagnostic(
                    rule="dtype-mismatch", severity=Severity.ERROR,
                    message=f"op {op.type!r} produces {name!r} as "
                            f"{inf_dt} but the VarDesc declares "
                            f"{decl_dt}",
                    block_idx=bi, op_index=oi, op_type=op.type, var=name,
                    details={"declared": decl_dt, "inferred": inf_dt}))
    ctx._shape_diags = diags
    return diags


@register_rule("shape-mismatch", Severity.ERROR,
               "an op's inferred output shape disagrees with the "
               "declared VarDesc shape (-1 batch dims are wildcards)",
               category="shapes")
def _shape_mismatch(ctx: AnalysisContext):
    return [d for d in _check_program_shapes(ctx)
            if d.rule == "shape-mismatch"]


@register_rule("dtype-mismatch", Severity.ERROR,
               "an op's inferred output dtype disagrees with the "
               "declared VarDesc dtype", category="shapes")
def _dtype_mismatch(ctx: AnalysisContext):
    return [d for d in _check_program_shapes(ctx)
            if d.rule == "dtype-mismatch"]


@register_rule("shape-infer-error", Severity.WARNING,
               "abstract evaluation of an emitter raised a genuine "
               "error (not a concretization skip) — an emitter bug or "
               "malformed attrs", category="shapes")
def _shape_infer_error(ctx: AnalysisContext):
    return [d for d in _check_program_shapes(ctx)
            if d.rule == "shape-infer-error"]
