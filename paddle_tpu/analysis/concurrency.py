"""Concurrency lint: an AST pass over the host-side orchestration code.

The ProgramDesc verifier (structural/shapes/dataflow) audits what runs
ON the chip; the last several shipped bugs lived in the Python that
orchestrates it — threaded routers, RPC accept loops, heartbeat threads
(the sink-called-under-registry-lock race, the restart-path inversions).
This pass parses the serving/distributed/data/observability sources and
builds, per class, a lock-ownership model:

- **lock attributes** — ``self.x = threading.Lock()/RLock()/Condition()``
  (and ``lock_witness.make_lock(...)`` / ``ObservedLock(...)`` wrappers);
- **thread entry points** — methods or nested functions handed to
  ``Thread(target=...)``, ``handle``/``finish`` methods of
  ``socketserver`` request handlers, plus the main thread (every public
  method callable from outside counts as main-thread-reachable);
- **guarded regions** — statements inside ``with self.x:`` /
  ``with obj.x:`` where ``x`` is a known lock attribute (and explicit
  ``.acquire()`` / ``.release()`` pairs).

Four rule families run over that model (rule ids below, catalog in
docs/static_analysis.md):

- ``ccy-unlocked-shared-write`` — a read-modify-write (``+=`` et al.) or
  plain store on an attribute that is reachable from two thread entry
  points (or is guarded by a lock elsewhere in the class) executed with
  no lock held;
- ``ccy-lock-order-cycle`` — the module's lock-order graph (edges from
  nested ``with`` regions and acquire-while-holding) has a cycle:
  deadlock potential. The runtime twin of this rule is
  ``observability.lock_witness`` (FLAGS_lock_witness);
- ``ccy-blocking-under-lock`` — socket recv/accept/connect/sendall/
  readline, ``subprocess`` waits, ``time.sleep``, thread ``join`` or an
  RPC ``exchange``/``call`` dispatched while a lock is held;
- ``ccy-callback-under-lock`` — invoking a user-registered callback
  (an element of a ``self.*sink*/*callback*/*hook*/*listener*``
  collection) while the registry's lock is held — the exact regression
  class of the PR 12 tracing-sink fix.

Suppression rides the ``__lint_suppress__`` discipline, source-comment
form, **justification mandatory**::

    self.hits += 1  # __lint_suppress__: ccy-unlocked-shared-write -- single writer: only the reaper thread mutates this

A suppression without the ``-- why`` tail is itself a finding
(``ccy-suppression-missing-justification``). The comment suppresses
findings anchored to its own line or the line directly below it.

Entry points: :func:`run_concurrency_lint` (returns ``Diagnostic``
records with file/line provenance in ``details``), surfaced on the CLI
as ``tools/proglint.py --concurrency`` and gated in
``tools/test_runner.py`` (zero-unsuppressed-findings baseline).
"""

from __future__ import annotations

import ast
import os
import time
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity
from paddle_tpu.analysis.rules import register_rule

# the default scan surface: every package hosting threads or locks
DEFAULT_PACKAGES = ("serving", "distributed", "data", "observability")

SUPPRESS_MARK = "__lint_suppress__"

# constructors recognized as lock objects when assigned to self.<attr>
_LOCK_CTORS = {"Lock", "RLock", "Condition", "ObservedLock", "make_lock"}

# call names (attribute or dotted) considered blocking while a lock is
# held. Attribute calls match the terminal name; dotted calls match the
# rendered path.
_BLOCKING_ATTRS = {"recv", "accept", "connect", "sendall", "readline",
                   "exchange", "join", "wait", "select"}
_BLOCKING_DOTTED = {"time.sleep", "subprocess.run", "subprocess.call",
                    "subprocess.check_call", "subprocess.check_output",
                    "socket.create_connection", "select.select"}

# attribute-name fragments marking a collection of user callbacks
_CALLBACK_HINTS = ("callback", "sink", "hook", "listener", "subscriber",
                   "observer", "handler_fn")


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class Suppression:
    line: int
    rules: frozenset
    justification: str


@dataclass
class LockRegion:
    """One `with <lock>:` region (or acquire/release span)."""
    lock: str                    # normalized lock key, e.g. "Router._pool_lock"
    expr: str                    # source expression, e.g. "self._pool_lock"
    line: int


@dataclass
class AttrAccess:
    attr: str
    line: int
    is_write: bool
    is_augmented: bool           # read-modify-write (+= etc.)
    receiver: str                # "self" or the receiver expression
    locks_held: Tuple[str, ...]  # normalized lock keys held at the access
    method: str                  # qualname of the enclosing function


@dataclass
class MethodModel:
    qualname: str                # "Class.method" or "func.<locals>.inner"
    name: str
    cls: Optional[str]
    line: int
    accesses: List[AttrAccess] = field(default_factory=list)
    blocking: List[Tuple[str, int, Tuple[str, ...], str]] = \
        field(default_factory=list)   # (call, line, locks_held, held_expr)
    callbacks: List[Tuple[str, int, Tuple[str, ...]]] = \
        field(default_factory=list)   # (descr, line, locks_held)
    calls_self: Set[str] = field(default_factory=set)  # self.m() targets
    is_thread_target: bool = False


@dataclass
class ClassModel:
    name: str
    line: int
    lock_attrs: Dict[str, int] = field(default_factory=dict)  # attr -> line
    attrs: Set[str] = field(default_factory=set)    # attrs assigned anywhere
    methods: Dict[str, MethodModel] = field(default_factory=dict)
    bases: Tuple[str, ...] = ()

    def is_request_handler(self) -> bool:
        return any("RequestHandler" in b or "TCPServer" in b
                   for b in self.bases)


@dataclass
class ModuleModel:
    path: str                    # path as given (relative when possible)
    classes: Dict[str, ClassModel] = field(default_factory=dict)
    functions: List[MethodModel] = field(default_factory=list)
    # lock-order edges: (lock_a, lock_b) -> (line, method qualname)
    lock_edges: Dict[Tuple[str, str], Tuple[int, str]] = \
        field(default_factory=dict)
    suppressions: Dict[int, Suppression] = field(default_factory=dict)
    bad_suppressions: List[Suppression] = field(default_factory=list)


class ConcurrencyContext:
    """What every concurrency rule reads: one :class:`ModuleModel` per
    scanned file. Built by :func:`run_concurrency_lint`; rules
    registered in the shared catalog no-op when handed the ProgramDesc
    :class:`~paddle_tpu.analysis.rules.AnalysisContext` instead."""

    def __init__(self, modules: Sequence[ModuleModel]):
        self.modules = list(modules)


# ---------------------------------------------------------------------------
# source -> model
# ---------------------------------------------------------------------------

def _parse_suppressions(path: str, source: str,
                        model: ModuleModel) -> None:
    try:
        tokens = tokenize.generate_tokens(iter(source.splitlines(True))
                                          .__next__)
        comments = [(t.start[0], t.string) for t in tokens
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        comments = [(i + 1, line[line.index("#"):])
                    for i, line in enumerate(source.splitlines())
                    if "#" in line]
    for line, text in comments:
        if SUPPRESS_MARK not in text:
            continue
        body = text.split(SUPPRESS_MARK, 1)[1].lstrip(" :")
        rules_part, sep, why = body.partition("--")
        rules = frozenset(r.strip() for r in rules_part.split(",")
                          if r.strip())
        sup = Suppression(line=line, rules=rules,
                          justification=why.strip() if sep else "")
        if not sep or not why.strip():
            model.bad_suppressions.append(sup)
        model.suppressions[line] = sup


def _is_lock_ctor(call: ast.Call) -> bool:
    name = _dotted(call.func) or ""
    return name.split(".")[-1] in _LOCK_CTORS


class _FunctionScanner(ast.NodeVisitor):
    """Walk one function body tracking the held-lock stack."""

    def __init__(self, model: MethodModel, cls: Optional[ClassModel],
                 module: ModuleModel):
        self.m = model
        self.cls = cls
        self.module = module
        self.held: List[LockRegion] = []
        self.loop_vars: Dict[str, str] = {}   # name -> source attr it
        #                                       iterates (callback hint)

    # -- lock key normalization -------------------------------------------
    def _lock_key(self, expr: ast.AST) -> Optional[Tuple[str, str]]:
        """(normalized key, source expr) when `expr` names a known lock:
        ``self.x`` where x is a lock attr of the enclosing class, or
        ``obj.x`` where x is a lock attr of ANY class in the module
        (cross-object locking, e.g. the router taking a replica's
        lock)."""
        dotted = _dotted(expr)
        if not dotted or "." not in dotted:
            return None
        recv, attr = dotted.rsplit(".", 1)
        if recv == "self" and self.cls is not None:
            if attr in self.cls.lock_attrs:
                return f"{self.cls.name}.{attr}", dotted
            return None
        for cm in self.module.classes.values():
            if attr in cm.lock_attrs:
                return f"{cm.name}.{attr}", dotted
        return None

    def _held_keys(self) -> Tuple[str, ...]:
        return tuple(r.lock for r in self.held)

    # -- visitors ----------------------------------------------------------
    def visit_With(self, node: ast.With):
        pushed = 0
        for item in node.items:
            key = self._lock_key(item.context_expr)
            if key is not None:
                lock, expr = key
                if self.held:
                    edge = (self.held[-1].lock, lock)
                    if edge[0] != edge[1]:
                        self.module.lock_edges.setdefault(
                            edge, (node.lineno, self.m.qualname))
                self.held.append(LockRegion(lock=lock, expr=expr,
                                            line=node.lineno))
                pushed += 1
        saved_loops = dict(self.loop_vars)
        for stmt in node.body:
            self.visit(stmt)
        self.loop_vars = saved_loops
        for _ in range(pushed):
            self.held.pop()

    def visit_For(self, node: ast.For):
        # `for s in self._sinks:` — s is a callback candidate while the
        # loop body executes
        src = _dotted(node.iter)
        if (isinstance(node.target, ast.Name) and src
                and src.startswith("self.")
                and any(h in src.lower() for h in _CALLBACK_HINTS)):
            self.loop_vars[node.target.id] = src
        self.generic_visit(node)

    def _record_access(self, target: ast.Attribute, is_write: bool,
                       augmented: bool):
        dotted = _dotted(target)
        if not dotted or "." not in dotted:
            return
        recv, attr = dotted.rsplit(".", 1)
        self.m.accesses.append(AttrAccess(
            attr=attr, line=target.lineno, is_write=is_write,
            is_augmented=augmented, receiver=recv,
            locks_held=self._held_keys(), method=self.m.qualname))

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            if isinstance(t, ast.Attribute):
                self._record_access(t, True, False)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        if isinstance(node.target, ast.Attribute):
            self._record_access(node.target, True, True)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.ctx, ast.Load):
            self._record_access(node, False, False)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        dotted = _dotted(node.func) or ""
        terminal = dotted.split(".")[-1] if dotted else ""
        # self.m(...) — intra-class call graph
        if dotted.startswith("self.") and dotted.count(".") == 1:
            self.m.calls_self.add(terminal)
        # Thread(target=...) — mark the target an entry point
        if terminal == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    tgt = _dotted(kw.value)
                    if tgt:
                        self.module.__dict__.setdefault(
                            "_thread_targets", set()).add(
                            (self.m.qualname, tgt))
        # acquire-while-holding also contributes lock-order edges
        if terminal == "acquire" and isinstance(node.func, ast.Attribute):
            key = self._lock_key(node.func.value)
            if key is not None and self.held:
                edge = (self.held[-1].lock, key[0])
                if edge[0] != edge[1]:
                    self.module.lock_edges.setdefault(
                        edge, (node.lineno, self.m.qualname))
        if self.held:
            self._scan_blocking(node, dotted, terminal)
            self._scan_callback(node, dotted)
        self.generic_visit(node)

    def _scan_blocking(self, node: ast.Call, dotted: str, terminal: str):
        blocking = (dotted in _BLOCKING_DOTTED
                    or (isinstance(node.func, ast.Attribute)
                        and terminal in _BLOCKING_ATTRS))
        if not blocking:
            return
        # `cond.wait()` on the lock object currently held is the normal
        # Condition protocol, not a finding
        if terminal == "wait" and isinstance(node.func, ast.Attribute):
            recv = _dotted(node.func.value)
            if recv and any(r.expr == recv for r in self.held):
                return
        self.m.blocking.append(
            (dotted or terminal, node.lineno, self._held_keys(),
             self.held[-1].expr))

    def _scan_callback(self, node: ast.Call, dotted: str):
        descr = None
        # self._cbs[k](...) — direct subscript call on a callback attr
        if isinstance(node.func, ast.Subscript):
            src = _dotted(node.func.value)
            if (src and src.startswith("self.")
                    and any(h in src.lower() for h in _CALLBACK_HINTS)):
                descr = f"{src}[...]"
        # s(...) or s.emit(...) where s iterates a callback collection
        elif isinstance(node.func, ast.Name) \
                and node.func.id in self.loop_vars:
            descr = f"{node.func.id} from {self.loop_vars[node.func.id]}"
        elif isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in self.loop_vars:
            src = self.loop_vars[node.func.value.id]
            descr = f"{node.func.value.id}.{node.func.attr} from {src}"
        if descr is not None:
            self.m.callbacks.append(
                (descr, node.lineno, self._held_keys()))

    # nested defs: scanned as their own MethodModel by _scan_function;
    # don't descend here (their lock context is their own)
    def visit_FunctionDef(self, node: ast.FunctionDef):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef


def _scan_function(fn: ast.FunctionDef, cls: Optional[ClassModel],
                   module: ModuleModel, prefix: str = "") -> MethodModel:
    qual = (f"{cls.name}.{fn.name}" if cls else
            f"{prefix}{fn.name}" if prefix else fn.name)
    m = MethodModel(qualname=qual, name=fn.name,
                    cls=cls.name if cls else None, line=fn.lineno)
    scanner = _FunctionScanner(m, cls, module)
    for stmt in fn.body:
        scanner.visit(stmt)
    # nested functions (accept loops, heartbeat loops) get their own
    # model — they are the usual Thread targets
    for sub in _immediate_defs(fn):
        nested = _scan_function(sub, cls, module,
                                prefix=f"{qual}.<locals>.")
        if cls is not None:
            cls.methods[nested.qualname] = nested
        else:
            module.functions.append(nested)
    return m


def _immediate_defs(fn: ast.AST) -> List[ast.FunctionDef]:
    """Function defs nested directly inside `fn` (not inside a deeper
    def — those belong to their own parent's scan)."""
    out: List[ast.FunctionDef] = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(child)
            else:
                walk(child)

    walk(fn)
    return out


def _collect_class(node: ast.ClassDef, module: ModuleModel) -> ClassModel:
    cm = ClassModel(name=node.name, line=node.lineno,
                    bases=tuple(_dotted(b) or "" for b in node.bases))
    # first pass: lock + plain attribute assignments across all methods
    for item in node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for sub in ast.walk(item):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    d = _dotted(t)
                    if d and d.startswith("self.") and d.count(".") == 1:
                        attr = d.split(".", 1)[1]
                        cm.attrs.add(attr)
                        if isinstance(sub.value, ast.Call) \
                                and _is_lock_ctor(sub.value):
                            cm.lock_attrs.setdefault(attr, sub.lineno)
            elif isinstance(sub, ast.AugAssign):
                d = _dotted(sub.target)
                if d and d.startswith("self.") and d.count(".") == 1:
                    cm.attrs.add(d.split(".", 1)[1])
    # second pass: per-method scan with the lock model in place
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            m = _scan_function(item, cm, module)
            cm.methods[m.qualname] = m
    return cm


def scan_file(path: str, display_path: Optional[str] = None
              ) -> Optional[ModuleModel]:
    """Parse one file into a :class:`ModuleModel`; None on syntax
    errors (a broken file fails ruff, not this pass)."""
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError):
        return None
    model = ModuleModel(path=display_path or path)
    _parse_suppressions(path, source, model)
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            model.classes[node.name] = _collect_class(node, model)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            model.functions.append(_scan_function(node, None, model))
    _mark_thread_targets(model)
    return model


def _mark_thread_targets(model: ModuleModel):
    """Resolve Thread(target=X) references onto MethodModels."""
    targets = model.__dict__.get("_thread_targets", set())
    names: Set[str] = set()
    for _src, tgt in targets:
        names.add(tgt.split(".")[-1])
    all_methods = list(model.functions)
    for cm in model.classes.values():
        all_methods.extend(cm.methods.values())
        if cm.is_request_handler():
            for m in cm.methods.values():
                if m.name in ("handle", "finish", "process_request"):
                    m.is_thread_target = True
    for m in all_methods:
        if m.name in names:
            m.is_thread_target = True


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

def _entry_reachable(cm: ClassModel) -> Dict[str, Set[str]]:
    """method name -> set of entry-point labels it is reachable from.
    Entry labels: 'thread:<target>' per thread target, 'main' for every
    public method (callable from the owning thread)."""
    # adjacency on short names within the class
    adj: Dict[str, Set[str]] = {}
    for m in cm.methods.values():
        adj.setdefault(m.name, set()).update(m.calls_self)
    reach: Dict[str, Set[str]] = {m.name: set()
                                  for m in cm.methods.values()}

    def flood(start: str, label: str):
        stack, seen = [start], set()
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            if cur in reach:
                reach[cur].add(label)
            stack.extend(adj.get(cur, ()))

    for m in cm.methods.values():
        if m.is_thread_target:
            flood(m.name, f"thread:{m.qualname}")
        elif not m.name.startswith("_") or m.name == "__init__":
            flood(m.name, "main")
    return reach


def _diag(rule: str, severity: Severity, message: str, module: ModuleModel,
          line: int, var: Optional[str] = None,
          method: Optional[str] = None, **extra) -> Diagnostic:
    details = {"file": module.path, "line": line}
    if method:
        details["function"] = method
    details.update(extra)
    return Diagnostic(rule=rule, severity=severity,
                      message=f"{module.path}:{line}: {message}",
                      var=var, details=details)


@register_rule(
    "ccy-unlocked-shared-write", Severity.ERROR,
    "read-modify-write (or store) on an attribute shared across thread "
    "entry points, with no owning lock held", category="concurrency")
def rule_unlocked_shared_write(ctx) -> Iterable[Diagnostic]:
    if not isinstance(ctx, ConcurrencyContext):
        return
    for module in ctx.modules:
        for cm in module.classes.values():
            if not cm.lock_attrs:
                continue
            reach = _entry_reachable(cm)
            # attr -> entry labels touching it, and whether it is ever
            # accessed under a lock (the class's own claim of guarding)
            touched: Dict[str, Set[str]] = {}
            guarded: Set[str] = set()
            for m in cm.methods.values():
                for a in m.accesses:
                    if a.receiver != "self" or a.attr not in cm.attrs:
                        continue
                    touched.setdefault(a.attr, set()).update(
                        reach.get(m.name, set()))
                    if a.locks_held:
                        guarded.add(a.attr)
            # cross-object accesses (router mutating replica.attr):
            # receiver is not self but attr belongs to a lock-owning
            # class of this module — count the accessor's entries too
            for other in module.classes.values():
                for m in other.methods.values():
                    oreach = _entry_reachable(other)
                    for a in m.accesses:
                        if a.receiver == "self" or a.attr in ("self",):
                            continue
                        if a.attr in cm.attrs and a.attr not in \
                                other.attrs:
                            touched.setdefault(a.attr, set()).update(
                                oreach.get(m.name, set()))
                            if a.locks_held:
                                guarded.add(a.attr)
            for other in module.classes.values():
                for m in other.methods.values():
                    for a in m.accesses:
                        if not a.is_write or a.locks_held:
                            continue
                        own = (a.receiver == "self" and other is cm)
                        cross = (a.receiver != "self"
                                 and a.attr in cm.attrs
                                 and a.attr not in other.attrs)
                        if not (own or cross):
                            continue
                        if a.attr not in cm.attrs \
                                or a.attr in cm.lock_attrs:
                            continue
                        if m.name == "__init__" and own:
                            continue        # construction precedes sharing
                        entries = touched.get(a.attr, set())
                        shared = len(entries) >= 2
                        # a plain (non-RMW) store is only flagged when
                        # the class guards this attr elsewhere — the
                        # inconsistent-locking signal; RMWs are flagged
                        # whenever the attr is shared at all
                        if a.is_augmented and (shared
                                               or a.attr in guarded):
                            why = ("read-modify-write on shared "
                                   f"attribute .{a.attr} with no lock "
                                   f"held (reachable from "
                                   f"{len(entries)} entry point(s))")
                        elif not a.is_augmented and a.attr in guarded \
                                and shared:
                            why = (f"store to .{a.attr} with no lock "
                                   "held, but other sites guard it "
                                   "with a lock")
                        else:
                            continue
                        yield _diag(
                            "ccy-unlocked-shared-write", Severity.ERROR,
                            f"{why} [class {cm.name}, in {a.method}]",
                            module, a.line, var=f"{cm.name}.{a.attr}",
                            method=a.method,
                            entries=sorted(entries))


@register_rule(
    "ccy-lock-order-cycle", Severity.ERROR,
    "the module's lock-order graph has a cycle — two threads taking the "
    "locks in opposite orders deadlock", category="concurrency")
def rule_lock_order_cycle(ctx) -> Iterable[Diagnostic]:
    if not isinstance(ctx, ConcurrencyContext):
        return
    for module in ctx.modules:
        edges = module.lock_edges
        adj: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)

        def path_exists(src: str, dst: str) -> bool:
            stack, seen = [src], set()
            while stack:
                cur = stack.pop()
                if cur == dst:
                    return True
                if cur in seen:
                    continue
                seen.add(cur)
                stack.extend(adj.get(cur, ()))
            return False

        reported = set()
        for (a, b), (line, method) in sorted(edges.items(),
                                             key=lambda kv: kv[1][0]):
            if (b, a) in reported or (a, b) in reported:
                continue
            # drop the edge a->b, ask whether b still reaches a
            adj[a].discard(b)
            cyclic = path_exists(b, a)
            adj[a].add(b)
            if cyclic:
                reported.add((a, b))
                other = edges.get((b, a))
                where = (f"; reverse order at line {other[0]} "
                         f"in {other[1]}" if other else "")
                yield _diag(
                    "ccy-lock-order-cycle", Severity.ERROR,
                    f"lock order {a} -> {b} (in {method}) completes a "
                    f"cycle{where} — deadlock potential",
                    module, line, var=f"{a}->{b}", method=method)


@register_rule(
    "ccy-blocking-under-lock", Severity.WARNING,
    "blocking call (socket recv/accept, subprocess wait, sleep, RPC "
    "dispatch) while holding a lock", category="concurrency")
def rule_blocking_under_lock(ctx) -> Iterable[Diagnostic]:
    if not isinstance(ctx, ConcurrencyContext):
        return
    for module in ctx.modules:
        methods = list(module.functions)
        for cm in module.classes.values():
            methods.extend(cm.methods.values())
        for m in methods:
            for call, line, held, expr in m.blocking:
                yield _diag(
                    "ccy-blocking-under-lock", Severity.WARNING,
                    f"blocking call {call}() while holding "
                    f"{', '.join(held)} (taken as {expr}) "
                    f"[in {m.qualname}]",
                    module, line, var=held[-1], method=m.qualname,
                    call=call, locks=list(held))


@register_rule(
    "ccy-callback-under-lock", Severity.WARNING,
    "user-registered callback invoked while the registry's lock is "
    "held — a callback that re-enters the registry deadlocks",
    category="concurrency")
def rule_callback_under_lock(ctx) -> Iterable[Diagnostic]:
    if not isinstance(ctx, ConcurrencyContext):
        return
    for module in ctx.modules:
        methods = list(module.functions)
        for cm in module.classes.values():
            methods.extend(cm.methods.values())
        for m in methods:
            for descr, line, held in m.callbacks:
                yield _diag(
                    "ccy-callback-under-lock", Severity.WARNING,
                    f"callback {descr} invoked while holding "
                    f"{', '.join(held)} [in {m.qualname}] — copy the "
                    "registry under the lock, call outside it",
                    module, line, var=held[-1], method=m.qualname,
                    locks=list(held))


@register_rule(
    "ccy-suppression-missing-justification", Severity.ERROR,
    "a __lint_suppress__ comment without the mandatory '-- why' "
    "justification tail", category="concurrency")
def rule_suppression_justified(ctx) -> Iterable[Diagnostic]:
    if not isinstance(ctx, ConcurrencyContext):
        return
    for module in ctx.modules:
        for sup in module.bad_suppressions:
            yield _diag(
                "ccy-suppression-missing-justification", Severity.ERROR,
                f"suppression of {sorted(sup.rules)} carries no "
                "justification — append '-- <why this is safe>'",
                module, sup.line, var=",".join(sorted(sup.rules)))


_CONCURRENCY_RULES = (
    rule_unlocked_shared_write,
    rule_lock_order_cycle,
    rule_blocking_under_lock,
    rule_callback_under_lock,
    rule_suppression_justified,
)


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

def default_scan_paths(root: Optional[str] = None) -> List[str]:
    """Every .py file of the default packages under the paddle_tpu
    source root (serving/, distributed/, data/, observability/)."""
    if root is None:
        root = os.path.dirname(os.path.abspath(__file__))  # analysis/
        root = os.path.dirname(root)                       # paddle_tpu/
    out: List[str] = []
    for pkg in DEFAULT_PACKAGES:
        d = os.path.join(root, pkg)
        if not os.path.isdir(d):
            continue
        for fn in sorted(os.listdir(d)):
            if fn.endswith(".py"):
                out.append(os.path.join(d, fn))
    return out


def _suppressed(module: ModuleModel, d: Diagnostic) -> bool:
    line = d.details.get("line")
    if line is None:
        return False
    for at in (line, line - 1):
        sup = module.suppressions.get(at)
        if sup is None or not sup.justification:
            continue
        if d.rule in sup.rules or "*" in sup.rules:
            return True
    return False


def run_concurrency_lint(paths: Optional[Sequence[str]] = None,
                         root: Optional[str] = None,
                         include_suppressed: bool = False
                         ) -> List[Diagnostic]:
    """Scan `paths` (default: the serving/distributed/data/observability
    packages) and return the surviving diagnostics, errors first. Each
    diagnostic carries ``details={'file', 'line', 'function'}``
    provenance; justified ``__lint_suppress__`` comments drop their
    findings (``include_suppressed=True`` keeps them, for baseline
    audits)."""
    if paths is None:
        paths = default_scan_paths(root)
    cwd = os.getcwd()
    modules: List[ModuleModel] = []
    for p in paths:
        disp = os.path.relpath(p, cwd) if os.path.isabs(p) else p
        if disp.startswith(".."):
            disp = p
        m = scan_file(p, display_path=disp)
        if m is not None:
            modules.append(m)
    ctx = ConcurrencyContext(modules)
    by_path = {m.path: m for m in modules}

    t0 = time.perf_counter()
    diags: List[Diagnostic] = []
    for rule in _CONCURRENCY_RULES:
        for d in rule(ctx):
            module = by_path.get(d.details.get("file", ""))
            if include_suppressed or module is None \
                    or not _suppressed(module, d):
                diags.append(d)
    diags.sort(key=lambda d: (-int(d.severity),
                              d.details.get("file", ""),
                              d.details.get("line", 0), d.rule))
    from paddle_tpu.analysis.rules import _publish_metrics
    _publish_metrics(diags, time.perf_counter() - t0)
    return diags
