"""Structured diagnostics for the build-time program verifier.

The reference surfaces malformed programs through C++ enforce failures
inside InferShape / op-registry validation at ``append_op`` time
(reference: framework/op_desc.cc CheckAttrs, operator.cc:963 runtime
InferShape). paddle_tpu instead lowers whole blocks through JAX, where a
malformed program dies as an opaque trace error deep in
``lowering.emit_op_seq`` — or trains silently wrong. This module defines
the record every analysis rule produces: a :class:`Diagnostic` carrying
the rule id, severity, and *op provenance* (block index, op index, op
type) so the user is pointed at the offending op, not at a JAX
traceback.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


class Severity(enum.IntEnum):
    """Ordered severities: ERROR fails a verified build
    (``FLAGS_verify_program``), WARNING is reported (and counted in the
    observability registry) but never blocks, INFO is advisory lint."""

    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self):
        return self.name.lower()


@dataclass(frozen=True)
class Diagnostic:
    """One finding from one rule, anchored to program coordinates.

    ``op_index`` is the index inside ``blocks[block_idx].ops`` (or None
    for program/var-level findings); ``var`` names the variable the
    finding is about when there is one.
    """

    rule: str
    severity: Severity
    message: str
    block_idx: int = 0
    op_index: Optional[int] = None
    op_type: Optional[str] = None
    var: Optional[str] = None
    details: Dict[str, Any] = field(default_factory=dict, compare=False)

    @property
    def where(self) -> str:
        loc = f"block {self.block_idx}"
        if self.op_index is not None:
            loc += f", op {self.op_index}"
            if self.op_type:
                loc += f" ({self.op_type})"
        if self.var:
            loc += f", var {self.var!r}"
        return loc

    def format(self) -> str:
        return f"[{self.severity}] {self.rule}: {self.message} ({self.where})"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "severity": str(self.severity),
            "message": self.message,
            "block_idx": self.block_idx,
            "op_index": self.op_index,
            "op_type": self.op_type,
            "var": self.var,
            "details": dict(self.details),
        }


def max_severity(diags) -> Optional[Severity]:
    sevs = [d.severity for d in diags]
    return max(sevs) if sevs else None


def partition(diags) -> Tuple[List[Diagnostic], List[Diagnostic],
                              List[Diagnostic]]:
    """(errors, warnings, infos) in stable order."""
    errs = [d for d in diags if d.severity == Severity.ERROR]
    warns = [d for d in diags if d.severity == Severity.WARNING]
    infos = [d for d in diags if d.severity == Severity.INFO]
    return errs, warns, infos


class ProgramVerificationError(ValueError):
    """Raised at CompiledBlock build (``FLAGS_verify_program``) when the
    analyzer finds ERROR-severity diagnostics. Carries the full
    diagnostic list; the message renders every error with provenance."""

    def __init__(self, diagnostics: List[Diagnostic]):
        self.diagnostics = list(diagnostics)
        errs, warns, _ = partition(self.diagnostics)
        lines = [f"program verification failed: {len(errs)} error(s), "
                 f"{len(warns)} warning(s)"]
        lines += ["  " + d.format() for d in errs]
        lines += ["  " + d.format() for d in warns]
        super().__init__("\n".join(lines))
