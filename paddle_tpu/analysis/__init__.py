"""Build-time program verifier and lint framework.

Runs over a ``ProgramDesc`` *before* lowering and rejects malformed
programs with diagnostics naming the offending op and rule, instead of
letting them surface as opaque JAX trace errors inside
``lowering.emit_op_seq`` (or as silent wrong training):

- **structural verifier** (:mod:`~paddle_tpu.analysis.structural`) —
  unknown ops, dangling input/output vars, def-before-use ordering,
  control-flow attr schemas, sub-block parent-scope bindings,
  forward/grad var pairing;
- **shape/dtype checker** (:mod:`~paddle_tpu.analysis.shapes`) —
  fixpoints abstract evaluation across blocks (threading the ``-1``
  batch sentinel) and reports every drift between inferred and declared
  ``VarDesc`` shape/dtype, plus genuine emitter failures the old
  inference swallowed;
- **dataflow analyses** (:mod:`~paddle_tpu.analysis.dataflow`) —
  dead ops / unused outputs against the fetch set, write-after-write
  hazards on parameters outside optimizer applies, unfed live inputs,
  RNG-in-inference determinism;
- **lint framework** (:mod:`~paddle_tpu.analysis.rules`) — rule
  registry with severities, per-op ``__lint_suppress__`` suppressions,
  structured :class:`Diagnostic` records, and observability counters.

Entry points: :func:`analyze_program` (returns diagnostics),
:func:`verify_program` (raises :class:`ProgramVerificationError` on
ERROR severities — wired into ``CompiledBlock`` via
``FLAGS_verify_program``), and the ``tools/proglint.py`` CLI.
Rule catalog and suppression syntax: docs/static_analysis.md.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from paddle_tpu.analysis.diagnostics import (  # noqa: F401
    Diagnostic, ProgramVerificationError, Severity, max_severity,
    partition)
from paddle_tpu.analysis.rules import (  # noqa: F401
    RuleSpec, all_rules, register_rule, run_rules, suppress_op)


def run_concurrency_lint(paths=None, root=None,
                         include_suppressed: bool = False):
    """AST concurrency lint over the host-side orchestration packages
    (serving/distributed/data/observability): unlocked shared writes,
    lock-order cycles, blocking calls and callback invocation under a
    lock. See :mod:`paddle_tpu.analysis.concurrency`; CLI:
    ``tools/proglint.py --concurrency``."""
    from paddle_tpu.analysis.concurrency import run_concurrency_lint as f
    return f(paths=paths, root=root,
             include_suppressed=include_suppressed)


def verify_family(family):
    """Cross-view program-contract verifier over one program family
    (``{key: (main, startup, feed_specs, fetch_name)}``): shared-var
    shape/dtype agreement, rng-salt alignment, donation coherence and
    geometry-record drift. See :mod:`paddle_tpu.analysis.contracts`;
    CLI: ``tools/proglint.py --contracts``."""
    from paddle_tpu.analysis.contracts import verify_family as f
    return f(family)


def validate_geometry(mode, prompt_len, max_new, **kwargs):
    """Normalize + validate one decoder_lm view's geometry constants
    into a :class:`~paddle_tpu.analysis.contracts.GeometryRecord` (the
    single source the view builders and the family verifier share)."""
    from paddle_tpu.analysis.contracts import validate_geometry as f
    return f(mode, prompt_len, max_new, **kwargs)


def analyze_program(program, feed_names: Optional[Sequence[str]] = None,
                    fetch_names: Optional[Sequence[str]] = None,
                    is_test: bool = False,
                    rules: Optional[Sequence[str]] = None,
                    suppress: Sequence[str] = ()) -> List[Diagnostic]:
    """Run the full rule catalog (or `rules`) over a program.

    `program` is a ``fluid.Program`` or an ``ir.ProgramDesc``. Feed and
    fetch names are optional: rules that need them (dead-op,
    unused-output, unfed-input) skip when they are unknown, so a
    program can be linted standalone (``tools/proglint.py``) or with
    the exact executor signature (``FLAGS_verify_program``). Returns
    diagnostics ordered errors-first.
    """
    return run_rules(program, feed_names=feed_names,
                     fetch_names=fetch_names, is_test=is_test,
                     rules=rules, suppress=suppress)


def verify_program(program, feed_names: Optional[Sequence[str]] = None,
                   fetch_names: Optional[Sequence[str]] = None,
                   is_test: bool = False,
                   suppress: Sequence[str] = ()) -> List[Diagnostic]:
    """Analyze and raise :class:`ProgramVerificationError` when any
    ERROR-severity diagnostic survives suppression; returns the full
    diagnostic list (warnings included) otherwise. This is what
    ``CompiledBlock`` calls under ``FLAGS_verify_program``."""
    diags = analyze_program(program, feed_names=feed_names,
                            fetch_names=fetch_names, is_test=is_test,
                            suppress=suppress)
    errors, _, _ = partition(diags)
    if errors:
        raise ProgramVerificationError(diags)
    return diags
