"""Structural verifier: IR invariants that must hold before lowering.

Capability parity with the reference's build-time validation
(reference: framework/op_desc.cc CheckAttrs + op_registry OpInfo checks
run on every append_op): unknown ops, dangling input/output vars,
def-before-use ordering, control-flow attr schemas, sub-block
parent-scope bindings, and forward/grad var pairing — each reported as a
:class:`~paddle_tpu.analysis.diagnostics.Diagnostic` with op provenance
instead of dying as a KeyError inside ``lowering.emit_op_seq``.
"""

from __future__ import annotations

from typing import Set

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity
from paddle_tpu.analysis.rules import (SKIPPED_OPS, SUB_BLOCK_ATTRS,
                                       AnalysisContext, register_rule)
from paddle_tpu.core.registry import has_op
from paddle_tpu.ops.grad_ops import GRAD_SUFFIX

# attrs through which a control-flow op binds parent-scope values into
# its sub-block's trace env (ops/control_flow.py emitters)
_BINDING_ATTRS = {
    "while": ("carry_vars", "x_vars"),
    "scan": ("carry_in_vars", "scan_in_vars", "x_vars"),
    "cond": ("x_vars",),
    "conditional_block": ("x_vars",),
}


def entry_bound(ctx: AnalysisContext, block_idx: int) -> Set[str]:
    """Names available in a sub-block's env at entry: whatever the owning
    control-flow op binds (carry/scan/x lists). Block 0 has no owner —
    its entry set is feeds + scope, handled separately."""
    owner = ctx.sub_block_owner.get(block_idx)
    if owner is None:
        return set()
    bi, oi = owner
    op = ctx.program.block(bi).ops[oi]
    bound: Set[str] = set()
    for attr in _BINDING_ATTRS.get(op.type, ()):
        vals = op.attrs.get(attr) or []
        if isinstance(vals, (list, tuple)):
            bound.update(str(v) for v in vals)
    return bound


@register_rule("unknown-op", Severity.ERROR,
               "op type has no registered emitter (core/registry.py); "
               "lowering would raise a KeyError mid-trace",
               category="structural")
def _unknown_op(ctx: AnalysisContext):
    for bi, block in enumerate(ctx.program.blocks):
        for oi, op in enumerate(block.ops):
            if op.type in SKIPPED_OPS or has_op(op.type):
                continue
            yield Diagnostic(
                rule="unknown-op", severity=Severity.ERROR,
                message=f"no emitter registered for op {op.type!r}",
                block_idx=bi, op_index=oi, op_type=op.type)


@register_rule("dangling-input", Severity.ERROR,
               "op input names a var with no VarDesc in the block or its "
               "ancestors and no producing op — undefined at trace time",
               category="structural")
def _dangling_input(ctx: AnalysisContext):
    for bi, block in enumerate(ctx.program.blocks):
        chain = ctx.ancestor_chain(bi)
        for oi, op in enumerate(block.ops):
            if op.type in SKIPPED_OPS:
                continue
            for slot, names in op.inputs.items():
                for n in names:
                    if ctx.resolve(bi, n) is not None:
                        continue
                    if any(n in ctx.writers[b] for b in chain):
                        continue
                    yield Diagnostic(
                        rule="dangling-input", severity=Severity.ERROR,
                        message=f"input slot {slot!r} references var "
                                f"{n!r}, which is neither declared nor "
                                f"produced by any op",
                        block_idx=bi, op_index=oi, op_type=op.type, var=n)


@register_rule("dangling-output", Severity.WARNING,
               "op writes a var with no VarDesc anywhere in scope — the "
               "IR symbol table has drifted from the op list",
               category="structural")
def _dangling_output(ctx: AnalysisContext):
    for bi, block in enumerate(ctx.program.blocks):
        for oi, op in enumerate(block.ops):
            if op.type in SKIPPED_OPS:
                continue
            for slot, names in op.outputs.items():
                for n in names:
                    if ctx.resolve(bi, n) is None:
                        yield Diagnostic(
                            rule="dangling-output",
                            severity=Severity.WARNING,
                            message=f"output slot {slot!r} writes var "
                                    f"{n!r}, which has no VarDesc",
                            block_idx=bi, op_index=oi, op_type=op.type,
                            var=n)


@register_rule("def-before-use", Severity.ERROR,
               "a non-persistable var is read before every op that "
               "writes it, so the trace env cannot contain it yet",
               category="structural")
def _def_before_use(ctx: AnalysisContext):
    for bi, block in enumerate(ctx.program.blocks):
        bound = entry_bound(ctx, bi)
        for oi, op in enumerate(block.ops):
            if op.type in SKIPPED_OPS:
                continue
            for n in op.input_names():
                writes = ctx.writers[bi].get(n)
                if not writes:
                    continue            # never written: a feed/scope source
                if min(writes) < oi:
                    continue            # defined by an earlier op
                if n in bound:
                    continue            # bound at sub-block entry
                if ctx.feed_names is not None and n in ctx.feed_names:
                    continue
                vd = ctx.resolve(bi, n)
                if vd is not None and vd.persistable:
                    continue            # read from scope, updated later
                yield Diagnostic(
                    rule="def-before-use", severity=Severity.ERROR,
                    message=f"var {n!r} is read here but first written by "
                            f"op {min(writes)} "
                            f"({block.ops[min(writes)].type!r}) — "
                            f"program order defines it too late",
                    block_idx=bi, op_index=oi, op_type=op.type, var=n,
                    details={"first_write_index": min(writes)})


@register_rule("subblock-unbound-read", Severity.ERROR,
               "a sub-block op reads a parent-scope var the owning "
               "control-flow op does not bind (x_vars/carry_vars/...) — "
               "emit_subblock would KeyError at trace time",
               category="structural")
def _subblock_unbound_read(ctx: AnalysisContext):
    for bi in ctx.sub_block_owner:
        block = ctx.program.block(bi)
        owner_bi, owner_oi = ctx.sub_block_owner[bi]
        owner = ctx.program.block(owner_bi).ops[owner_oi]
        bound = entry_bound(ctx, bi)
        produced: Set[str] = set()
        for oi, op in enumerate(block.ops):
            if op.type in SKIPPED_OPS:
                continue
            for n in op.input_names():
                if n in bound or n in produced:
                    continue
                yield Diagnostic(
                    rule="subblock-unbound-read", severity=Severity.ERROR,
                    message=f"var {n!r} is read inside sub-block {bi} but "
                            f"not bound by the owning {owner.type!r} op "
                            f"(block {owner_bi}, op {owner_oi}); add it "
                            f"to x_vars or the carry",
                    block_idx=bi, op_index=oi, op_type=op.type, var=n,
                    details={"owner_block": owner_bi,
                             "owner_op": owner_oi,
                             "owner_type": owner.type})
            produced.update(op.output_names())


def _is_int_list(v) -> bool:
    return isinstance(v, (list, tuple)) and \
        all(isinstance(x, (int, bool)) for x in v)


def _is_str_list(v) -> bool:
    return isinstance(v, (list, tuple)) and all(isinstance(x, str) for x in v)


@register_rule("attr-schema", Severity.ERROR,
               "op attributes violate the emitter's schema: missing "
               "required control-flow attrs, sub_block indices out of "
               "range, malformed __vjp__ masks",
               category="structural")
def _attr_schema(ctx: AnalysisContext):
    n_blocks = len(ctx.program.blocks)
    for bi, block in enumerate(ctx.program.blocks):
        for oi, op in enumerate(block.ops):
            where = dict(block_idx=bi, op_index=oi, op_type=op.type)

            def bad(msg, **details):
                return Diagnostic(rule="attr-schema",
                                  severity=Severity.ERROR, message=msg,
                                  details=details, **where)

            if op.type in SUB_BLOCK_ATTRS:
                required = {"while": ("sub_block", "cond_var",
                                      "carry_vars"),
                            "scan": ("sub_block",),
                            "cond": ("out_vars",),
                            "conditional_block": ("out_vars",)}[op.type]
                for a in required:
                    if a not in op.attrs:
                        yield bad(f"{op.type!r} op is missing required "
                                  f"attr {a!r}", attr=a)
                for a in SUB_BLOCK_ATTRS[op.type]:
                    sb = op.attrs.get(a, -1)
                    if not isinstance(sb, int):
                        yield bad(f"attr {a!r} must be a block index, "
                                  f"got {type(sb).__name__}", attr=a)
                    elif sb >= n_blocks or (sb >= 0 and sb == bi):
                        yield bad(f"attr {a!r} references block {sb}, "
                                  f"which "
                                  + ("is the op's own block"
                                     if sb == bi else "does not exist"),
                                  attr=a, block_ref=sb)
                for a in _BINDING_ATTRS.get(op.type, ()) + ("out_vars",):
                    v = op.attrs.get(a)
                    if v is not None and not _is_str_list(v):
                        yield bad(f"attr {a!r} must be a list of var "
                                  f"names", attr=a)
                cv = op.attrs.get("cond_var")
                carry = op.attrs.get("carry_vars")
                if op.type == "while" and isinstance(cv, str) \
                        and _is_str_list(carry) and cv not in carry:
                    yield bad(f"cond_var {cv!r} is not in carry_vars "
                              f"{list(carry)}", attr="cond_var")
            elif op.type == "__vjp__":
                fwd = op.attrs.get("fwd_op")
                if not isinstance(fwd, dict) or "type" not in fwd:
                    yield bad("__vjp__ op is missing its fwd_op dict")
                    continue
                n_out = sum(len(v)
                            for v in (fwd.get("outputs") or {}).values())
                masks = {"in_grad_mask": len(op.input("FwdIn")),
                         "out_grad_mask": n_out}
                for a, want in masks.items():
                    m = op.attrs.get(a)
                    if not _is_int_list(m):
                        yield bad(f"__vjp__ attr {a!r} must be a list of "
                                  f"booleans", attr=a)
                    elif want and len(m) != want:
                        yield bad(f"__vjp__ attr {a!r} has {len(m)} "
                                  f"entries for {want} slots", attr=a,
                                  expected=want, got=len(m))


@register_rule("grad-pairing", Severity.WARNING,
               "a @GRAD var exists whose forward counterpart is missing "
               "— backward graph drifted from the forward",
               category="structural")
def _grad_pairing(ctx: AnalysisContext):
    for bi, block in enumerate(ctx.program.blocks):
        for name in block.vars:
            if GRAD_SUFFIX not in name:
                continue
            base = name.split(GRAD_SUFFIX, 1)[0]
            if not base or ctx.resolve(bi, base) is not None:
                continue
            yield Diagnostic(
                rule="grad-pairing", severity=Severity.WARNING,
                message=f"gradient var {name!r} has no forward var "
                        f"{base!r} in scope",
                block_idx=bi, var=name, details={"forward_var": base})
