"""Lint-rule framework: registry, analysis context, suppressions, runner.

A rule is a function ``rule(ctx: AnalysisContext) -> Iterable[Diagnostic]``
registered under a stable kebab-case id with a default severity. The
runner executes every (selected) rule over a program, applies per-op and
program-level suppressions, and publishes
``paddle_analysis_diagnostics_total{rule,severity}`` plus a per-program
duration histogram to the observability registry
(docs/observability.md conventions; docs/static_analysis.md catalogs the
rules).

Suppression syntax (docs/static_analysis.md):

- per op: the op attr ``__lint_suppress__`` holds a list of rule ids (or
  ``"*"``) — diagnostics anchored to that op are dropped. Layer code can
  set it via :func:`suppress_op`.
- per run: ``analyze_program(..., suppress=("dead-op", ...))`` drops the
  rule program-wide.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity
from paddle_tpu.core import ir

SUPPRESS_ATTR = "__lint_suppress__"

# control-flow ops and the attrs naming their sub-blocks (block indices;
# -1 means "no block", e.g. a cond with an identity false branch)
SUB_BLOCK_ATTRS: Dict[str, Tuple[str, ...]] = {
    "while": ("sub_block",),
    "scan": ("sub_block",),
    "cond": ("sub_block_true", "sub_block_false"),
    "conditional_block": ("sub_block_true", "sub_block_false"),
}

# ops accepted in programs but skipped at lowering (executor feeds/fetches
# are native jit arguments — core/executor.py module docstring)
SKIPPED_OPS = frozenset({"feed", "fetch"})


@dataclass(frozen=True)
class RuleSpec:
    id: str
    severity: Severity
    help: str
    fn: Callable
    category: str = "general"


RULES: Dict[str, RuleSpec] = {}


def register_rule(rule_id: str, severity: Severity, help_: str,
                  category: str = "general"):
    """Register an analysis rule (analogue of the op registry's
    ``register_op`` — one flat, importable catalog)."""

    def deco(fn: Callable) -> Callable:
        if rule_id in RULES:
            raise ValueError(f"rule {rule_id!r} registered twice")
        RULES[rule_id] = RuleSpec(id=rule_id, severity=severity,
                                  help=help_, fn=fn, category=category)
        return fn

    return deco


def all_rules() -> Dict[str, RuleSpec]:
    _ensure_builtin_rules()
    return dict(RULES)


def suppress_op(op, *rule_ids: str):
    """Mark an op (framework.Operator or ir.OpDesc) so the given rules
    skip it (``"*"`` suppresses everything)."""
    desc = op.desc if hasattr(op, "desc") else op
    cur = list(desc.attrs.get(SUPPRESS_ATTR, []))
    for r in rule_ids:
        if r not in cur:
            cur.append(r)
    desc.attrs[SUPPRESS_ATTR] = cur


class AnalysisContext:
    """Shared, precomputed view of one program that every rule reads.

    Indexing is over the serialized IR (``ir.ProgramDesc``) so the same
    analysis covers programs built through ``fluid.framework``, loaded
    from a saved ``__model__.json``, or hand-constructed.
    """

    def __init__(self, program: ir.ProgramDesc,
                 feed_names: Optional[Sequence[str]] = None,
                 fetch_names: Optional[Sequence[str]] = None,
                 is_test: bool = False):
        self.program = program
        self.feed_names = (frozenset(feed_names)
                           if feed_names is not None else None)
        self.fetch_names = (tuple(fetch_names)
                            if fetch_names is not None else None)
        self.is_test = bool(is_test)

        # per block: name -> sorted op indices writing / reading it
        self.writers: List[Dict[str, List[int]]] = []
        self.readers: List[Dict[str, List[int]]] = []
        for block in program.blocks:
            w: Dict[str, List[int]] = {}
            r: Dict[str, List[int]] = {}
            for i, op in enumerate(block.ops):
                for n in op.input_names():
                    r.setdefault(n, []).append(i)
                for n in op.output_names():
                    w.setdefault(n, []).append(i)
            self.writers.append(w)
            self.readers.append(r)

        # block idx -> (parent block idx, parent op index) for blocks
        # referenced from a control-flow op's sub_block attrs
        self.sub_block_owner: Dict[int, Tuple[int, int]] = {}
        for bi, block in enumerate(program.blocks):
            for oi, op in enumerate(block.ops):
                for attr in SUB_BLOCK_ATTRS.get(op.type, ()):
                    sb = op.attrs.get(attr, -1)
                    if isinstance(sb, int) and 0 <= sb < len(program.blocks):
                        self.sub_block_owner.setdefault(sb, (bi, oi))

    # -- var resolution ---------------------------------------------------
    def resolve(self, block_idx: int, name: str) -> Optional[ir.VarDesc]:
        """VarDesc for `name` in `block_idx` or its ancestor chain."""
        return ir.find_var_recursive(self.program,
                                     self.program.block(block_idx), name)

    def written_anywhere(self, name: str) -> bool:
        return any(name in w for w in self.writers)

    def ancestor_chain(self, block_idx: int) -> List[int]:
        """[block_idx, parent, ..., 0] following parent_idx links."""
        out = [block_idx]
        b = self.program.block(block_idx)
        while b.idx != 0 and 0 <= b.parent_idx != b.idx:
            b = self.program.block(b.parent_idx)
            out.append(b.idx)
        return out

    # -- liveness (mirrors lowering.analyze_block for block 0) ------------
    def live_ops(self) -> Optional[frozenset]:
        """Indices of block-0 ops that would execute for the declared
        fetch set, or None when fetches are unknown. Matches
        ``lowering.analyze_block``: an op is live if it contributes to a
        fetch or writes persistable state."""
        if self.fetch_names is None:
            return None
        cached = getattr(self, "_live_ops", None)
        if cached is not None:
            return cached
        block = self.program.global_block

        def is_persistable(n: str) -> bool:
            vd = self.resolve(0, n)
            return vd is not None and vd.persistable

        needed = set(self.fetch_names)
        live = set()
        for i in range(len(block.ops) - 1, -1, -1):
            op = block.ops[i]
            if op.type in SKIPPED_OPS:
                continue
            outs = op.output_names()
            if (set(outs) & needed) or any(is_persistable(n) for n in outs):
                live.add(i)
                needed.update(op.input_names())
        self._live_ops = frozenset(live)
        return self._live_ops


def _ensure_builtin_rules():
    # rule modules self-register on import (same pattern as ops/__init__
    # registering emitters); imported lazily to avoid a cycle with
    # core.shape_inference. The concurrency + contracts rules live in
    # the same catalog (--list-rules, docs) but run over their own
    # contexts (ConcurrencyContext / FamilyContext) and no-op here.
    from paddle_tpu.analysis import (  # noqa: F401
        concurrency, contracts, dataflow, shapes, structural)


def _op_suppressions(op: ir.OpDesc) -> frozenset:
    sup = op.attrs.get(SUPPRESS_ATTR)
    if not sup:
        return frozenset()
    if isinstance(sup, str):
        sup = [sup]
    return frozenset(str(s) for s in sup)


def _suppressed(ctx: AnalysisContext, d: Diagnostic,
                program_suppress: frozenset) -> bool:
    if d.rule in program_suppress or "*" in program_suppress:
        return True
    if d.op_index is None:
        return False
    try:
        op = ctx.program.block(d.block_idx).ops[d.op_index]
    except (IndexError, TypeError):
        return False
    sup = _op_suppressions(op)
    return d.rule in sup or "*" in sup


def run_rules(program, feed_names=None, fetch_names=None, is_test=False,
              rules: Optional[Sequence[str]] = None,
              suppress: Sequence[str] = ()) -> List[Diagnostic]:
    """Run the (selected) rule catalog over a program and return the
    surviving diagnostics, ordered by severity (errors first) then by
    program position. Accepts a ``fluid.Program`` or an
    ``ir.ProgramDesc``."""
    _ensure_builtin_rules()
    desc = program.desc if hasattr(program, "desc") else program
    if is_test is False and getattr(program, "_is_test", False):
        is_test = True
    if rules is None:
        selected = list(RULES.values())
    else:
        unknown = sorted(set(rules) - set(RULES))
        if unknown:
            raise ValueError(f"unknown rule id(s) {unknown}; available: "
                             f"{sorted(RULES)}")
        selected = [RULES[r] for r in rules]
    program_suppress = frozenset(suppress)
    ctx = AnalysisContext(desc, feed_names=feed_names,
                          fetch_names=fetch_names, is_test=is_test)

    t0 = time.perf_counter()
    diags: List[Diagnostic] = []
    for spec in selected:
        for d in spec.fn(ctx):
            if not _suppressed(ctx, d, program_suppress):
                diags.append(d)
    diags.sort(key=lambda d: (-int(d.severity), d.block_idx,
                              -1 if d.op_index is None else d.op_index,
                              d.rule))
    _publish_metrics(diags, time.perf_counter() - t0)
    return diags


def declare_metrics():
    """Get-or-create the analyzer's metric families in the default
    registry (called per analysis run AND from the exporters' catalog
    preregistration so a scrape shows them at zero)."""
    from paddle_tpu.observability import metrics as obs_metrics
    diags = obs_metrics.counter(
        "paddle_analysis_diagnostics_total",
        "diagnostics emitted by the build-time program verifier, "
        "per rule and severity", ("rule", "severity"))
    dur = obs_metrics.histogram(
        "paddle_analysis_duration_seconds",
        "wall time of one whole-program analysis pass "
        "(structural + shape/dtype + dataflow rules)")
    return diags, dur


def _publish_metrics(diags: List[Diagnostic], elapsed_s: float):
    """paddle_analysis_diagnostics_total{rule,severity} + per-program
    duration histogram (never fails the analysis)."""
    try:
        fam, dur = declare_metrics()
        for d in diags:
            fam.labels(rule=d.rule, severity=str(d.severity)).inc()
        dur.observe(elapsed_s)
    except Exception:
        pass
