"""Event-driven trainer loop — the paddle.v2 capability surface
(reference: python/paddle/v2/trainer.py SGD class with
train(reader, num_passes, event_handler, feed_order), test(); events in
python/paddle/v2/event.py: BeginPass/EndPass/BeginIteration/EndIteration
with cost/metrics payloads; the later fluid Trainer mirrored the same
shape). SURVEY L7 note: v2-unique capabilities are delivered once in the
modern stack — this trainer drives the compiled-program executor, not a
GradientMachine."""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from paddle_tpu.observability import tracing as _tracing


class BeginPass:
    def __init__(self, pass_id):
        self.pass_id = pass_id


class EndPass:
    def __init__(self, pass_id, metrics):
        self.pass_id = pass_id
        self.metrics = metrics


class BeginIteration:
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndIteration:
    def __init__(self, pass_id, batch_id, cost, metrics):
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost
        self.metrics = metrics


class SGD:
    """reference: paddle.v2.trainer.SGD — construct with the built cost
    program, then .train(reader, event_handler). Here the cost/optimizer
    live in a fluid Program pair built by the caller (the modern two-
    program convention replaces v2's topology+parameters)."""

    def __init__(self, cost, main_program=None, startup_program=None,
                 place=None, extra_fetch: Optional[Dict[str, str]] = None):
        import paddle_tpu.fluid as fluid
        self._fluid = fluid
        self.cost = cost
        self.main = main_program or fluid.default_main_program()
        self.startup = startup_program or fluid.default_startup_program()
        self.exe = fluid.Executor(place or fluid.TPUPlace())
        self.extra_fetch = extra_fetch or {}
        self._initialized = False
        self._cached_test_prog = None

    def _init(self):
        if not self._initialized:
            self.exe.run(self.startup)
            self._initialized = True

    def _feed_dict(self, batch, feed_order: Optional[List[str]]):
        if not feed_order:
            raise ValueError(
                "feed_order is required: the column order of reader samples "
                "-> feed names (the v2 reference inferred it from the "
                "topology; Program feeds are unordered here)")
        cols = list(zip(*batch))
        return {name: np.asarray(col)
                for name, col in zip(feed_order, cols)}

    def train(self, reader: Callable, num_passes: int = 1,
              event_handler: Optional[Callable] = None,
              feed_order: Optional[List[str]] = None):
        """reader: batch reader (yields lists of sample tuples, e.g. from
        paddle_tpu.reader.batch(...)); feed_order maps sample columns to
        feed names."""
        self._init()
        event_handler = event_handler or (lambda e: None)
        fetch = [self.cost.name] + list(self.extra_fetch.values())
        for pass_id in range(num_passes):
            event_handler(BeginPass(pass_id))
            costs = []
            with _tracing.span("trainer.pass"):
                for batch_id, batch in enumerate(reader()):
                    event_handler(BeginIteration(pass_id, batch_id))
                    feed = self._feed_dict(batch, feed_order)
                    # step span: aggregates always (thread-safe event
                    # table), a timeline span under an active profiler;
                    # the executor records the step-stats sample
                    # (steps/s, examples/s, MFU gauges) when
                    # observability is enabled
                    with _tracing.span("trainer.step"):
                        vals = self.exe.run(self.main, feed=feed,
                                            fetch_list=fetch)
                    cost = float(np.asarray(vals[0]).reshape(()))
                    costs.append(cost)
                    metrics = {k: np.asarray(v) for k, v in
                               zip(self.extra_fetch, vals[1:])}
                    event_handler(EndIteration(pass_id, batch_id, cost,
                                               metrics))
            event_handler(EndPass(pass_id,
                                  {"mean_cost": float(np.mean(costs))
                                   if costs else float("nan")}))

    def _test_program(self, feed_order: List[str]):
        """Cost-only eval program: clone(for_test) then prune away the
        backward/optimizer ops so test() can never mutate parameters."""
        if self._cached_test_prog is None:
            from paddle_tpu.core import ir
            cloned = self.main.clone(for_test=True)
            pruned_block = ir.prune_block(cloned.desc.global_block,
                                          [self.cost.name],
                                          list(feed_order))
            cloned.desc.blocks = [pruned_block]
            cloned.desc.bump_version()
            self._cached_test_prog = cloned
        return self._cached_test_prog

    def test(self, reader: Callable, feed_order: Optional[List[str]] = None,
             test_program=None):
        """Average cost over a test reader (reference: v2 trainer.test).
        Evaluation runs a pruned cost-only program — never the optimizer."""
        self._init()
        prog = test_program or self._test_program(feed_order or [])
        costs = []
        for batch in reader():
            feed = self._feed_dict(batch, feed_order)
            (c,) = self.exe.run(prog, feed=feed,
                                fetch_list=[self.cost.name])
            costs.append(float(np.asarray(c).reshape(())))
        return {"mean_cost": float(np.mean(costs)) if costs else
                float("nan")}
