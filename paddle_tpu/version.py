"""Version metadata (reference: python/paddle/version.py —
full_version/major/minor/patch/commit consumed by tooling and the
fluid __init__ banner)."""

full_version = "1.2.0+tpu"
major = "1"
minor = "2"
patch = "0"
rc = "0"
istaged = True
commit = "tpu-native-rebuild"
with_mkl = "OFF"


def show():
    print("commit:", commit)
    print("version:", full_version)
