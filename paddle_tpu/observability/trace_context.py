"""Cross-process trace context: the Dapper-style causal spine.

PR 2's tracer records spans per process; every open ROADMAP item
(multi-process serving, cross-process elastic training) is about
*multiple processes failing independently*, and a request that crosses
``ServingClient`` → ``ModelServer`` → slot scheduler used to leave two
disconnected span logs. This module carries a W3C-traceparent-style
:class:`TraceContext` (trace_id / span_id / parent_id) in a
``contextvars.ContextVar`` and injects/extracts it through every JSON
wire format the repo owns:

- serving client/server (``serving/client.py`` / ``serving/server.py``)
- ``MasterClient`` / ``MasterServer`` RPCs, heartbeats included
  (``data/master_service.py``)
- ``AsyncTrainerClient`` / pserver push-pull
  (``distributed/async_pserver.py``)

so a span recorded in another process parents correctly: the server
extracts the caller's context, activates it for the handling thread,
and every :func:`tracing.span` recorded inside becomes a *child* of the
caller's span — ``tools/trace_collect.py`` then stitches the spools
into one Perfetto trace with flow events across the process edges.

Wire format: one extra JSON key ``"traceparent":
"00-<32 hex trace_id>-<16 hex span_id>-01"`` (the W3C header shape, as
a message field). The key is only added while a context is ACTIVE, so
with tracing off the wire bytes are identical to before.

Hot-path discipline: :func:`active` is one boolean check when tracing
is fully off; :func:`span` / :func:`client_span` yield immediately in
that case (docs/observability.md).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import time
from dataclasses import dataclass
from typing import Optional

from paddle_tpu.observability import tracing as _tracing

TRACEPARENT_KEY = "traceparent"
_VERSION = "00"
_FLAGS = "01"            # sampled


@dataclass(frozen=True)
class TraceContext:
    """One node of the causal tree: which trace this execution belongs
    to (``trace_id``), which span is currently open (``span_id``), and
    that span's parent (``parent_id``; None at the trace root)."""

    trace_id: str                       # 32 hex chars
    span_id: str                        # 16 hex chars
    parent_id: Optional[str] = None     # 16 hex chars or None

    def child(self) -> "TraceContext":
        """Fresh span under this one (same trace)."""
        return TraceContext(self.trace_id, _new_span_id(), self.span_id)

    def to_traceparent(self) -> str:
        return f"{_VERSION}-{self.trace_id}-{self.span_id}-{_FLAGS}"


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


def new_trace() -> TraceContext:
    """Start a new trace (root context, no parent)."""
    return TraceContext(_new_trace_id(), _new_span_id(), None)


def from_traceparent(header: str) -> Optional[TraceContext]:
    """Parse ``"00-<trace>-<span>-01"``; None on anything malformed
    (a hostile or stale peer must never break request handling)."""
    try:
        parts = str(header).split("-")
        if len(parts) != 4:
            return None
        _, trace_id, span_id, _ = parts
        int(trace_id, 16)
        int(span_id, 16)
        if len(trace_id) != 32 or len(span_id) != 16:
            return None
        return TraceContext(trace_id, span_id, None)
    except (ValueError, AttributeError):
        return None


_CURRENT: contextvars.ContextVar[Optional[TraceContext]] = \
    contextvars.ContextVar("paddle_trace_context", default=None)


def current() -> Optional[TraceContext]:
    """The context active on THIS thread/task (None outside any trace)."""
    return _CURRENT.get()


def attach(ctx: Optional[TraceContext]):
    """Set the current context; returns the token for :func:`detach`."""
    return _CURRENT.set(ctx)


def detach(token) -> None:
    _CURRENT.reset(token)


@contextlib.contextmanager
def activate(ctx: Optional[TraceContext]):
    """``with activate(extract(msg)): ...`` — scope a context (or None)
    to a block; always restores the previous one."""
    token = _CURRENT.set(ctx)
    try:
        yield ctx
    finally:
        _CURRENT.reset(token)


# -- wire inject / extract ----------------------------------------------

def inject(msg: dict) -> dict:
    """Stamp the ACTIVE context into an outgoing JSON message (in
    place). No-op without an active context — the wire stays
    byte-identical when tracing is off."""
    ctx = _CURRENT.get()
    if ctx is not None:
        msg[TRACEPARENT_KEY] = ctx.to_traceparent()
    return msg


def extract(msg: dict) -> Optional[TraceContext]:
    """Parse the caller's context out of an incoming message (None when
    absent/malformed). Activate it to parent this process's spans under
    the caller's span: ``with activate(extract(req)): handle(req)``."""
    header = msg.get(TRACEPARENT_KEY) if isinstance(msg, dict) else None
    if not header:
        return None
    return from_traceparent(header)


# -- span recording under the context -----------------------------------

def active() -> bool:
    """True when spans are being captured anywhere (tracer ring started
    or a spool/flight-recorder sink attached) — the one-flag check hot
    paths gate on."""
    return _tracing.active()


@contextlib.contextmanager
def span(name: str, ctx: Optional[TraceContext] = None, **args):
    """Record a span under ``ctx`` (default: the current context; a new
    root trace when none is active). The block runs with the span's own
    context current, so nested spans and injected RPCs parent to it.

    One boolean check and an immediate yield when tracing is off."""
    if not _tracing.active():
        yield None
        return
    parent = ctx if ctx is not None else _CURRENT.get()
    child = parent.child() if parent is not None else new_trace()
    token = _CURRENT.set(child)
    t0 = time.perf_counter()
    try:
        yield child
    finally:
        _CURRENT.reset(token)
        _tracing.default_tracer().record(
            name, t0, time.perf_counter(),
            args=args or None, trace=child)


# serving/master/pserver clients wrap each logical RPC in this: a root
# span when the caller isn't traced yet, a child span when it is —
# either way the traceparent injected INSIDE the block carries this
# span's id, so the server's spans parent under the client's.
client_span = span


def record_span(name: str, start_s: float, end_s: float,
                ctx: Optional[TraceContext] = None, **args) -> None:
    """Retroactively record a span that already happened (queue wait,
    decode step) as a child of ``ctx`` — for lifecycle phases measured
    by timestamps rather than wrapped in a with-block."""
    if not _tracing.active():
        return
    child = ctx.child() if ctx is not None else new_trace()
    _tracing.default_tracer().record(name, start_s, end_s,
                                     args=args or None, trace=child)


def current_or_new() -> Optional[TraceContext]:
    """The current context, or a fresh root when tracing is active but
    no caller context exists (an untraced client talking to a traced
    server still gets a server-side trace). None when tracing is off."""
    ctx = _CURRENT.get()
    if ctx is not None:
        return ctx
    if not _tracing.active():
        return None
    return new_trace()
