"""Thread-safe metrics registry: labeled Counter / Gauge / Histogram.

The control plane PR 1 hardened (retries, breakers, heartbeats, CRC
checkpoints) proves recovery in tests but is invisible in production —
there was no counter for a retried RPC, no gauge for heartbeat age. This
module is the one place runtime telemetry lands: a process-default
:class:`MetricsRegistry` of named metric *families*, each optionally
fanned out by label values, rendered either as Prometheus text
exposition (scraped via ``observability.exporters``) or as a JSON
snapshot (dumped next to checkpoints / bench results).

Design constraints (why not ``prometheus_client``): no new dependencies
(container bake rule), and the hot path must stay cheap enough that the
bench step loop shows <2% overhead — ``Counter.inc`` is one lock + one
float add, and instrument sites fire per control-plane EVENT (an RPC, a
lease, a checkpoint shard), never per tensor op.

Conventions (docs/observability.md catalogs every metric):
- names are ``paddle_<subsystem>_<what>[_total|_seconds|_bytes]``,
  counters end in ``_total``, durations are seconds (Prometheus idiom);
- label cardinality is bounded by construction: labels carry enum-like
  values (an RPC method name, a failure cause), never ids or paths;
- families are get-or-create (:func:`counter` twice returns the same
  family) so every instrumented module can declare its metrics at import
  without coordination.
"""

from __future__ import annotations

import json
import threading
import time
from bisect import bisect_left
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# latency-shaped default buckets (sub-ms RPCs up to multi-second
# checkpoint writes), upper bounds in seconds; +Inf is implicit
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def _check_name(name: str):
    if not name or not all(c.isalnum() or c == "_" for c in name) \
            or name[0].isdigit():
        raise ValueError(f"invalid metric name {name!r} (use "
                         f"[a-zA-Z_][a-zA-Z0-9_]*)")


def _escape_label(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


class Counter:
    """Monotonic accumulator for one label combination."""

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0.0

    def inc(self, amount: float = 1.0):
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-written value for one label combination."""

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0.0

    def set(self, value: float):
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0):
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    def set_to_current_time(self):
        self.set(time.time())

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Cumulative-bucket histogram for one label combination
    (Prometheus semantics: ``bucket[i]`` counts observations ≤
    ``upper_bounds[i]``, the implicit +Inf bucket equals ``count``)."""

    def __init__(self, lock: threading.Lock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self._lock = lock
        self.upper_bounds = tuple(sorted(float(b) for b in buckets))
        if not self.upper_bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._bucket_counts = [0] * len(self.upper_bounds)
        self._sum = 0.0
        self._count = 0
        # last exemplar per bucket index (len(upper_bounds) = +Inf):
        # bounded by construction, so an outlier in the top bucket is
        # one lookup away from its trace (docs/observability.md)
        self._exemplars: Dict[int, str] = {}

    def observe(self, value: float, exemplar: Optional[str] = None):
        v = float(value)
        # le semantics: v lands in the smallest bucket whose bound >= v
        # (bisect_left keeps an exact-bound observation in that bucket)
        i = bisect_left(self.upper_bounds, v)
        with self._lock:
            # per-bound counts here; rendered cumulatively (le semantics)
            if i < len(self._bucket_counts):
                self._bucket_counts[i] += 1
            self._sum += v
            self._count += 1
            if exemplar is not None:
                self._exemplars[i] = str(exemplar)

    def time(self):
        """``with hist.time(): ...`` — observe the block's duration."""
        return _HistTimer(self)

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """[(upper_bound, cumulative_count)], +Inf last."""
        return self.snapshot()[0]

    def snapshot(self) -> Tuple[List[Tuple[float, int]], float, int]:
        """(cumulative_buckets, sum, count) read under ONE lock hold —
        renderers must use this so a concurrent observe() can never
        produce text where bucket{le="+Inf"} != count."""
        with self._lock:
            out, acc = [], 0
            for ub, c in zip(self.upper_bounds, self._bucket_counts):
                acc += c
                out.append((ub, acc))
            out.append((float("inf"), self._count))
            return out, self._sum, self._count

    def exemplars(self) -> Dict[float, str]:
        """{bucket_upper_bound: last exemplar} for buckets that have
        one (e.g. the trace_id of the last sample to land there)."""
        with self._lock:
            bounds = self.upper_bounds + (float("inf"),)
            return {bounds[i]: ex for i, ex in self._exemplars.items()}


class _HistTimer:
    def __init__(self, hist: Histogram):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.perf_counter() - self._t0)


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Family:
    """One named metric fanned out by label values. A family declared
    with no ``labelnames`` proxies the metric methods directly
    (``family.inc()`` == ``family.labels().inc()``)."""

    def __init__(self, name: str, kind: str, help_: str,
                 labelnames: Sequence[str] = (), **kwargs):
        _check_name(name)
        for ln in labelnames:
            _check_name(ln)
        self.name = name
        self.kind = kind
        self.help = help_
        self.labelnames = tuple(labelnames)
        self._kwargs = kwargs
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            self.labels()          # eager zero-valued child: renders at 0

    def labels(self, *values, **kv) -> object:
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by "
                                 "keyword, not both")
            try:
                values = tuple(str(kv[ln]) for ln in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"{self.name}: missing label {e} "
                    f"(labels: {self.labelnames})") from None
            if len(kv) != len(self.labelnames):
                extra = set(kv) - set(self.labelnames)
                raise ValueError(f"{self.name}: unknown labels {extra}")
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects {len(self.labelnames)} label "
                f"value(s) {self.labelnames}, got {len(values)}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = _KINDS[self.kind](threading.Lock(), **self._kwargs)
                self._children[values] = child
            return child

    # -- no-label convenience proxies -----------------------------------
    def _solo(self):
        if self.labelnames:
            raise ValueError(f"{self.name} has labels {self.labelnames}; "
                             f"call .labels(...) first")
        return self.labels()

    def inc(self, amount: float = 1.0):
        self._solo().inc(amount)

    def dec(self, amount: float = 1.0):
        self._solo().dec(amount)

    def set(self, value: float):
        self._solo().set(value)

    def observe(self, value: float, exemplar: Optional[str] = None):
        self._solo().observe(value, exemplar)

    def time(self):
        return self._solo().time()

    @property
    def value(self):
        return self._solo().value

    def children(self) -> Dict[Tuple[str, ...], object]:
        with self._lock:
            return dict(self._children)


class MetricsRegistry:
    """Thread-safe name → :class:`Family` map with get-or-create
    declaration and two render targets (Prometheus text, JSON dict)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, Family] = {}

    def _declare(self, name: str, kind: str, help_: str,
                 labelnames: Sequence[str], **kwargs) -> Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != tuple(labelnames) \
                        or fam._kwargs != kwargs:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.labelnames} "
                        f"(options {fam._kwargs}), cannot redeclare as "
                        f"{kind}{tuple(labelnames)} (options {kwargs})")
                return fam
            fam = Family(name, kind, help_, labelnames, **kwargs)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help_: str = "",
                labelnames: Sequence[str] = ()) -> Family:
        return self._declare(name, "counter", help_, labelnames)

    def gauge(self, name: str, help_: str = "",
              labelnames: Sequence[str] = ()) -> Family:
        return self._declare(name, "gauge", help_, labelnames)

    def histogram(self, name: str, help_: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Family:
        return self._declare(name, "histogram", help_, labelnames,
                             buckets=buckets)

    def get(self, name: str) -> Optional[Family]:
        with self._lock:
            return self._families.get(name)

    def families(self) -> List[Family]:
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    def unregister(self, name: str):
        with self._lock:
            self._families.pop(name, None)

    def clear(self):
        """Drop every family — test isolation only; instrumented modules
        keep references to their (now orphaned) families, so production
        code must never call this."""
        with self._lock:
            self._families.clear()

    # -- rendering -------------------------------------------------------
    @staticmethod
    def _labels_text(names: Iterable[str], values: Iterable[str],
                     extra: Tuple[str, str] = None) -> str:
        pairs = [(n, v) for n, v in zip(names, values)]
        if extra is not None:
            pairs.append(extra)
        if not pairs:
            return ""
        inner = ",".join(f'{n}="{_escape_label(v)}"' for n, v in pairs)
        return "{" + inner + "}"

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4. HELP/TYPE lines render
        for every registered family — a scrape shows the full catalog
        from process start, not metrics popping into existence."""
        lines: List[str] = []
        for fam in self.families():
            help_ = fam.help.replace("\\", r"\\").replace("\n", r"\n")
            lines.append(f"# HELP {fam.name} {help_}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for values, child in sorted(fam.children().items()):
                lt = self._labels_text(fam.labelnames, values)
                if fam.kind == "histogram":
                    buckets, hsum, hcount = child.snapshot()
                    for ub, cum in buckets:
                        blt = self._labels_text(
                            fam.labelnames, values, ("le", _fmt_value(ub)))
                        lines.append(f"{fam.name}_bucket{blt} {cum}")
                    lines.append(f"{fam.name}_sum{lt} "
                                 f"{_fmt_value(hsum)}")
                    lines.append(f"{fam.name}_count{lt} {hcount}")
                else:
                    lines.append(f"{fam.name}{lt} "
                                 f"{_fmt_value(child.value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, dict]:
        """JSON-able {name: {type, help, samples: [...]}} — the format
        the exporters dump and bench.py writes next to its results."""
        out: Dict[str, dict] = {}
        for fam in self.families():
            samples = []
            for values, child in sorted(fam.children().items()):
                labels = dict(zip(fam.labelnames, values))
                if fam.kind == "histogram":
                    buckets, hsum, hcount = child.snapshot()
                    sample = {
                        "labels": labels, "sum": hsum, "count": hcount,
                        "buckets": [[("inf" if ub == float("inf") else ub),
                                     c] for ub, c in buckets]}
                    ex = child.exemplars()
                    if ex:
                        sample["exemplars"] = {
                            ("inf" if ub == float("inf") else str(ub)): e
                            for ub, e in ex.items()}
                    samples.append(sample)
                else:
                    samples.append({"labels": labels,
                                    "value": child.value})
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "samples": samples}
        return out

    def snapshot_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-default registry every instrumented module declares
    into (the analogue of prometheus_client's REGISTRY)."""
    return _DEFAULT


def counter(name: str, help_: str = "",
            labelnames: Sequence[str] = ()) -> Family:
    return _DEFAULT.counter(name, help_, labelnames)


def gauge(name: str, help_: str = "",
          labelnames: Sequence[str] = ()) -> Family:
    return _DEFAULT.gauge(name, help_, labelnames)


def histogram(name: str, help_: str = "", labelnames: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Family:
    return _DEFAULT.histogram(name, help_, labelnames, buckets=buckets)
