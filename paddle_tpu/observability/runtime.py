"""Per-compiled-step runtime stats: step-time ring buffer → steps/s,
examples/s, tokens/s gauges, plus an MFU gauge.

The executor records one sample per *dispatch* (a dispatch covers
``iterations`` device-side steps under the lax.scan hot loop, so the
per-sample overhead amortizes to nothing); the ring buffer holds the
last ``window`` samples and the throughput gauges are recomputed from
the window on every record — an operator scraping /metrics sees a
moving-average rate, not a lifetime mean.

MFU comes from XLA's own compiled-computation cost analysis
(``jit_fn.lower(...).compile().cost_analysis()['flops']``, the
per-signature truth about what the compiler actually emitted), cached
per jit signature; when the backend reports no FLOPs the analytic
model-FLOP walk (``utils/flops.py``, 2 FLOPs/MAC, backward = 2x
forward) is the fallback. The peak-FLOP/s denominator is the attached
chip's spec-sheet number (``utils.flops.device_peak_flops``) or the
``FLAGS_peak_flops`` override (how CPU runs and tests get a real MFU
value instead of null).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, Optional

from paddle_tpu.observability import metrics

STEPS_TOTAL = metrics.counter(
    "paddle_steps_total", "Training/executor steps dispatched")
STEP_TIME = metrics.gauge(
    "paddle_step_time_seconds", "Wall time per step, last dispatch "
    "(dispatch time / iterations; includes D2H sync when the caller "
    "fetched numpy)")
STEPS_PER_S = metrics.gauge(
    "paddle_steps_per_second", "Steps/s over the ring-buffer window")
EXAMPLES_PER_S = metrics.gauge(
    "paddle_examples_per_second", "Examples/s over the ring-buffer window")
TOKENS_PER_S = metrics.gauge(
    "paddle_tokens_per_second", "Tokens/s over the ring-buffer window "
    "(0 until a caller declares tokens-per-example)")
MFU = metrics.gauge(
    "paddle_mfu_ratio", "Model FLOPs Utilization in [0,1]: achieved "
    "FLOP/s over peak (FLAGS_peak_flops or the chip spec sheet); 0 when "
    "no peak is known")


class StepStats:
    """Ring buffer of (step_time_s, steps, examples, tokens, flops)
    samples; recomputes the throughput/MFU gauges on every record."""

    def __init__(self, window: int = 256):
        self._lock = threading.Lock()
        self._ring = deque(maxlen=int(window))
        self._peak_flops: Optional[float] = None
        self._peak_resolved = False
        self.total_steps = 0

    # -- peak-FLOPs denominator -----------------------------------------
    def _peak(self) -> Optional[float]:
        from paddle_tpu import flags
        override = flags.get("peak_flops")
        if override:
            return float(override)
        if not self._peak_resolved:
            self._peak_resolved = True
            try:
                from paddle_tpu.utils import flops as flops_mod
                self._peak_flops = flops_mod.device_peak_flops()
            except Exception:
                self._peak_flops = None
        return self._peak_flops

    # -- recording -------------------------------------------------------
    def record(self, step_time_s: float, steps: int = 1,
               examples: Optional[int] = None,
               tokens: Optional[int] = None,
               flops_per_step: Optional[float] = None) -> dict:
        """Record one dispatch of ``steps`` device steps that took
        ``step_time_s`` seconds *per step*. Returns the snapshot dict the
        step-JSONL exporter appends (one line per dispatch)."""
        with self._lock:
            self._ring.append((float(step_time_s), int(steps),
                               examples, tokens, flops_per_step))
            self.total_steps += int(steps)
            secs = sum(r[0] * r[1] for r in self._ring)
            n = sum(r[1] for r in self._ring)
            ex = sum((r[2] or 0) * r[1] for r in self._ring)
            tok = sum((r[3] or 0) * r[1] for r in self._ring)
            total = self.total_steps
        steps_s = n / secs if secs > 0 else 0.0
        examples_s = ex / secs if secs > 0 else 0.0
        tokens_s = tok / secs if secs > 0 else 0.0
        STEPS_TOTAL.inc(steps)
        STEP_TIME.set(step_time_s)
        STEPS_PER_S.set(steps_s)
        EXAMPLES_PER_S.set(examples_s)
        TOKENS_PER_S.set(tokens_s)
        mfu = None
        peak = self._peak()
        if peak and flops_per_step and step_time_s > 0:
            mfu = flops_per_step / step_time_s / peak
            MFU.set(mfu)
        return {"step": total, "step_time_s": step_time_s,
                "steps_per_s": round(steps_s, 4),
                "examples_per_s": round(examples_s, 2),
                "tokens_per_s": round(tokens_s, 2), "mfu": mfu}

    def reset(self):
        with self._lock:
            self._ring.clear()
            self.total_steps = 0


_DEFAULT = StepStats()


def step_stats() -> StepStats:
    return _DEFAULT


def record_dispatch(step_time_s: float, steps: int = 1,
                    examples: Optional[int] = None,
                    tokens: Optional[int] = None,
                    flops_per_step: Optional[float] = None):
    """Record into the process-default :class:`StepStats` and hand the
    per-dispatch record to the step-JSONL exporter (no-op unless the
    dump thread is running)."""
    rec = _DEFAULT.record(step_time_s, steps, examples=examples,
                          tokens=tokens, flops_per_step=flops_per_step)
    from paddle_tpu.observability import exporters
    exporters.offer_step_record(rec)
    return rec


# -- compiled-cost FLOPs (cached per jit signature) -----------------------

_COST_CACHE: Dict[Any, Optional[float]] = {}
_COST_LOCK = threading.Lock()
_COST_CACHE_MAX = 4096     # bound: long-lived processes churning
# compiled blocks (per-shape serving compiles) must not grow this
# forever — dicts iterate in insertion order, so eviction is FIFO


def cost_cache_peek(key: Any):
    """(hit, value) for a compiled-cost cache key — lets callers skip
    argument gathering entirely once a signature is resolved."""
    with _COST_LOCK:
        if key in _COST_CACHE:
            return True, _COST_CACHE[key]
    return False, None


def compiled_flops(jit_fn, *args, cache_key: Any = None,
                   per_call_steps: int = 1) -> Optional[float]:
    """Per-step FLOPs of ``jit_fn`` specialized to ``args``, from XLA's
    compiled-cost analysis. ``cache_key`` identifies the jit signature
    (callers pass their executable-cache key); the lower/compile round
    trip runs once per key — jax's internal caches make it cheap when
    the signature was already compiled by a real call. Returns None when
    the backend reports no FLOPs (callers fall back to the analytic walk
    in ``utils/flops.py``)."""
    key = cache_key if cache_key is not None else id(jit_fn)
    with _COST_LOCK:
        if key in _COST_CACHE:
            return _COST_CACHE[key]
    flops: Optional[float] = None
    try:
        cost = jit_fn.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):   # older jax: one per device
            cost = cost[0] if cost else {}
        raw = float(cost.get("flops", 0.0) or 0.0)
        # some backends report -1/0 for "unknown"
        if raw > 0:
            flops = raw / max(int(per_call_steps), 1)
    except Exception:
        flops = None
    with _COST_LOCK:
        while len(_COST_CACHE) >= _COST_CACHE_MAX:
            _COST_CACHE.pop(next(iter(_COST_CACHE)))
        _COST_CACHE[key] = flops
    return flops


def mfu_ratio(flops_per_step: Optional[float], step_time_s: float,
              device=None) -> Optional[float]:
    """MFU in [0,1] from per-step FLOPs + step time, against
    FLAGS_peak_flops (override) or the attached chip's spec-sheet peak.
    None when either side is unknown."""
    if not flops_per_step or step_time_s <= 0:
        return None
    from paddle_tpu import flags
    peak = float(flags.get("peak_flops")) or None
    if peak is None:
        try:
            from paddle_tpu.utils import flops as flops_mod
            peak = flops_mod.device_peak_flops(device)
        except Exception:
            peak = None
    if not peak:
        return None
    return flops_per_step / step_time_s / peak
