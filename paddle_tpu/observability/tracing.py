"""Structured host-side tracing: a lock-protected, thread-id-aware span
recorder with a context-manager/decorator API and chrome-trace export.

This replaces ``fluid/profiler.py``'s module-global ``_events``/``_spans``
lists, which were mutated without a lock from reader/producer threads
(the DataLoader's produce thread races the training thread) and recorded
no thread ids, so ``spans_to_chrome_trace`` stacked every thread on
tid 0. ``fluid.profiler`` now delegates here (public API unchanged);
new code uses :func:`span` / :func:`trace` directly.

Two always-cheap layers:
- **event aggregates** — per-name {calls, total, min, max}, updated on
  every :func:`span` exit (a dict update under one lock);
- **span records** — (name, t0, t1, tid, args) appended only while the
  tracer is *enabled* (``start()``/``stop()``), bounded by ``max_spans``
  so a forgotten ``start()`` cannot grow memory without bound.

Export: :func:`to_chrome_trace` emits the chrome://tracing JSON dict,
which Perfetto (ui.perfetto.dev) opens natively — the host-side half of
the timeline; device-side traces stay with jax.profiler (XPlane).
"""

from __future__ import annotations

import contextlib
import functools
import json
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional


@dataclass
class Span:
    name: str
    start_s: float            # time.perf_counter() timebase
    end_s: float
    tid: int                  # real thread id (threading.get_ident())
    args: Optional[dict] = None

    @property
    def duration_s(self) -> float:
        return self.end_s - self.start_s


@dataclass
class _EventStat:
    calls: int = 0
    total: float = 0.0
    min: float = float("inf")
    max: float = 0.0


class Tracer:
    """Thread-safe span recorder. One process-default instance
    (:func:`default_tracer`) backs both ``fluid.profiler`` and the
    ``observability`` API, so spans from either land on one timeline."""

    def __init__(self, max_spans: int = 200_000):
        self._lock = threading.Lock()
        self._events: Dict[str, _EventStat] = {}
        self._spans: List[Span] = []
        self._dropped = 0
        self._enabled = False
        self.max_spans = int(max_spans)

    # -- control ---------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def start(self):
        self._enabled = True

    def stop(self):
        self._enabled = False

    def reset(self):
        with self._lock:
            self._events.clear()
            self._spans.clear()
            self._dropped = 0

    # -- recording -------------------------------------------------------
    def record(self, name: str, start_s: float, end_s: float,
               tid: Optional[int] = None, args: Optional[dict] = None):
        """Record one finished span: aggregates always, the span record
        only while enabled. Safe from any thread."""
        dt = end_s - start_s
        with self._lock:
            e = self._events.get(name)
            if e is None:
                e = self._events[name] = _EventStat()
            e.calls += 1
            e.total += dt
            if dt < e.min:
                e.min = dt
            if dt > e.max:
                e.max = dt
            if self._enabled:
                if len(self._spans) < self.max_spans:
                    self._spans.append(Span(
                        name, start_s, end_s,
                        tid if tid is not None else threading.get_ident(),
                        args))
                else:
                    self._dropped += 1

    @contextlib.contextmanager
    def span(self, name: str, **args):
        """``with tracer.span("step"): ...`` — RAII span + aggregate."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, t0, time.perf_counter(),
                        args=args or None)

    def trace(self, name_or_fn=None):
        """Decorator form: ``@tracer.trace`` or ``@tracer.trace("name")``."""
        def deco(fn, name=None):
            label = name or f"{fn.__module__}.{fn.__qualname__}"

            @functools.wraps(fn)
            def wrapper(*a, **kw):
                with self.span(label):
                    return fn(*a, **kw)
            return wrapper

        if callable(name_or_fn):
            return deco(name_or_fn)
        return lambda fn: deco(fn, name_or_fn)

    # -- reading ---------------------------------------------------------
    def event_stats(self) -> Dict[str, dict]:
        with self._lock:
            return {n: {"calls": e.calls, "total": e.total,
                        "min": e.min, "max": e.max}
                    for n, e in self._events.items()}

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    @property
    def dropped_spans(self) -> int:
        with self._lock:
            return self._dropped

    # -- export ----------------------------------------------------------
    def to_chrome_trace(self, pid: int = 0) -> dict:
        """chrome://tracing / Perfetto JSON ('X' complete events, µs)."""
        events = []
        for s in self.spans():
            ev = {"name": s.name, "cat": "host", "ph": "X",
                  "ts": s.start_s * 1e6, "dur": s.duration_s * 1e6,
                  "pid": pid, "tid": s.tid}
            if s.args:
                ev["args"] = s.args
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str, pid: int = 0):
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(pid), f)


_DEFAULT = Tracer()


def default_tracer() -> Tracer:
    return _DEFAULT


def span(name: str, **args):
    """Module-level convenience on the default tracer:
    ``with tracing.span("master.get_task"): ...``"""
    return _DEFAULT.span(name, **args)


def trace(name_or_fn=None):
    """``@tracing.trace`` / ``@tracing.trace("name")`` on the default
    tracer."""
    return _DEFAULT.trace(name_or_fn)
