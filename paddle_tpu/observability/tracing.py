"""Structured host-side tracing: a lock-protected, thread-id-aware span
recorder with a context-manager/decorator API and chrome-trace export.

This replaces ``fluid/profiler.py``'s module-global ``_events``/``_spans``
lists, which were mutated without a lock from reader/producer threads
(the DataLoader's produce thread races the training thread) and recorded
no thread ids, so ``spans_to_chrome_trace`` stacked every thread on
tid 0. ``fluid.profiler`` now delegates here (public API unchanged);
new code uses :func:`span` / :func:`trace` directly.

Two always-cheap layers:
- **event aggregates** — per-name {calls, total, min, max}, updated on
  every :func:`span` exit (a dict update under one lock);
- **span records** — (name, t0, t1, tid, args) appended only while the
  tracer is *enabled* (``start()``/``stop()``), bounded by ``max_spans``
  so a forgotten ``start()`` cannot grow memory without bound.

Distributed additions (docs/observability.md "Distributed tracing"):
- spans carry optional **trace identity** (trace_id/span_id/parent_id
  from ``observability.trace_context``), and :meth:`Tracer.span`
  auto-parents under the thread's current :class:`TraceContext`, so an
  RPC handler that activated its caller's context gets correctly
  parented ``executor.run`` / ``master.*`` spans for free;
- **sinks** — callables invoked with each finished :class:`Span`
  (outside the tracer lock); the per-process spool and the flight
  recorder attach here. Spans are *constructed* when enabled OR a sink
  is attached; the in-memory ring only fills while enabled.
- ring overflow is no longer silent: drops count into
  ``paddle_trace_dropped_spans_total`` (exporter-preregistered) and the
  first drop emits a one-time warning.

Export: :func:`to_chrome_trace` emits the chrome://tracing JSON dict,
which Perfetto (ui.perfetto.dev) opens natively — the host-side half of
the timeline; device-side traces stay with jax.profiler (XPlane).
Cross-process merge is ``tools/trace_collect.py`` over the spools.
"""

from __future__ import annotations

import contextlib
import functools
import json
import threading
import time
import warnings
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from paddle_tpu.observability import metrics as _metrics

DROPPED_SPANS = _metrics.counter(
    "paddle_trace_dropped_spans_total",
    "Spans dropped on the tracer ring's max_spans bound — a non-zero "
    "value means the in-memory timeline is truncated (raise max_spans "
    "or export more often); spool/flight-recorder sinks still saw them")


@dataclass
class Span:
    name: str
    start_s: float            # time.perf_counter() timebase
    end_s: float
    tid: int                  # real thread id (threading.get_ident())
    args: Optional[dict] = None
    # distributed identity (None for purely local spans)
    trace_id: Optional[str] = None
    span_id: Optional[str] = None
    parent_id: Optional[str] = None

    @property
    def duration_s(self) -> float:
        return self.end_s - self.start_s


@dataclass
class _EventStat:
    calls: int = 0
    total: float = 0.0
    min: float = float("inf")
    max: float = 0.0


class Tracer:
    """Thread-safe span recorder. One process-default instance
    (:func:`default_tracer`) backs both ``fluid.profiler`` and the
    ``observability`` API, so spans from either land on one timeline."""

    def __init__(self, max_spans: int = 200_000):
        self._lock = threading.Lock()
        self._events: Dict[str, _EventStat] = {}
        self._spans: List[Span] = []
        self._dropped = 0
        self._dropped_warned = False
        self._enabled = False
        self._sinks: List[Callable[[Span], None]] = []
        self.max_spans = int(max_spans)

    # -- control ---------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def start(self):
        self._enabled = True

    def stop(self):
        self._enabled = False

    def active(self) -> bool:
        """True when spans are being captured (ring enabled or any sink
        attached) — the cheap gate hot paths check before building span
        arguments."""
        return self._enabled or bool(self._sinks)

    def add_sink(self, sink: Callable[[Span], None]):
        """Attach a per-span callback (spool writer, flight recorder).
        Called OUTSIDE the tracer lock; exceptions are swallowed — a
        broken sink must not take down the traced code."""
        with self._lock:
            if sink not in self._sinks:
                self._sinks.append(sink)

    def remove_sink(self, sink: Callable[[Span], None]):
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    def reset(self):
        with self._lock:
            self._events.clear()
            self._spans.clear()
            self._dropped = 0
            self._dropped_warned = False

    # -- recording -------------------------------------------------------
    def record(self, name: str, start_s: float, end_s: float,
               tid: Optional[int] = None, args: Optional[dict] = None,
               trace=None):
        """Record one finished span: aggregates always, the span record
        while enabled (ring) or sinks are attached (spool / flight
        recorder). ``trace`` is an optional
        ``trace_context.TraceContext`` giving the span its distributed
        identity. Safe from any thread."""
        dt = end_s - start_s
        sp = None
        sinks = ()
        dropped = first_drop = False
        with self._lock:
            e = self._events.get(name)
            if e is None:
                e = self._events[name] = _EventStat()
            e.calls += 1
            e.total += dt
            if dt < e.min:
                e.min = dt
            if dt > e.max:
                e.max = dt
            if self._enabled or self._sinks:
                sp = Span(
                    name, start_s, end_s,
                    tid if tid is not None else threading.get_ident(),
                    args,
                    trace.trace_id if trace is not None else None,
                    trace.span_id if trace is not None else None,
                    trace.parent_id if trace is not None else None)
                if self._enabled:
                    if len(self._spans) < self.max_spans:
                        self._spans.append(sp)
                    else:
                        self._dropped += 1
                        dropped = True
                        if not self._dropped_warned:
                            self._dropped_warned = first_drop = True
                sinks = tuple(self._sinks)
        # metric/warning/sinks outside the lock: none of them may block
        # (or re-enter) the recording path
        if dropped:
            DROPPED_SPANS.inc()
            if first_drop:
                warnings.warn(
                    f"tracer ring full ({self.max_spans} spans): further "
                    f"spans are dropped and counted in "
                    f"paddle_trace_dropped_spans_total", RuntimeWarning,
                    stacklevel=3)
        if sp is not None:
            for cb in sinks:
                try:
                    cb(sp)
                except Exception:
                    pass

    @contextlib.contextmanager
    def span(self, name: str, **args):
        """``with tracer.span("step"): ...`` — RAII span + aggregate.

        While capturing, the span auto-parents under the thread's
        current :class:`TraceContext` (and exposes itself as current for
        the block), so spans nest causally across process boundaries
        once an RPC layer activated the caller's context."""
        ctx = token = tc = None
        if self._enabled or self._sinks:
            from paddle_tpu.observability import trace_context as tc
            parent = tc.current()
            if parent is not None:
                ctx = parent.child()
                token = tc.attach(ctx)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            if token is not None:
                tc.detach(token)
            self.record(name, t0, t1, args=args or None, trace=ctx)

    def trace(self, name_or_fn=None):
        """Decorator form: ``@tracer.trace`` or ``@tracer.trace("name")``."""
        def deco(fn, name=None):
            label = name or f"{fn.__module__}.{fn.__qualname__}"

            @functools.wraps(fn)
            def wrapper(*a, **kw):
                with self.span(label):
                    return fn(*a, **kw)
            return wrapper

        if callable(name_or_fn):
            return deco(name_or_fn)
        return lambda fn: deco(fn, name_or_fn)

    # -- reading ---------------------------------------------------------
    def event_stats(self) -> Dict[str, dict]:
        with self._lock:
            return {n: {"calls": e.calls, "total": e.total,
                        "min": e.min, "max": e.max}
                    for n, e in self._events.items()}

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    @property
    def dropped_spans(self) -> int:
        with self._lock:
            return self._dropped

    # -- export ----------------------------------------------------------
    def to_chrome_trace(self, pid: int = 0) -> dict:
        """chrome://tracing / Perfetto JSON ('X' complete events, µs)."""
        events = []
        for s in self.spans():
            ev = {"name": s.name, "cat": "host", "ph": "X",
                  "ts": s.start_s * 1e6, "dur": s.duration_s * 1e6,
                  "pid": pid, "tid": s.tid}
            if s.args:
                ev["args"] = s.args
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str, pid: int = 0):
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(pid), f)


_DEFAULT = Tracer()
_autostart_done = False


def default_tracer() -> Tracer:
    return _DEFAULT


def _autostart_from_flags():
    """One-shot: attach the span spool / flight recorder when their
    flags are set (how a ``tools/launch.py`` child — which cannot call
    our Python API before main — turns capture on via env)."""
    global _autostart_done
    _autostart_done = True
    from paddle_tpu.observability import flight_recorder, spool
    spool.maybe_start_from_flags()
    flight_recorder.maybe_start_from_flags()


def active() -> bool:
    """One cheap check for hot paths: is ANY span capture on (tracer
    ring, spool, flight recorder)? First call consults the spool/flight
    flags so flag-configured processes start capturing lazily."""
    if not _autostart_done:
        _autostart_from_flags()
    return _DEFAULT._enabled or bool(_DEFAULT._sinks)


def add_sink(sink: Callable[[Span], None]) -> None:
    _DEFAULT.add_sink(sink)


def remove_sink(sink: Callable[[Span], None]) -> None:
    _DEFAULT.remove_sink(sink)


def span(name: str, **args):
    """Module-level convenience on the default tracer:
    ``with tracing.span("master.get_task"): ...``"""
    return _DEFAULT.span(name, **args)


def trace(name_or_fn=None):
    """``@tracing.trace`` / ``@tracing.trace("name")`` on the default
    tracer."""
    return _DEFAULT.trace(name_or_fn)
