"""Crash flight recorder: a black box for processes that die.

The ROADMAP's chaos story (exactly-once chunk accounting "via the
observability counters", process-level kill tests) needs telemetry that
*survives the kill*. This module keeps a bounded per-process ring of
recent events — finished spans (tracer sink), metric counter deltas,
fault-site fires (``utils.faults`` observer), explicit notes (breaker
opens) — and persists it two ways:

- **dump**: ``<dir>/<role>.<pid>.dump.json``, written atomically
  (tmp + rename) on unhandled exception, SIGTERM, or a fault-injection
  fire — a single readable artifact: the ring, a metrics snapshot, and
  the fault-site counters at death;
- **black box**: ``<dir>/<role>.<pid>.blackbox.jsonl``, every event
  appended and ``flush()``ed immediately. SIGKILL gives no hook, but
  flushed lines are in the kernel page cache and survive process death
  — the chaos test reconstructs what a SIGKILLed server was doing from
  the last lines.

Enable with ``FLAGS_flight_recorder_dir`` (capacity via
``FLAGS_flight_recorder_capacity``) or :func:`ensure_started`. The
SIGTERM handler dumps, restores the previous disposition, and re-kills
itself so the exit status stays honest. Hot-path cost when disabled:
zero (nothing is registered anywhere).
"""

from __future__ import annotations

import collections
import json
import os
import signal
import sys
import threading
import time
from typing import Optional

from paddle_tpu.observability.spool import default_role, wall_us


class FlightRecorder:
    """Bounded event ring + always-flushed black box + atomic dump."""

    # sample metric deltas into the ring every N recorded events, so a
    # dump carries counter movement without per-event snapshot cost
    METRICS_EVERY = 32

    def __init__(self, directory: str, role: Optional[str] = None,
                 capacity: int = 256):
        self.role = role or default_role()
        self.pid = os.getpid()
        os.makedirs(directory, exist_ok=True)
        stem = os.path.join(directory, f"{self.role}.{self.pid}")
        self.dump_path = stem + ".dump.json"
        self.blackbox_path = stem + ".blackbox.jsonl"
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=max(1, int(capacity)))
        self._bb = open(self.blackbox_path, "a", encoding="utf-8")
        self._since_metrics = 0
        self._last_counters = self._counter_values()
        self._dumped_reasons = set()
        self._event("start", argv=sys.argv[:4])

    # -- event intake ----------------------------------------------------
    def _event(self, kind: str, **fields):
        rec = {"t": wall_us(time.perf_counter()), "kind": kind}
        rec.update(fields)
        line = json.dumps(rec, separators=(",", ":"), default=str)
        with self._lock:
            if self._bb.closed:
                return
            self._ring.append(rec)
            self._bb.write(line + "\n")
            self._bb.flush()     # must survive SIGKILL
            self._since_metrics += 1
            sample = self._since_metrics >= self.METRICS_EVERY
            if sample:
                self._since_metrics = 0
        if sample and kind != "metrics":
            self._sample_metrics()

    def __call__(self, span) -> None:
        """Tracer sink: every finished span becomes a ring event."""
        f = {"name": span.name, "ts": wall_us(span.start_s),
             "dur_us": max(0.0, span.end_s - span.start_s) * 1e6}
        if span.trace_id:
            f["trace_id"] = span.trace_id
            f["span_id"] = span.span_id
        if span.args:
            f["args"] = span.args
        self._event("span", **f)

    def on_fault(self, site: str, mode: str) -> None:
        """utils.faults observer — recorded BEFORE the fault's effect,
        so the black box names the kill point even when the fault (or a
        SIGKILL riding on it) ends the process. Also dumps: an armed
        fault site is a death sentence often enough that the last dump
        before the effect is the one worth having (re-dumps overwrite,
        so the newest fire wins)."""
        self._event("fault", site=site, mode=mode)
        try:
            self.dump("fault")
        except Exception:
            pass

    def note(self, what: str, **fields) -> None:
        """Explicit breadcrumb (breaker opened, lease taken...)."""
        self._event("note", what=what, **fields)

    def _counter_values(self) -> dict:
        from paddle_tpu.observability import metrics
        out = {}
        for fam in metrics.default_registry().families():
            if fam.kind not in ("counter", "gauge"):
                continue
            for values, child in fam.children().items():
                key = fam.name + (":" + ",".join(values) if values else "")
                out[key] = child.value
        return out

    def _sample_metrics(self):
        now = self._counter_values()
        delta = {k: v - self._last_counters.get(k, 0.0)
                 for k, v in now.items()
                 if v != self._last_counters.get(k, 0.0)}
        self._last_counters = now
        if delta:
            self._event("metrics", delta=delta)

    # -- dumping ---------------------------------------------------------
    def dump(self, reason: str, once_per_reason: bool = False) -> str:
        """Write the dump atomically; returns its path. Re-dumping
        overwrites (later = closer to death = better)."""
        with self._lock:
            if once_per_reason and reason in self._dumped_reasons:
                return self.dump_path
            self._dumped_reasons.add(reason)
            ring = list(self._ring)
        from paddle_tpu.observability import metrics
        from paddle_tpu.utils import faults
        doc = {"role": self.role, "pid": self.pid, "reason": reason,
               "wall_us": wall_us(time.perf_counter()),
               "events": ring,
               "metrics": metrics.default_registry().snapshot(),
               "faults": faults.stats()}
        try:
            # every crash artifact answers "what was resident": census
            # families + top buffers + watermark history
            from paddle_tpu.observability import memory
            doc["memory"] = memory.dump_section()
        except Exception:
            pass
        tmp = self.dump_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.dump_path)
        return self.dump_path

    def close(self):
        with self._lock:
            if not self._bb.closed:
                self._bb.close()


_REC: Optional[FlightRecorder] = None
_lock = threading.Lock()
_prev_excepthook = None
_prev_sigterm = None


def _excepthook(exc_type, exc, tb):
    rec = _REC
    if rec is not None:
        try:
            rec._event("exception", exc_type=exc_type.__name__,
                       message=str(exc)[:500])
            rec.dump("exception")
        except Exception:
            pass
    (_prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)


def _sigterm(signum, frame):
    rec = _REC
    if rec is not None:
        try:
            rec._event("sigterm")
            rec.dump("sigterm")
        except Exception:
            pass
    # restore the previous disposition and re-kill: the process must
    # still die *of SIGTERM* (wait status, not a clean exit code)
    signal.signal(signal.SIGTERM,
                  _prev_sigterm if callable(_prev_sigterm)
                  else signal.SIG_DFL)
    os.kill(os.getpid(), signal.SIGTERM)


def ensure_started(directory: Optional[str] = None,
                   role: Optional[str] = None,
                   capacity: Optional[int] = None
                   ) -> Optional[FlightRecorder]:
    """Start (once) the process flight recorder: open the black box,
    attach the tracer sink + fault observer, install the excepthook and
    (main thread only) the SIGTERM dumper. Falls back to
    FLAGS_flight_recorder_dir / FLAGS_flight_recorder_capacity."""
    global _REC, _prev_excepthook, _prev_sigterm
    with _lock:
        if _REC is not None:
            return _REC
        from paddle_tpu import flags
        if directory is None:
            directory = flags.get("flight_recorder_dir")
        if not directory:
            return None
        if capacity is None:
            capacity = flags.get("flight_recorder_capacity")
        _REC = FlightRecorder(directory, role, capacity)
        _prev_excepthook = sys.excepthook
        sys.excepthook = _excepthook
        try:
            _prev_sigterm = signal.signal(signal.SIGTERM, _sigterm)
        except ValueError:       # not the main thread
            _prev_sigterm = None
    from paddle_tpu.observability import tracing
    from paddle_tpu.utils import faults
    tracing.add_sink(_REC)
    faults.add_observer(_REC.on_fault)
    return _REC


def maybe_start_from_flags() -> None:
    """tracing.active()'s one-time autostart hook."""
    ensure_started()


def current() -> Optional[FlightRecorder]:
    return _REC


def note(what: str, **fields) -> None:
    """Breadcrumb into the recorder when one is running (else no-op —
    one attribute read on the disabled path)."""
    rec = _REC
    if rec is not None:
        rec.note(what, **fields)


def dump(reason: str) -> Optional[str]:
    rec = _REC
    return rec.dump(reason) if rec is not None else None


def shutdown() -> None:
    """Detach hooks and close (tests)."""
    global _REC
    with _lock:
        rec, _REC = _REC, None
    if rec is None:
        return
    from paddle_tpu.observability import tracing
    from paddle_tpu.utils import faults
    tracing.remove_sink(rec)
    faults.remove_observer(rec.on_fault)
    if sys.excepthook is _excepthook:
        sys.excepthook = _prev_excepthook or sys.__excepthook__
    try:
        if signal.getsignal(signal.SIGTERM) is _sigterm:
            signal.signal(signal.SIGTERM,
                          _prev_sigterm if _prev_sigterm is not None
                          else signal.SIG_DFL)
    except ValueError:
        pass
    rec.close()
