"""HBM memory observability: compiled breakdowns, live-buffer census,
donation audit, OOM forensics.

`contrib/memory_usage.py` is a static per-var estimator, and fusion-era
XLA reuses buffers aggressively enough that static sums are only a
band — the *compiled* numbers are the truth. XLA exposes them per
executable (``lower(...).compile().memory_analysis()``, the memory twin
of the cost-analysis FLOPs the MFU gauge rides), so this module makes
memory a first-class telemetry layer:

- **compiled breakdown** — argument/output/temp/alias/generated-code
  bytes per jit signature, cached exactly like ``analyzed_flops``
  (:func:`compiled_memory`), exported as
  ``paddle_hbm_compiled_bytes{program,kind}``;
- **live-buffer census** — walk the noted scopes and classify every
  device-resident array by family (param, optimizer moment, KV cache,
  embed hot-rows cache, activation, other) with per-family gauges and a
  process watermark (:func:`census` / :func:`record_census`);
- **donation audit** — parse the compiled HLO's
  ``input_output_alias`` header and verify every mutated state var the
  runtime donates actually aliases (:func:`donation_audit`), counting
  ``paddle_donation_violations_total{program}``;
- **OOM forensics** — :func:`oom_dump` writes an atomic
  ``<role>.<pid>.memdump.json`` through the flight-recorder directory:
  top-N live buffers with names/families, the failing program's
  compiled breakdown, and the watermark history.

Off by default: ``FLAGS_memory_stats`` (or :func:`enable`) gates
everything, and the executor pays exactly ONE flag lookup per dispatch
when it is off — the same contract as the step sampler. CLI probes:
``tools/mem_probe.py`` (zoo sweep → MEM_r01.json) and
``tools/proglint.py --memory`` (donation-audit CI gate).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from paddle_tpu.observability import metrics
from paddle_tpu.observability.spool import default_role, wall_us

HBM_COMPILED = metrics.gauge(
    "paddle_hbm_compiled_bytes", "Compiled-executable memory breakdown "
    "from XLA memory_analysis(), per program and kind (argument/output/"
    "temp/alias/generated_code/peak; peak = argument + output - alias + "
    "temp + generated_code)", ("program", "kind"))
HBM_LIVE = metrics.gauge(
    "paddle_hbm_live_bytes", "Live device-resident bytes by buffer "
    "family from the scope census (param, optimizer_moment, kv_cache, "
    "embed_cache, activation, other)", ("family",))
HBM_WATERMARK = metrics.gauge(
    "paddle_hbm_watermark_bytes", "Process high-watermark of total "
    "census bytes since start")
HBM_KV_POOL = metrics.gauge(
    "paddle_hbm_kv_pool_bytes", "Exact KV-cache pool bytes resident for "
    "a serving model (sum of its *_cache_/*_slot_/*_page_ k/v arrays "
    "incl. codec scale planes); the paged layout's page economy is the "
    "paddle_kv_pages_* family (serving/metrics.py)", ("model",))
DONATION_VIOLATIONS = metrics.counter(
    "paddle_donation_violations_total", "State vars the runtime donated "
    "that the compiled executable did NOT alias in input_output_alias — "
    "each one is a silently-doubled buffer", ("program",))
OOM_EVENTS = metrics.counter(
    "paddle_oom_events_total", "Device OOMs (RESOURCE_EXHAUSTED at "
    "dispatch) caught by the executor's forensics path", ("program",))

# every census family renders even at 0, so a scrape shows the catalog
FAMILIES = ("param", "optimizer_moment", "kv_cache", "embed_cache",
            "activation", "other")

_force = False


def enable():
    """Switch memory telemetry on for this process (flag-free path)."""
    global _force
    _force = True


def disable():
    global _force
    _force = False


def enabled() -> bool:
    """One module bool + one flag lookup — the executor's entire
    per-dispatch cost when memory telemetry is off."""
    if _force:
        return True
    from paddle_tpu import flags
    return bool(flags.get("memory_stats"))


# -- compiled memory breakdown (cached per jit signature) -----------------

_MEM_CACHE: Dict[Any, Optional[dict]] = {}
_MEM_LOCK = threading.Lock()
_MEM_CACHE_MAX = 4096      # FIFO eviction, same bound/rationale as the
# compiled-cost cache (per-shape serving compiles must not grow forever)


def memory_cache_peek(key: Any):
    """(hit, value) — lets CompiledBlock.analyzed_memory skip argument
    gathering once a signature is resolved (per-dispatch telemetry)."""
    with _MEM_LOCK:
        if key in _MEM_CACHE:
            return True, _MEM_CACHE[key]
    return False, None


def _cache_put(key: Any, value):
    with _MEM_LOCK:
        while len(_MEM_CACHE) >= _MEM_CACHE_MAX:
            _MEM_CACHE.pop(next(iter(_MEM_CACHE)))
        _MEM_CACHE[key] = value


def compiled_memory(jit_fn, *args, cache_key: Any = None
                    ) -> Optional[dict]:
    """Memory breakdown of ``jit_fn`` specialized to ``args`` from XLA's
    ``memory_analysis()``: {argument,output,temp,alias,generated_code,
    peak}_bytes. The lower/compile round trip runs once per ``cache_key``
    (jax's executable caches make it cheap after a real dispatch).
    None when the backend reports nothing."""
    key = cache_key if cache_key is not None else id(jit_fn)
    hit, val = memory_cache_peek(key)
    if hit:
        return val
    out: Optional[dict] = None
    try:
        ma = jit_fn.lower(*args).compile().memory_analysis()
        if isinstance(ma, (list, tuple)):   # older jax: one per device
            ma = ma[0] if ma else None
        if ma is not None:
            out = {
                "argument_bytes": int(
                    getattr(ma, "argument_size_in_bytes", 0) or 0),
                "output_bytes": int(
                    getattr(ma, "output_size_in_bytes", 0) or 0),
                "temp_bytes": int(
                    getattr(ma, "temp_size_in_bytes", 0) or 0),
                "alias_bytes": int(
                    getattr(ma, "alias_size_in_bytes", 0) or 0),
                "generated_code_bytes": int(
                    getattr(ma, "generated_code_size_in_bytes", 0) or 0),
            }
            # donated buffers alias: they are counted in argument_bytes
            # AND output_bytes but occupy HBM once
            out["peak_bytes"] = (
                out["argument_bytes"] + out["output_bytes"]
                - out["alias_bytes"] + out["temp_bytes"]
                + out["generated_code_bytes"])
    except Exception:
        out = None
    _cache_put(key, out)
    return out


def sharded_state_bytes(block, shardings: Dict[str, Any]) -> int:
    """Analytic PER-DEVICE bytes of a sharded state/const set: for each
    var, total bytes divided by the product of the mesh-axis sizes its
    PartitionSpec names. This is the cheap pre-compile estimator the
    HBM-budget ladder (core/lowering.py CompiledBlock._plan_under_budget)
    ranks plans with — params + optimizer moments dominate a training
    step's footprint; activations/temps are confirmed post-hoc by
    :func:`compiled_memory`. Vars with dynamic dims are skipped."""
    import numpy as np
    total = 0
    for name, sh in shardings.items():
        if not block.has_var(name):
            continue
        v = block.var(name)
        shape = v.shape or ()
        if not shape or any(d is None or d <= 0 for d in shape):
            continue
        try:
            itemsize = np.dtype(v.dtype or "float32").itemsize
        except TypeError:
            itemsize = 4
        nbytes = int(np.prod(shape)) * itemsize
        mesh = getattr(sh, "mesh", None)
        spec = tuple(getattr(sh, "spec", ()) or ())
        factor = 1
        for entry in spec:
            axes = entry if isinstance(entry, tuple) else (entry,)
            for ax in axes:
                if ax is not None and mesh is not None \
                        and ax in mesh.shape:
                    factor *= int(mesh.shape[ax])
        total += nbytes // max(factor, 1)
    return total


def set_compiled_gauges(program: str, breakdown: Optional[dict]):
    if not breakdown:
        return
    for k, v in breakdown.items():
        kind = k[:-len("_bytes")] if k.endswith("_bytes") else k
        HBM_COMPILED.labels(program=program, kind=kind).set(v)


# -- donation audit -------------------------------------------------------

# ENTRY parameter lines carry jax's pytree arg paths as op_name
# metadata — fn(state, consts, feeds, step_seed) names them
# "state['w']" / "feeds['x']". Inner fusion-computation parameters have
# unrelated or absent op_name, so the (state|consts|feeds)[ anchor plus
# the ENTRY-region scan below keeps them out.
_HLO_PARAM_RE = re.compile(
    r"parameter\((\d+)\)[^\n]*?op_name=\"(state|consts|feeds)"
    r"\[\\?['\"]([^'\"\\\]]+)")


def parse_hlo_aliasing(hlo_text: str
                       ) -> Tuple[Dict[Tuple[str, str], int], set]:
    """({(tree, var_name): entry_param_number}, {aliased_param_numbers})
    from compiled HLO text. The alias header looks like
    ``input_output_alias={ {1}: (0, {}, may-alias), ... }`` — output
    tuple index → (parameter number, index path)."""
    aliased = set()
    i = hlo_text.find("input_output_alias={")
    if i >= 0:
        j = i + len("input_output_alias={")
        depth, k = 1, j
        while k < len(hlo_text) and depth:
            c = hlo_text[k]
            if c == "{":
                depth += 1
            elif c == "}":
                depth -= 1
            k += 1
        body = hlo_text[j:k - 1]
        aliased = {int(g) for g in re.findall(r"\((\d+),\s*\{", body)}
    params: Dict[Tuple[str, str], int] = {}
    in_entry = False
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY"):
            in_entry = True
            continue
        if in_entry:
            if line.startswith("}"):
                break
            m = _HLO_PARAM_RE.search(line)
            if m:
                params[(m.group(2), m.group(3))] = int(m.group(1))
    return params, aliased


def donation_audit(lower_text: Callable[[], str],
                   state_names: Iterable[str], program: str = "",
                   cache_key: Any = None) -> dict:
    """Verify the donated state vars actually alias in the compiled
    executable. ``lower_text`` produces the HLO text lazily (the
    lower/compile trip only runs on a cache miss). A state var jit
    pruned entirely (keep_unused=False drops unused args) has no ENTRY
    parameter and is *skipped*, not flagged. Returns {program, expected,
    aliased, violations, skipped} and counts
    paddle_donation_violations_total once per cache fill."""
    if cache_key is not None:
        hit, val = memory_cache_peek(cache_key)
        if hit:
            return val
    names = list(state_names)
    try:
        params, aliased_nums = parse_hlo_aliasing(lower_text())
    except Exception as e:
        result = {"program": program, "error": str(e)[:200],
                  "expected": names, "aliased": [], "violations": [],
                  "skipped": names}
        if cache_key is not None:
            _cache_put(cache_key, result)
        return result
    ok, violations, skipped = [], [], []
    for n in names:
        pnum = params.get(("state", n))
        if pnum is None:
            skipped.append(n)
        elif pnum in aliased_nums:
            ok.append(n)
        else:
            violations.append(n)
    result = {"program": program, "expected": names, "aliased": ok,
              "violations": violations, "skipped": skipped}
    if violations:
        DONATION_VIOLATIONS.labels(
            program=program or "unknown").inc(len(violations))
    if cache_key is not None:
        _cache_put(cache_key, result)
    return result


# -- live-buffer census ---------------------------------------------------

_SCOPES: "weakref.WeakSet" = weakref.WeakSet()
_FAMILY_OVERRIDES: Dict[str, str] = {}
_PARAM_NAMES: set = set()
_WATERMARK_HIST: deque = deque(maxlen=256)
_watermark_peak = 0
_CENSUS_LOCK = threading.Lock()

# matches the contiguous caches (_cache_k_0 / _slot_v_1), the paged
# pools (_page_k_0), and the paged codec's scale planes (_page_ks_0 /
# _page_vs_0) — all kv_cache family
_KV_RE = re.compile(r"_(cache|slot|page)_(k|v)s?_\d+$")
# optimizer accumulators are '<param>_<kind>_N' (fluid/optimizer.py
# _add_accumulator); the kinds below are every _add_accumulator call site
_ACC_RE = re.compile(
    r"_(velocity|moment1|moment2|beta1_pow_acc|beta2_pow_acc|moment|"
    r"inf_norm|avg_squared_grad|avg_squared_update|mean_square|momentum|"
    r"mean_grad|squared|linear)_\d+$")
_PARAM_NAME_RE = re.compile(r"\.(w|b)_\d+$")


def note_scope(scope):
    """Register a scope for the census walk (weakly held)."""
    _SCOPES.add(scope)


def register_buffer_family(name: str, family: str):
    """Pin a scope var name to a census family — the embed hot-rows
    cache registers its device arrays here (their names are the TABLE's,
    which would otherwise classify as a parameter)."""
    _FAMILY_OVERRIDES[name] = family


def note_params(names: Iterable[str]):
    """Teach the classifier which names are parameters (the executor
    feeds each compiled block's is_parameter vars through here)."""
    _PARAM_NAMES.update(names)


def classify(name: str) -> str:
    fam = _FAMILY_OVERRIDES.get(name)
    if fam:
        return fam
    if _KV_RE.search(name):
        return "kv_cache"
    if _ACC_RE.search(name):
        return "optimizer_moment"
    if name.endswith("@GRAD"):
        return "activation"
    if name in _PARAM_NAMES or _PARAM_NAME_RE.search(name):
        return "param"
    return "other"


def census(scopes=None) -> dict:
    """Walk scopes (noted ones by default) and classify every array:
    {families: {family: bytes}, total_bytes, buffers: [...desc, largest
    first]}. Arrays are deduped by identity — a var visible in a parent
    and child scope counts once."""
    if scopes is None:
        scopes = list(_SCOPES)
    seen = set()
    fams: Dict[str, int] = {}
    buffers: List[dict] = []
    for sc in scopes:
        if sc is None:
            continue
        it = getattr(sc, "iter_vars", None)
        items = it() if it is not None else getattr(sc, "_vars", {}).items()
        for name, v in items:
            nb = int(getattr(v, "nbytes", 0) or 0)
            if nb <= 0:
                continue
            key = id(v)
            if key in seen:
                continue
            seen.add(key)
            fam = classify(name)
            fams[fam] = fams.get(fam, 0) + nb
            buffers.append({
                "name": name, "family": fam, "bytes": nb,
                "shape": [int(d) for d in (getattr(v, "shape", ()) or ())],
                "dtype": str(getattr(v, "dtype", ""))})
    buffers.sort(key=lambda b: -b["bytes"])
    return {"families": fams,
            "total_bytes": sum(fams.values()),
            "buffers": buffers}


def record_census(scope=None) -> dict:
    """Take a census (noting ``scope`` first) and publish it: per-family
    gauges, the watermark gauge, and a history sample."""
    global _watermark_peak
    if scope is not None:
        note_scope(scope)
    cen = census()
    fams = cen["families"]
    for fam in set(FAMILIES) | set(fams):
        HBM_LIVE.labels(family=fam).set(fams.get(fam, 0))
    total = cen["total_bytes"]
    with _CENSUS_LOCK:
        _WATERMARK_HIST.append(
            {"t": wall_us(time.perf_counter()), "bytes": total})
        if total > _watermark_peak:
            _watermark_peak = total
    HBM_WATERMARK.set(_watermark_peak)
    return cen


def watermark() -> int:
    return _watermark_peak


def kv_pool_bytes(scope, model: str = "") -> int:
    """Sum the KV-cache/slot-pool arrays resident in ``scope`` and set
    the exact-bytes gauge for ``model``. Serving engines call this after
    their pools exist (post-startup / post-first-prefill)."""
    total = 0
    it = getattr(scope, "iter_vars", None)
    items = it() if it is not None else getattr(scope, "_vars", {}).items()
    for name, v in items:
        if _KV_RE.search(name) or _FAMILY_OVERRIDES.get(name) == "kv_cache":
            total += int(getattr(v, "nbytes", 0) or 0)
    if model:
        HBM_KV_POOL.labels(model=model).set(total)
    return total


def dump_section() -> dict:
    """The ``memory`` block flight-recorder dumps embed: census
    families + top buffers + watermark history."""
    cen = census()
    with _CENSUS_LOCK:
        hist = list(_WATERMARK_HIST)
    return {"families": cen["families"],
            "total_bytes": cen["total_bytes"],
            "top_buffers": cen["buffers"][:10],
            "watermark_bytes": _watermark_peak,
            "watermark_history": hist[-32:]}


def snapshot() -> dict:
    """The JSON document the /memory scrape route serves."""
    cen = census()
    with _CENSUS_LOCK:
        hist = list(_WATERMARK_HIST)
    return {"families": cen["families"],
            "total_bytes": cen["total_bytes"],
            "top_buffers": cen["buffers"][:20],
            "watermark_bytes": _watermark_peak,
            "watermark_history": hist}


# -- OOM forensics --------------------------------------------------------

def is_oom_error(e: BaseException) -> bool:
    """Device OOM (XLA RESOURCE_EXHAUSTED) or the host analogue the
    chaos harness injects (MemoryError)."""
    if isinstance(e, MemoryError):
        return True
    s = str(e)
    return "RESOURCE_EXHAUSTED" in s or "out of memory" in s.lower()


def oom_dump(cb, scope, exc, feeds=None, iterations: int = 1,
             stacked=False) -> Optional[str]:
    """Write ``<role>.<pid>.memdump.json`` (atomic: tmp + fsync +
    replace) into the flight-recorder directory: the failing program's
    compiled breakdown, top live buffers by bytes with families, and
    the watermark history. Gated on the flight recorder / its dir flag
    or :func:`enabled` — and NEVER raises (it runs inside the
    executor's except path; the original error must propagate)."""
    try:
        from paddle_tpu import flags
        from paddle_tpu.observability import flight_recorder
        rec = flight_recorder.current()
        dirpath = (os.path.dirname(rec.dump_path) if rec is not None
                   else (flags.get("flight_recorder_dir") or None))
        if dirpath is None and not enabled():
            return None
        program = getattr(cb, "obs_label", None) or "unknown"
        OOM_EVENTS.labels(program=program).inc()
        cen = census(list(_SCOPES) + ([scope] if scope is not None
                                      else []))
        breakdown = None
        try:
            # memory_analysis is compiler-side (allocates no device
            # buffers) and usually already cached from telemetry
            breakdown = cb.analyzed_memory(scope, feeds or {},
                                           iterations, stacked)
        except Exception:
            breakdown = None
        with _CENSUS_LOCK:
            hist = list(_WATERMARK_HIST)
        role = rec.role if rec is not None else default_role()
        doc = {"role": role, "pid": os.getpid(), "reason": "oom",
               "wall_us": wall_us(time.perf_counter()),
               "program": program, "error": str(exc)[:500],
               "exc_type": type(exc).__name__,
               "compiled": breakdown,
               "families": cen["families"],
               "total_bytes": cen["total_bytes"],
               "top_buffers": cen["buffers"][:20],
               "watermark_bytes": _watermark_peak,
               "watermark_history": hist}
        path = None
        if dirpath:
            os.makedirs(dirpath, exist_ok=True)
            path = os.path.join(dirpath,
                                f"{role}.{os.getpid()}.memdump.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        flight_recorder.note("oom", program=program,
                             total_bytes=cen["total_bytes"],
                             memdump=path or "")
        if rec is not None:
            rec.dump("oom")
        return path
    except Exception:
        return None


def _reset_for_tests():
    """Test isolation: clear registries, caches, and watermark state."""
    global _watermark_peak, _force
    _force = False
    _FAMILY_OVERRIDES.clear()
    _PARAM_NAMES.clear()
    with _MEM_LOCK:
        _MEM_CACHE.clear()
    with _CENSUS_LOCK:
        _WATERMARK_HIST.clear()
        _watermark_peak = 0
    for sc in list(_SCOPES):
        _SCOPES.discard(sc)
