"""Telemetry exporters: background file dumper + Prometheus scrape
endpoint.

- :class:`MetricsDumper` — a daemon thread that, every
  ``FLAGS_metrics_dump_interval`` seconds, appends the step records the
  runtime produced since the last tick to ``<dump_path>/steps.jsonl``
  (one JSON object per dispatch: step, step_time_s, steps/s,
  examples/s, tokens/s, mfu) and atomically rewrites
  ``<dump_path>/metrics.prom`` with the full registry in Prometheus
  text format. ``stop()``/``flush()`` force a final write, and an
  atexit hook flushes on interpreter exit — a short training run never
  loses its tail to the interval.
- :class:`MetricsServer` — an optional stdlib ``http.server`` scrape
  endpoint (``GET /metrics``) on ``FLAGS_metrics_port``. The server
  socket binds at construction (port 0 = ephemeral, read ``.port``
  back), so there is no pick-a-port-then-rebind TOCTOU window — same
  discipline as ``utils/net.bound_listener``.

:func:`ensure_started` is the one idempotent entry point the executor
pokes when observability flags are set; it also pre-imports every
instrumented module so the exported catalog is complete from the first
scrape (master-lease, pserver-retry, checkpoint-CRC counters render at
zero instead of popping into existence at their first event).
"""

from __future__ import annotations

import atexit
import json
import os
import threading
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from paddle_tpu.observability import metrics

STEP_LOG_NAME = "steps.jsonl"
PROM_NAME = "metrics.prom"

# step records offered by runtime.record_dispatch, drained by the dump
# thread; bounded so a run without a dumper (or a stalled disk) cannot
# grow memory — oldest records drop first
_STEP_QUEUE: deque = deque(maxlen=65536)
_lock = threading.Lock()
_dumper: Optional["MetricsDumper"] = None
_server: Optional["MetricsServer"] = None
_started_from_flags = False
_ready_probe = None


def set_ready_probe(fn) -> None:
    """Register the process's readiness callable for ``GET /readyz``
    (``None`` clears it). Distinct from ``/healthz`` the same way the
    replica wire protocol splits them (docs/serving.md): healthz says
    "this process serves HTTP", readyz says "send me traffic" — false
    during warmup and while draining. With no probe registered /readyz
    answers 200 like /healthz (a process with no warmup phase is ready
    the moment it serves). A probe that returns falsy OR raises answers
    503 — a broken probe must read as not-ready, never as ready."""
    global _ready_probe
    _ready_probe = fn


def offer_step_record(rec: dict):
    """Called by ``runtime.record_dispatch`` for every dispatch; cheap
    append (the dump thread serializes to disk). Dropped when no dumper
    exists — scrape-endpoint-only mode must not retain 65k records for
    a consumer that will never drain them."""
    if _dumper is not None:
        _STEP_QUEUE.append(rec)


class MetricsDumper:
    """Background JSONL-step-log + Prometheus-text-file writer."""

    def __init__(self, dump_dir: str, interval_s: float = 10.0,
                 registry: Optional[metrics.MetricsRegistry] = None):
        self.dump_dir = dump_dir
        self.interval_s = max(float(interval_s), 0.05)
        self.registry = registry or metrics.default_registry()
        os.makedirs(dump_dir, exist_ok=True)
        self._stop = threading.Event()
        self._wlock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="paddle-metrics-dump")
        self._thread.start()

    @property
    def step_log_path(self) -> str:
        return os.path.join(self.dump_dir, STEP_LOG_NAME)

    @property
    def prom_path(self) -> str:
        return os.path.join(self.dump_dir, PROM_NAME)

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.flush()
            except OSError:
                pass          # disk trouble must not kill the thread

    def flush(self):
        """Drain pending step records to the JSONL log and rewrite the
        Prometheus snapshot (atomic tmp+rename, so a scraper of the
        file never reads a torn snapshot). A failed write re-queues the
        drained records — a transient disk error costs a delay, not an
        interval of telemetry."""
        with self._wlock:
            lines = []
            while True:
                try:
                    lines.append(_STEP_QUEUE.popleft())
                except IndexError:
                    break
            try:
                if lines:
                    # one buffered write: a failure requeues the whole
                    # batch (at-least-once — a duplicate line is only
                    # possible if the OS partially persisted the single
                    # write, which beats silently losing the interval)
                    buf = "".join(json.dumps(rec) + "\n" for rec in lines)
                    with open(self.step_log_path, "a") as f:
                        f.write(buf)
                    lines = []
                tmp = self.prom_path + ".tmp"
                with open(tmp, "w") as f:
                    f.write(self.registry.render_prometheus())
                os.replace(tmp, self.prom_path)
            finally:
                for rec in reversed(lines):   # failed write: requeue,
                    # without evicting newer records from a full deque
                    if len(_STEP_QUEUE) >= (_STEP_QUEUE.maxlen or 0):
                        break
                    _STEP_QUEUE.appendleft(rec)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)
        try:
            self.flush()
        except OSError:
            pass


class _ScrapeHandler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 - http.server API
        route = self.path.split("?")[0]
        if route == "/healthz":
            # liveness probe for process-launch tests / orchestrators:
            # no registry render, just "this process serves HTTP"
            body = b"ok\n"
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if route == "/readyz":
            probe = _ready_probe
            try:
                ready = True if probe is None else bool(probe())
            except Exception:
                ready = False
            body = b"ready\n" if ready else b"not ready\n"
            self.send_response(200 if ready else 503)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if route == "/memory":
            # on-demand HBM snapshot (observability.memory): census
            # families, top buffers, watermark history — JSON, so an
            # operator can jq it without a Prometheus stack
            try:
                from paddle_tpu.observability import memory
                body = json.dumps(memory.snapshot(), default=str,
                                  sort_keys=True).encode()
            except Exception as e:
                self.send_error(500, str(e))
                return
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if route not in ("/metrics", "/"):
            self.send_error(404)
            return
        body = self.server.registry.render_prometheus().encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # quiet: no per-scrape stderr spam
        pass


class MetricsServer:
    """Prometheus scrape endpoint on a socket bound AT CONSTRUCTION
    (port 0 picks an ephemeral port; read ``.port`` back) — no TOCTOU
    window between choosing the port and serving on it."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: Optional[metrics.MetricsRegistry] = None):
        self._httpd = ThreadingHTTPServer((host, port), _ScrapeHandler)
        self._httpd.daemon_threads = True
        # __lint_suppress__: ccy-unlocked-shared-write -- writes to the just-constructed HTTPServer before its serve thread starts (the lint matches .registry to MetricsDumper by attr name)
        self._httpd.registry = (registry  # type: ignore[attr-defined]
                                or metrics.default_registry())
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1}, daemon=True,
            name="paddle-metrics-http")
        self._thread.start()

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def _preregister_catalog():
    """Import every instrumented module so its metric families exist in
    the registry before the first snapshot — the operator's scrape shows
    the full catalog at zero, and the acceptance contract (master-lease
    / pserver-retry / checkpoint-CRC counters present in the text
    snapshot of ANY observed run) holds without those paths firing."""
    import importlib
    for mod in ("paddle_tpu.observability.runtime",
                # HBM memory families (paddle_hbm_*, paddle_donation_*,
                # paddle_oom_*): compiled breakdowns, census gauges,
                # donation violations, OOM events
                "paddle_tpu.observability.memory",
                # the tracer's ring-overflow counter
                # (paddle_trace_dropped_spans_total) — silent span loss
                # is a lying timeline, so it's part of the catalog
                "paddle_tpu.observability.tracing",
                # SPMD families (paddle_spmd_*): mesh size and the
                # entry-reshard byte counter that witnesses
                # device-resident state (docs/performance.md)
                "paddle_tpu.observability.spmd",
                "paddle_tpu.distributed.resilience",
                "paddle_tpu.distributed.async_pserver",
                "paddle_tpu.data.master_service",
                "paddle_tpu.data.pipeline",
                "paddle_tpu.fluid.sharded_io",
                "paddle_tpu.fluid.io",
                # the model-server families (paddle_serving_*): request
                # latency/outcomes, queue depth, batch occupancy, the
                # zero-steady-state compile counter, and the predictor's
                # AOT-fallback counter — import-light (docs/serving.md)
                "paddle_tpu.serving.metrics",
                # sharded embedding tables: hot-rows cache hit/miss/
                # eviction/occupancy and per-shard wire bytes
                # (docs/performance.md 'Sharded embedding tables')
                "paddle_tpu.ops.embed_cache",
                "paddle_tpu.distributed.sharded_table"):
        try:
            importlib.import_module(mod)
        except Exception:     # a broken optional module must not kill
            pass              # telemetry for the rest
    try:
        # analyzer families (paddle_analysis_*) declare lazily per run;
        # force them into the catalog so a scrape shows them at zero
        from paddle_tpu.analysis import rules as _analysis_rules
        _analysis_rules.declare_metrics()
    except Exception:
        pass
    try:
        # cross-view program-contract checks (paddle_analysis_contract_
        # checks_total): each validate_geometry / verify_family run
        # counts here — zero on a scrape means the verifier never ran
        from paddle_tpu.analysis import contracts as _contracts
        _contracts.declare_metrics()
    except Exception:
        pass
    try:
        # runtime lock-order witness (paddle_lock_witness_violations_
        # total): the chaos suites assert this stays zero; a non-zero
        # scrape in prod is a latent-deadlock page
        from paddle_tpu.observability import lock_witness as _lock_witness
        _lock_witness.declare_metrics()
    except Exception:
        pass
    try:
        # pass-pipeline + autotune-cache families (paddle_pass_*,
        # paddle_autotune_*): applied/rewrites/duration per pass, cache
        # hit/miss per region kind, and the measurement counter whose
        # zero-ness IS the CI determinism contract
        from paddle_tpu import passes as _tpu_passes
        _tpu_passes.declare_metrics()
    except Exception:
        pass


def ensure_started() -> bool:
    """Idempotently start the exporters the flags ask for
    (FLAGS_metrics_dump_path / FLAGS_metrics_dump_interval /
    FLAGS_metrics_port). Called by the executor when observability is
    enabled; safe to call every step (one attribute check once running).
    Never raises — a misconfigured exporter (port in use, unwritable
    dump dir) warns once and latches off instead of failing every
    training step. With no exporter flag set nothing latches, so flags
    set later in the process are still honored. Returns True once
    anything is running."""
    global _dumper, _server, _started_from_flags
    if _dumper is not None or _server is not None:
        return True
    if _started_from_flags:       # a prior attempt failed: stay off
        return False              # (shutdown() un-latches)
    from paddle_tpu import flags
    dump_path = flags.get("metrics_dump_path")
    port = flags.get("metrics_port")
    if not dump_path and port < 0:
        # nothing requested: don't latch (flags set later are honored)
        # and don't take the lock — the enable()-without-flags path hits
        # this every dispatch and must stay two env lookups, no lock
        return False
    with _lock:
        if _dumper is not None or _server is not None:
            return True
        if _started_from_flags:
            return False
        _preregister_catalog()
        import warnings
        if dump_path:
            try:
                _dumper = MetricsDumper(
                    dump_path, flags.get("metrics_dump_interval"))
            except Exception as e:
                warnings.warn(f"metrics dump thread disabled: cannot "
                              f"start on {dump_path!r}: {e!r}")
        if port >= 0:
            try:
                _server = MetricsServer(port=port,
                                        host=flags.get("metrics_host"))
            except Exception as e:
                warnings.warn(f"metrics scrape endpoint disabled: "
                              f"cannot bind port {port}: {e!r}")
        _started_from_flags = True
        return _dumper is not None or _server is not None


def active_dumper() -> Optional[MetricsDumper]:
    return _dumper


def active_server() -> Optional[MetricsServer]:
    return _server


def flush():
    """Force the dump files current (tests; end-of-run hooks)."""
    if _dumper is not None:
        _dumper.flush()


def shutdown():
    """Stop the flag-started exporters and allow a later
    :func:`ensure_started` to re-read the flags (tests toggle the flags
    between runs)."""
    global _dumper, _server, _started_from_flags
    with _lock:
        if _dumper is not None:
            _dumper.stop()
            _dumper = None
        if _server is not None:
            _server.stop()
            _server = None
        _started_from_flags = False


@atexit.register
def _flush_at_exit():        # pragma: no cover - interpreter teardown
    try:
        flush()
    except Exception:
        pass
