"""SPMD execution telemetry (docs/performance.md "SPMD execution").

Two families witness the multi-chip execution contract:

- ``paddle_spmd_mesh_devices`` — devices of the mesh the most recently
  built CompiledBlock compiled over (0 until a sharded program builds);
- ``paddle_spmd_resharding_bytes_total{program}`` — bytes of dispatch
  inputs that arrived in a different layout than the program's
  NamedSharding and were resharded on entry by jit. The startup->
  training-layout move on the FIRST dispatch is expected here; a
  counter that keeps advancing means state is bouncing layouts every
  step — the device-resident state cache (core/lowering.py) is being
  defeated by external scope writes.

Import-light on purpose: the exporter catalog preregisters this module
so both families appear at zero in any scrape.
"""

from __future__ import annotations

from paddle_tpu.observability import metrics as _metrics

MESH_DEVICES = _metrics.gauge(
    "paddle_spmd_mesh_devices",
    "devices in the mesh of the most recently compiled sharded program")

RESHARD_BYTES = _metrics.counter(
    "paddle_spmd_resharding_bytes_total",
    "bytes of dispatch inputs resharded on entry because they arrived "
    "in a different layout than the program's NamedSharding, per "
    "program", ("program",))


def note_mesh(n_devices: int) -> None:
    MESH_DEVICES.set(int(n_devices))


def note_resharding(program: str, nbytes: int) -> None:
    RESHARD_BYTES.labels(program=program).inc(int(nbytes))
