"""Runtime lock-order witness: the dynamic twin of the concurrency lint.

The static pass (``analysis/concurrency.py`` ``ccy-lock-order-cycle``)
proves the lock-order graph it can SEE is acyclic; this module witnesses
the orders that actually happen at runtime — including orders assembled
across modules and call chains no AST pass can follow. Every
instrumented lock is an :class:`ObservedLock`; under ``FLAGS_lock_witness``
each acquisition records, per thread, the stack of locks already held
and adds held→acquiring edges to one global order graph. An edge that
closes a cycle is a **witnessed inversion**: two threads interleaving
those two call sites can deadlock, even if this run got lucky.

On a violation the witness

- increments ``paddle_lock_witness_violations_total``,
- notes the event in the flight recorder with BOTH stacks — the Python
  stack acquiring in the reversed order now, and the stack recorded
  when the forward edge was first witnessed — and triggers a dump
  (``FLAGS_flight_recorder_dir``), so a chaos run's crash artifact
  names the two call sites to reorder,
- keeps the record in :func:`violations` for in-process assertions
  (the chaos suites run with the witness on and assert zero).

The wrapper is always safe to construct: with the flag off, ``acquire``
costs one flag lookup over the bare ``threading.Lock``. Construct
instrumented locks via :func:`make_lock`::

    self._pool_lock = lock_witness.make_lock("Router._pool_lock")

Witness bookkeeping runs under its own plain (never-observed) lock and
never raises into the caller.
"""

from __future__ import annotations

import threading
import traceback
from typing import Dict, List, Optional, Tuple

from paddle_tpu import flags

# (held, acquiring) -> first-witness record: the stack + thread that
# established the order
_EDGES: Dict[Tuple[str, str], dict] = {}
_VIOLATIONS: List[dict] = []
_STATE_LOCK = threading.Lock()      # plain on purpose: guards the graph
_HELD = threading.local()           # .stack: per-thread held lock names


def declare_metrics():
    """Get-or-create the violation counter (also called from the
    exporters' catalog preregistration so a scrape shows it at zero)."""
    from paddle_tpu.observability import metrics as obs_metrics
    return obs_metrics.counter(
        "paddle_lock_witness_violations_total",
        "lock-order inversions witnessed at runtime by ObservedLock "
        "(FLAGS_lock_witness): an acquisition whose held->acquiring "
        "edge closes a cycle in the observed lock-order graph")


def _held_stack() -> List[str]:
    stack = getattr(_HELD, "stack", None)
    if stack is None:
        stack = _HELD.stack = []
    return stack


def _path_exists(src: str, dst: str) -> bool:
    """True when src reaches dst in the witnessed order graph
    (_STATE_LOCK held by the caller)."""
    stack, seen = [src], set()
    while stack:
        cur = stack.pop()
        if cur == dst:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        for (a, b) in _EDGES:
            if a == cur:
                stack.append(b)
    return False


def _record_violation(held: str, acquiring: str, prior: dict,
                      stack_now: str):
    rec = {"held": held, "acquiring": acquiring,
           "thread": threading.current_thread().name,
           "stack_now": stack_now,
           "prior_thread": prior.get("thread"),
           "prior_stack": prior.get("stack")}
    with _STATE_LOCK:
        _VIOLATIONS.append(rec)
    try:
        declare_metrics().inc()
    except Exception:
        pass
    try:
        from paddle_tpu.observability import flight_recorder
        flight_recorder.note(
            "lock_witness_violation", held=held, acquiring=acquiring,
            thread=rec["thread"], stack_now=stack_now,
            prior_thread=rec["prior_thread"],
            prior_stack=rec["prior_stack"])
        flight_recorder.dump("lock_witness")
    except Exception:
        pass


class ObservedLock:
    """A ``threading.Lock``/``RLock`` wrapper feeding the global
    lock-order witness when ``FLAGS_lock_witness`` is on. Supports the
    context-manager protocol plus ``acquire``/``release``/``locked``,
    so it drops in anywhere a plain lock object is stored."""

    def __init__(self, name: str, rlock: bool = False):
        self.name = str(name)
        self._inner = threading.RLock() if rlock else threading.Lock()

    def __repr__(self):
        return f"ObservedLock({self.name!r})"

    # -- witnessing -------------------------------------------------------
    def _witness(self, held: List[str]):
        try:
            acquiring = self.name
            if acquiring in held:
                return                       # reentrant / same-name class
            stack_now = None
            for h in reversed(held):
                edge = (h, acquiring)
                with _STATE_LOCK:
                    known = edge in _EDGES
                    # a cycle exists iff the new edge's head already
                    # reaches its tail through witnessed edges
                    cyclic = (not known
                              and _path_exists(acquiring, h))
                    if not known:
                        if stack_now is None:
                            stack_now = "".join(
                                traceback.format_stack(limit=16)[:-2])
                        _EDGES[edge] = {
                            "stack": stack_now,
                            "thread":
                                threading.current_thread().name}
                    prior = dict(_EDGES.get((acquiring, h)) or {})
                if cyclic:
                    if not prior:
                        # the reverse order was witnessed transitively;
                        # name the first edge of the return path we have
                        with _STATE_LOCK:
                            for (a, b), info in _EDGES.items():
                                if a == acquiring:
                                    prior = dict(info)
                                    break
                    _record_violation(h, acquiring, prior,
                                      stack_now or "".join(
                                          traceback.format_stack(
                                              limit=16)[:-2]))
        except Exception:
            pass                             # the witness never raises

    # -- lock protocol ----------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        witnessing = False
        try:
            witnessing = bool(flags.get("lock_witness"))
        except Exception:
            pass
        if witnessing:
            self._witness(_held_stack())
        got = self._inner.acquire(blocking, timeout)
        if got and witnessing:
            _held_stack().append(self.name)
        return got

    def release(self):
        self._inner.release()
        stack = getattr(_HELD, "stack", None)
        if stack and self.name in stack:
            # remove the most recent acquisition of this name
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] == self.name:
                    del stack[i]
                    break

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        locked_fn = getattr(self._inner, "locked", None)
        return locked_fn() if locked_fn is not None else False


def make_lock(name: str, rlock: bool = False) -> ObservedLock:
    """An instrumented lock for a known lock site. Cheap when
    FLAGS_lock_witness is off (one flag lookup per acquire)."""
    return ObservedLock(name, rlock=rlock)


def violations() -> List[dict]:
    """Witnessed inversions so far (each names both locks, both threads
    and both stacks). The chaos suites assert this stays empty."""
    with _STATE_LOCK:
        return list(_VIOLATIONS)


def edges() -> Dict[Tuple[str, str], dict]:
    """The witnessed lock-order graph (copy)."""
    with _STATE_LOCK:
        return {k: dict(v) for k, v in _EDGES.items()}


def reset():
    """Clear the witnessed graph and violation list (tests)."""
    with _STATE_LOCK:
        _EDGES.clear()
        _VIOLATIONS.clear()


# the witness's own metric family exists from first import, so the
# exporter catalog can preregister it by importing this module
try:
    declare_metrics()
except Exception:
    pass
