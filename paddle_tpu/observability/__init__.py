"""Runtime observability: metrics registry, structured tracing, step
telemetry, exporters.

The chaos-hardened control plane (retries, circuit breakers,
heartbeats, CRC-verified checkpoints — docs/robustness.md) is provable
in tests but was invisible in production. This package makes it
watchable:

- :mod:`~paddle_tpu.observability.metrics` — thread-safe registry of
  labeled Counter/Gauge/Histogram families, Prometheus-text + JSON
  rendering, one process-default registry;
- :mod:`~paddle_tpu.observability.tracing` — lock-protected,
  thread-id-aware span recorder (context manager / decorator) with
  chrome-trace/Perfetto export; ``fluid.profiler`` delegates here;
- :mod:`~paddle_tpu.observability.runtime` — per-compiled-step stats:
  step-time ring buffer → steps/s, examples/s, tokens/s gauges, and an
  MFU gauge from XLA's compiled-cost analysis (analytic-FLOPs
  fallback);
- :mod:`~paddle_tpu.observability.exporters` — background JSONL step
  log + Prometheus text file (``FLAGS_metrics_dump_path`` /
  ``FLAGS_metrics_dump_interval``) and an optional stdlib http scrape
  endpoint (``FLAGS_metrics_port``, with ``/healthz``);
- :mod:`~paddle_tpu.observability.trace_context` — W3C-traceparent
  style cross-process trace context (inject/extract on every JSON wire
  format) so spans parent correctly across processes;
- :mod:`~paddle_tpu.observability.spool` — crash-tolerant per-process
  span spool (``FLAGS_trace_spool_dir``), merged by
  ``tools/trace_collect.py`` into one Perfetto trace;
- :mod:`~paddle_tpu.observability.flight_recorder` — black-box ring of
  recent spans / metric deltas / fault fires, dumped on crash signals
  (``FLAGS_flight_recorder_dir``);
- :mod:`~paddle_tpu.observability.lock_witness` — runtime lock-order
  witness (``FLAGS_lock_witness``): ``ObservedLock`` validates the
  global lock DAG per acquisition, counting inversions and dumping
  both offending stacks through the flight recorder — the dynamic twin
  of the static ``ccy-lock-order-cycle`` lint.

Everything is off by default; with no observability flag set the hot
path pays one flag lookup per executor dispatch. Metric catalog and
label conventions: docs/observability.md.
"""

from __future__ import annotations

from paddle_tpu.observability import metrics  # noqa: F401
from paddle_tpu.observability import tracing  # noqa: F401
from paddle_tpu.observability import trace_context  # noqa: F401
from paddle_tpu.observability import runtime  # noqa: F401
from paddle_tpu.observability import exporters  # noqa: F401
from paddle_tpu.observability import spool  # noqa: F401
from paddle_tpu.observability import flight_recorder  # noqa: F401
from paddle_tpu.observability import lock_witness  # noqa: F401
from paddle_tpu.observability import memory  # noqa: F401
from paddle_tpu.observability.metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, counter, default_registry,
    gauge, histogram)
from paddle_tpu.observability.tracing import (  # noqa: F401
    Tracer, default_tracer, span, trace)
from paddle_tpu.observability.trace_context import (  # noqa: F401
    TraceContext, extract, inject, new_trace)

_force_enabled = False


def enable():
    """Programmatically switch step telemetry on for this process (the
    flag-free path tests and bench use)."""
    global _force_enabled
    _force_enabled = True


def disable():
    global _force_enabled
    _force_enabled = False


def enabled() -> bool:
    """True when step telemetry should be recorded: an observability
    flag is set (dump path / scrape port) or :func:`enable` was called.
    The executor checks this once per dispatch — with everything off
    the whole subsystem costs two flag lookups."""
    if _force_enabled:
        return True
    from paddle_tpu import flags
    return bool(flags.get("metrics_dump_path")) \
        or flags.get("metrics_port") >= 0
