"""Per-process span spool: append-only, crash-tolerant JSONL.

The tracer's in-memory ring dies with the process — useless exactly when
a chaos test SIGKILLs a server mid-request. The spool is the durable
half: a :class:`SpanSpool` attaches to the tracer as a sink and appends
every finished span to ``FLAGS_trace_spool_dir/<role>.<pid>.jsonl``,
one JSON object per line, ``flush()``ed per span — after a kill the file
is complete up to the last whole line (a torn final line is skipped by
the reader). ``tools/trace_collect.py`` merges all spools in a directory
into one Perfetto trace.

File layout (docs/observability.md "Distributed tracing"):
- line 1 is a ``{"k": "meta", ...}`` header naming the role, pid and the
  process's wall-clock anchor;
- every other line is ``{"k": "span", "name", "ts", "dur", "tid",
  "trace_id", "span_id", "parent_id", "args"}`` with ``ts``/``dur`` in
  wall-clock MICROSECONDS — spans are perf_counter-based in memory, so
  each process converts through one anchor captured at import
  (``wall = perf + _PERF_TO_WALL``) and cross-process timestamps land
  on a shared axis without clock negotiation.

Enable per process with ``FLAGS_trace_spool_dir`` (+ optional
``FLAGS_trace_role``) — how ``tools/launch.py`` children inherit
capture via env — or programmatically via :func:`ensure_started`.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Optional

# one wall↔perf anchor per process, captured as early as possible so
# every span this process ever spools converts identically
_PERF_TO_WALL = time.time() - time.perf_counter()


def wall_us(perf_s: float) -> float:
    """perf_counter seconds → wall-clock microseconds (shared axis)."""
    return (perf_s + _PERF_TO_WALL) * 1e6


def default_role() -> str:
    """FLAGS_trace_role, else the script basename, else 'proc'."""
    from paddle_tpu import flags
    role = flags.get("trace_role")
    if role:
        return role
    argv0 = os.path.basename(sys.argv[0] or "")
    if argv0.endswith(".py"):
        argv0 = argv0[:-3]
    return argv0 or "proc"


class SpanSpool:
    """Append-only span writer; usable directly as a tracer sink."""

    def __init__(self, directory: str, role: Optional[str] = None):
        self.role = role or default_role()
        self.pid = os.getpid()
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory,
                                 f"{self.role}.{self.pid}.jsonl")
        self._lock = threading.Lock()
        self._f = open(self.path, "a", encoding="utf-8")
        self._write({"k": "meta", "role": self.role, "pid": self.pid,
                     "argv": sys.argv[:4],
                     "start_wall_us": wall_us(time.perf_counter())})

    def _write(self, obj: dict):
        line = json.dumps(obj, separators=(",", ":"))
        with self._lock:
            if self._f.closed:
                return
            self._f.write(line + "\n")
            self._f.flush()      # crash tolerance: every line durable

    def __call__(self, span) -> None:
        """Tracer sink entry point (observability.tracing.Span)."""
        rec = {"k": "span", "name": span.name,
               "ts": wall_us(span.start_s),
               "dur": max(0.0, span.end_s - span.start_s) * 1e6,
               "tid": span.tid}
        if span.trace_id:
            rec["trace_id"] = span.trace_id
            rec["span_id"] = span.span_id
            if span.parent_id:
                rec["parent_id"] = span.parent_id
        if span.args:
            rec["args"] = span.args
        self._write(rec)

    def close(self):
        with self._lock:
            if not self._f.closed:
                self._f.close()


_SPOOL: Optional[SpanSpool] = None
_lock = threading.Lock()


def ensure_started(directory: Optional[str] = None,
                   role: Optional[str] = None) -> Optional[SpanSpool]:
    """Start (once) the process spool and attach it to the default
    tracer. With no ``directory``, falls back to FLAGS_trace_spool_dir
    (returns None when that is empty too)."""
    global _SPOOL
    with _lock:
        if _SPOOL is not None:
            return _SPOOL
        if directory is None:
            from paddle_tpu import flags
            directory = flags.get("trace_spool_dir")
        if not directory:
            return None
        _SPOOL = SpanSpool(directory, role)
    from paddle_tpu.observability import tracing
    tracing.add_sink(_SPOOL)
    return _SPOOL


def maybe_start_from_flags() -> None:
    """tracing.active()'s one-time autostart hook."""
    ensure_started()


def current() -> Optional[SpanSpool]:
    return _SPOOL


def shutdown() -> None:
    """Detach and close the process spool (tests; atexit not needed —
    every line is already flushed)."""
    global _SPOOL
    with _lock:
        sp, _SPOOL = _SPOOL, None
    if sp is not None:
        from paddle_tpu.observability import tracing
        tracing.remove_sink(sp)
        sp.close()
