"""Recompute / gradient-checkpointing rewrite (TPU-first addition; the
reference era's closest capability is the gradient-accumulation
multi_batch_merge_pass — ir/multi_batch_merge_pass.cc — which trades
throughput for memory at the batch level. Here the trade is per op:
`jax.checkpoint` on tagged ops makes the backward re-run their forward
instead of keeping their internals as residuals, so e.g. attention
probability matrices [B, H, T, T] or wide FFN activations never persist
between the forward and backward passes — the standard long-context
memory lever on TPU).

Attr-only, like contrib.mixed_precision / contrib.layout: tagging sets
`__remat__` on forward ops AND their `__vjp__` snapshots; the `__vjp__`
emitter (ops/grad_ops.py) wraps the re-traced forward in jax.checkpoint.
"""

from __future__ import annotations

# memory-heavy ops whose internals dominate activation footprints
# ("attention" is the fused scaled_dot_product_attention op)
DEFAULT_REMAT_OPS = ("attention", "softmax", "matmul", "fc", "mul")


def rewrite_program_recompute(program=None, op_types=DEFAULT_REMAT_OPS):
    """Tag `op_types` for backward rematerialization. Apply after
    minimize() (the `__vjp__` snapshots must exist) or before (forward
    tags propagate when backward is appended later). Returns #ops
    tagged."""
    from paddle_tpu.fluid import framework
    program = program or framework.default_main_program()
    n = 0
    for block in program.desc.blocks:
        for op in block.ops:
            if op.type in op_types:
                op.attrs["__remat__"] = True
                n += 1
            elif op.type == "__vjp__":
                fwd = op.attrs.get("fwd_op", {})
                if fwd.get("type") in op_types:
                    fwd.setdefault("attrs", {})["__remat__"] = True
                    n += 1
    program.desc.bump_version()
    return n
