"""Model-compression framework (capability parity with the reference's
contrib/slim: core/strategy.py Strategy callbacks, core/compress_pass.py
CompressPass/Context orchestration, prune/pruner.py Magnitude/Ratio
pruners, prune/prune_strategy.py Sensitive/PruneStrategy).

TPU-native re-design: the reference computes zero-masks with in-graph
layers (topk/less_than) and mutates scope tensors through a side program;
here masks are computed host-side from the scope's device arrays and
re-applied after each training step (mask-and-freeze magnitude pruning) —
a scope-level transform, like contrib.float16's transpilers, with no
per-step graph overhead. Sparsity survives optimizer updates because the
strategy re-masks after every batch; for deployment the masked weights
serialize as-is through fluid.io (dense-with-zeros, the reference's
format too — neither stack had a sparse kernel path in this era).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class Pruner:
    """reference: slim/prune/pruner.py:21 — mask factory base."""

    def prune(self, name: str, value: np.ndarray) -> np.ndarray:
        raise NotImplementedError


class MagnitudePruner(Pruner):
    """Zero-mask by |w| < threshold (reference: pruner.py:33)."""

    def __init__(self, threshold: float):
        self.threshold = float(threshold)

    def prune(self, name, value):
        return (np.abs(value) >= self.threshold).astype(value.dtype)


class RatioPruner(Pruner):
    """Keep the top `ratio` fraction of weights by magnitude (reference:
    pruner.py:51 — `ratio=0.4` keeps 40%, zeroing the rest). Per-param
    ratios with a '*' default, like the reference's ratios dict."""

    def __init__(self, ratios: Optional[Dict[str, float]] = None):
        self.ratios = ratios or {"*": 1.0}

    def ratio_for(self, name: str) -> float:
        return float(self.ratios.get(name, self.ratios.get("*", 1.0)))

    def prune(self, name, value, ratio: Optional[float] = None):
        rat = self.ratio_for(name) if ratio is None else float(ratio)
        if rat >= 1.0:
            return np.ones_like(value)
        k = max(int(rat * value.size), 1)
        flat = np.abs(value).reshape(-1)
        thresh = np.partition(flat, -k)[-k]
        return (np.abs(value) >= thresh).astype(value.dtype)


class Strategy:
    """reference: slim/core/strategy.py:18 — epoch/batch callbacks."""

    def __init__(self, start_epoch=0, end_epoch=10):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch

    def on_compress_begin(self, context):
        pass

    def on_epoch_begin(self, context):
        pass

    def on_epoch_end(self, context):
        pass

    def on_batch_begin(self, context):
        pass

    def on_batch_end(self, context):
        pass

    def on_compress_end(self, context):
        pass


class Context:
    """reference: slim/core/compress_pass.py:21 — compression state."""

    def __init__(self, exe, program, scope):
        self.epoch = 0
        self.epoch_id = 0
        self.batch_id = 0
        self.exe = exe
        self.program = program
        self.scope = scope


class PruneStrategy(Strategy):
    """Apply a pruner's masks to `params` at start_epoch and RE-APPLY
    after every batch so the optimizer cannot regrow pruned weights
    (reference: slim/prune/prune_strategy.py:38 PruneStrategy)."""

    def __init__(self, pruner: Pruner, params: List[str],
                 start_epoch=0, end_epoch=10):
        super().__init__(start_epoch, end_epoch)
        self.pruner = pruner
        self.params = list(params)
        self.masks: Dict[str, np.ndarray] = {}
        self._device_masks: Dict[str, object] = {}

    def _apply_masks(self, context):
        import jax.numpy as jnp
        for name, mask in self.masks.items():
            v = context.scope.find_var(name)
            if v is not None:
                # device-side multiply with a device-resident mask — no
                # per-batch host round-trip (the masks are tiny state;
                # the WEIGHTS must not sync through the host every step)
                dm = self._device_masks.get(name)
                if dm is None or dm.dtype != jnp.asarray(v).dtype:
                    dm = self._device_masks[name] = jnp.asarray(
                        mask, dtype=jnp.asarray(v).dtype)
                context.scope.set_var(name, jnp.asarray(v) * dm)

    def on_epoch_begin(self, context):
        if context.epoch_id == self.start_epoch and not self.masks:
            for name in self.params:
                v = context.scope.find_var(name)
                if v is None:
                    raise KeyError(f"PruneStrategy: param {name!r} not in "
                                   f"scope — run the startup program first")
                self.masks[name] = self.pruner.prune(name, np.asarray(v))
            self._apply_masks(context)

    def on_batch_end(self, context):
        if self.masks and context.epoch_id >= self.start_epoch:
            self._apply_masks(context)

    def sparsity(self, context) -> Dict[str, float]:
        out = {}
        for name in self.params:
            v = context.scope.find_var(name)
            if v is not None:
                a = np.asarray(v)
                out[name] = float((a == 0).mean())
        return out


class SensitivePruneStrategy(PruneStrategy):
    """Pick each param's keep-ratio by SENSITIVITY: sweep candidate
    ratios, measure the eval-loss delta from pruning that param alone,
    and keep the most aggressive ratio whose delta stays under
    `max_loss_increase` (reference: prune_strategy.py:23 — its published
    form delegated the schedule; the scan here is the capability)."""

    def __init__(self, pruner: RatioPruner, params: List[str],
                 eval_fn, candidate_ratios=(0.9, 0.7, 0.5, 0.3),
                 max_loss_increase=0.05, start_epoch=0, end_epoch=10):
        super().__init__(pruner, params, start_epoch, end_epoch)
        self.eval_fn = eval_fn
        self.candidates = sorted(candidate_ratios, reverse=True)
        self.max_loss_increase = float(max_loss_increase)
        self.chosen: Dict[str, float] = {}

    def on_compress_begin(self, context):
        import jax
        base = float(self.eval_fn())
        for name in self.params:
            v = context.scope.find_var(name)
            if v is None:
                raise KeyError(
                    f"SensitivePruneStrategy: param {name!r} not in "
                    f"scope — run the startup program first")
            orig = np.asarray(v).copy()
            chosen = 1.0
            # largest keep-ratio first; stop at the first ratio whose
            # loss delta exceeds the budget (sensitivity is monotone)
            for ratio in self.candidates:
                mask = self.pruner.prune(name, orig, ratio=ratio)
                context.scope.set_var(name, jax.numpy.asarray(orig * mask))
                loss = float(self.eval_fn())
                if loss - base <= self.max_loss_increase:
                    chosen = ratio
                else:
                    break
            context.scope.set_var(name, jax.numpy.asarray(orig))
            self.chosen[name] = chosen
        self.pruner.ratios = dict(self.pruner.ratios)
        self.pruner.ratios.update(self.chosen)


class Compressor:
    """Training-loop orchestration (reference: compress_pass.py:45
    CompressPass.apply): runs `epoch` epochs over `reader`, executing the
    train program per batch and firing every strategy's callbacks."""

    def __init__(self, place=None, reader=None, feeder=None, scope=None,
                 epoch: Optional[int] = None):
        import paddle_tpu.fluid as fluid
        self.place = place or fluid.TPUPlace()
        self.reader = reader
        self.feeder = feeder
        self.scope = scope
        # an EXPLICIT epoch is the user's training length and wins; left
        # unset, strategies' end_epoch extends the run (the reference's
        # max() behavior, compress_pass.py add_strategy)
        self._epoch_explicit = epoch is not None
        self.epoch = epoch if epoch is not None else 1
        self.strategies: List[Strategy] = []

    def add_strategy(self, strategy: Strategy):
        self.strategies.append(strategy)
        if not self._epoch_explicit:
            self.epoch = max(self.epoch, strategy.end_epoch)
        return self

    def run(self, program, fetch_list=None):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.core.scope import global_scope
        exe = fluid.Executor(self.place)
        scope = self.scope or global_scope()
        context = Context(exe, program, scope)
        context.epoch = self.epoch
        for s in self.strategies:
            s.on_compress_begin(context)
        last_fetch = None
        for epoch_id in range(self.epoch):
            context.epoch_id = epoch_id
            for s in self.strategies:
                s.on_epoch_begin(context)
            for batch_id, data in enumerate(self.reader()):
                context.batch_id = batch_id
                for s in self.strategies:
                    s.on_batch_begin(context)
                feed = self.feeder.feed(data) if self.feeder else data
                last_fetch = exe.run(program, feed=feed,
                                     fetch_list=fetch_list or [],
                                     scope=scope)
                for s in self.strategies:
                    s.on_batch_end(context)
            for s in self.strategies:
                s.on_epoch_end(context)
        for s in self.strategies:
            s.on_compress_end(context)
        return last_fetch
