"""Mixed-precision training decorator (capability successor of the
reference's fp16 direction: the reference era shipped fp16 *inference*
(contrib/float16); this adds the training half the way later fluid did —
loss scaling + overflow-safe updates — expressed dataflow-style for XLA).

On TPU the compute dtype is bfloat16, whose fp32-equal exponent range
makes loss scaling unnecessary for most models; `decorate` exists for
capability parity and true-fp16 experiments. Semantics:

  scaled_loss = loss * scale;  grads = backward(scaled_loss)
  finite      = all(isfinite(g))
  g'          = g * finite / scale      # zeroed on overflow -> update is
                                        # skipped in effect (divergence:
                                        # adaptive moments see a zero grad
                                        # instead of no op at all)
  dynamic: scale grows by incr_ratio after incr_every_n_steps clean steps,
  shrinks by decr_ratio on overflow — all on-device (XLA select), no host
  round-trip per step."""

from __future__ import annotations

from paddle_tpu.fluid import framework
from paddle_tpu.fluid.initializer import ConstantInitializer
from paddle_tpu.fluid.layer_helper import LayerHelper


def _emit(op_type, inputs, n_out=1, attrs=None, dtype="float32",
          out_slot="Out"):
    helper = LayerHelper(op_type)
    outs = [helper.create_variable_for_type_inference(dtype)
            for _ in range(n_out)]
    helper.append_op(op_type, inputs=inputs, outputs={out_slot: outs},
                     attrs=attrs or {})
    return outs[0] if n_out == 1 else outs


def _const(value):
    from paddle_tpu.fluid import layers
    return layers.fill_constant([1], "float32", float(value))


def _finite_flag(grads):
    """all(isfinite(g)) over every gradient, as a float32 [1] tensor."""
    from paddle_tpu.fluid import layers
    flags = []
    for g in grads:
        fin = _emit("isfinite", {"X": [g]}, dtype="bool")
        flags.append(layers.cast(fin, "float32"))
    prod = flags[0]
    for f in flags[1:]:
        prod = layers.elementwise_mul(prod, f)
    return layers.reshape(prod, shape=[1])


def decorate(optimizer, init_loss_scaling=2.0 ** 15,
             use_dynamic_loss_scaling=True, incr_every_n_steps=1000,
             decr_every_n_nan_or_inf=2, incr_ratio=2.0, decr_ratio=0.5):
    """reference: fluid.contrib.mixed_precision.decorate(optimizer, ...)
    -> optimizer whose minimize() trains under loss scaling."""
    return OptimizerWithMixedPrecision(
        optimizer, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, incr_ratio, decr_ratio,
        decr_every_n_nan_or_inf)


class OptimizerWithMixedPrecision:
    def __init__(self, optimizer, init_scale, dynamic, incr_every,
                 incr_ratio, decr_ratio, decr_every=2):
        self._opt = optimizer
        self._init_scale = float(init_scale)
        self._dynamic = dynamic
        self._incr_every = float(incr_every)
        self._incr_ratio = float(incr_ratio)
        self._decr_ratio = float(decr_ratio)
        self._decr_every = float(decr_every)

    @property
    def loss_scaling_name(self):
        return "loss_scaling@AMP"

    def backward(self, *a, **kw):
        return self._opt.backward(*a, **kw)

    def apply_gradients(self, params_grads):
        return self._opt.apply_gradients(params_grads)

    def _persistable(self, name, value):
        main = framework.default_main_program()
        startup = framework.default_startup_program()
        v = main.global_block().create_var(
            name=name, shape=[1], dtype="float32", persistable=True,
            stop_gradient=True)
        sv = startup.global_block().create_var(
            name=name, shape=[1], dtype="float32", persistable=True)
        ConstantInitializer(float(value))(sv, startup.global_block())
        return v

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from paddle_tpu.fluid import layers

        scale_var = self._persistable(self.loss_scaling_name,
                                      self._init_scale)
        good_steps = self._persistable("good_steps@AMP", 0.0)

        scaled_loss = layers.elementwise_mul(loss, scale_var)
        params_grads = self._opt.backward(scaled_loss, startup_program,
                                          parameter_list, no_grad_set)

        finite = _finite_flag([g for _, g in params_grads])
        # g' = g * (finite / scale): [1] broadcasts against any grad shape
        mult = layers.elementwise_div(finite, scale_var)
        safe = [(p, layers.elementwise_mul(g, mult))
                for p, g in params_grads]
        opt_ops = self._opt.apply_gradients(safe)

        if self._dynamic:
            bad_steps = self._persistable("bad_steps@AMP", 0.0)
            one = _const(1.0)
            not_finite = layers.elementwise_sub(one, finite)
            inc = layers.elementwise_mul(
                layers.elementwise_add(good_steps, one), finite)
            reached = layers.cast(
                _ge(inc, _const(self._incr_every)), "float32")
            grown = layers.elementwise_mul(
                scale_var,
                layers.elementwise_add(
                    one, layers.elementwise_mul(
                        reached, _const(self._incr_ratio - 1.0))))
            # shrink only after decr_every consecutive nan/inf steps
            # (reference: decr_every_n_nan_or_inf semantics)
            bad_inc = layers.elementwise_mul(
                layers.elementwise_add(bad_steps, one), not_finite)
            decr_reached = layers.cast(
                _ge(bad_inc, _const(self._decr_every)), "float32")
            shrunk_overflow = layers.elementwise_add(
                layers.elementwise_mul(
                    layers.elementwise_mul(scale_var,
                                           _const(self._decr_ratio)),
                    decr_reached),
                layers.elementwise_mul(
                    scale_var, layers.elementwise_sub(one, decr_reached)))
            new_scale = layers.elementwise_add(
                layers.elementwise_mul(grown, finite),
                layers.elementwise_mul(shrunk_overflow, not_finite))
            layers.assign(new_scale, scale_var)
            keep = layers.elementwise_mul(
                inc, layers.elementwise_sub(one, reached))
            layers.assign(keep, good_steps)
            keep_bad = layers.elementwise_mul(
                bad_inc, layers.elementwise_sub(one, decr_reached))
            layers.assign(keep_bad, bad_steps)

        return opt_ops, params_grads


def _ge(a, b):
    """a >= b as a float-friendly bool tensor via the compare ops."""
    from paddle_tpu.fluid import layers
    return layers.greater_equal(a, b)


AMP_OP_TYPES = ("conv2d", "depthwise_conv2d", "conv2d_fusion", "conv3d",
                "mul", "matmul", "conv2d_transpose", "fc",
                "fused_linear_ce", "fused_attention_block")


RECURRENT_OPS = ("dynamic_lstm", "dynamic_gru", "dynamic_lstmp", "while",
                 "gru_unit", "lstm_unit")


def rewrite_program_amp(program=None, op_types=AMP_OP_TYPES, pure=None):
    """bf16 compute rewrite: tag every MXU op so its emitter casts float
    inputs to bfloat16 (master weights stay fp32 in the Scope — the
    later-fluid pure-bf16 AMP capability, done at the op level so autodiff
    re-traces see the same cast).

    pure=True additionally keeps the tagged ops' OUTPUTS bf16, so
    activations stay half-width through the whole elementwise/norm tail
    between MXU ops (batch/layer norm compute fp32 statistics and
    bias-adds cast parameters down rather than promoting — see
    ops/nn_ops.py, ops/basic.py); the loss boundary
    (softmax_with_cross_entropy) upcasts to fp32. pure=False restores
    fp32 at every op edge (the conservative per-op mode).

    pure=None (default) auto-selects: pure bf16 unless the program
    contains recurrent-scan ops (RECURRENT_OPS) — scan steps are small
    and latency-bound, where bf16 activation edges add per-step converts
    instead of saving bandwidth (measured: machine_translation GRU 772k
    words/s conservative vs 650k pure on v5e; ResNet-50 the reverse,
    2530 pure vs 1890 conservative img/s).

    bf16's fp32-equal exponent range makes loss scaling unnecessary
    (module docstring), so this composes with — but does not require —
    `decorate`."""
    from paddle_tpu.fluid import framework
    program = program or framework.default_main_program()
    from paddle_tpu.ops.basic import ELEMENTWISE_OPS as elementwise
    if pure is None:
        pure = not any(op.type in RECURRENT_OPS
                       for block in program.desc.blocks
                       for op in block.ops)
    n = 0
    for block in program.desc.blocks:        # sub-blocks too (while/cond)
        for op in block.ops:
            if op.type in op_types:
                op.attrs["__amp_bf16__"] = True
                if pure:
                    op.attrs["__amp_keep_bf16__"] = True
                n += 1
            elif pure and op.type in elementwise:
                # bias/scale adds after tagged ops: cast the fp32 param
                # operand down instead of promoting the bf16 activation up
                op.attrs["__amp_match_dtype__"] = True
            elif pure and op.type == "lookup_table":
                # the embedding STARTS the residual stream: keep it bf16
                # or every downstream elementwise/norm runs fp32 (2x HBM)
                op.attrs["__amp_keep_bf16__"] = True
                n += 1
            elif op.type == "__vjp__":
                # backward ops re-trace a SNAPSHOT of the forward op
                # (grad_ops.py fwd_op dict) — tag it too so rewrites after
                # minimize() keep the backward in bf16
                fwd = op.attrs.get("fwd_op", {})
                if fwd.get("type") in op_types:
                    fwd.setdefault("attrs", {})["__amp_bf16__"] = True
                    if pure:
                        fwd["attrs"]["__amp_keep_bf16__"] = True
                    n += 1
                elif pure and fwd.get("type") in elementwise:
                    fwd.setdefault("attrs", {})["__amp_match_dtype__"] = True
                elif pure and fwd.get("type") == "lookup_table":
                    fwd.setdefault("attrs", {})["__amp_keep_bf16__"] = True
    program.desc.bump_version()
    return n
