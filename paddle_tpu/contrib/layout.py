"""NHWC layout transpiler (TPU-first addition; the reference's conv ops
carry a fixed NCHW data layout — operators/conv_op.cc — with MKLDNN doing
its own internal relayout; on TPU the vector lanes are the MINOR dimension,
so channels-last puts C on the 128-wide lane axis: measured on v5e,
elementwise traffic runs 2.4x faster (1496 vs 624 GB/s effective) and convs
~1.3x (208 vs 160 TFLOP/s) versus NCHW).

Attr-only rewrite, like contrib.mixed_precision: no ops are inserted and no
vars renamed. Each convertible op (conv2d / depthwise_conv2d / pool2d /
batch_norm) gets `__nhwc__` plus boundary flags, and transposes happen
INSIDE the tagged emitters only at region edges; `__vjp__` backward ops
re-trace the tagged forward emitter, so gradients follow the layout
automatically (cotangents mirror the primal layout jax.vjp sees).

Apply after (or before) minimize(), same as rewrite_program_amp:

    rewrite_program_nhwc(main_program)
"""

from __future__ import annotations

from collections import defaultdict

# data slots of convertible ops: (input slot, output slot)
CONVERT_SLOTS = {
    "conv2d": ("Input", "Output"),
    "depthwise_conv2d": ("Input", "Output"),
    "conv2d_fusion": ("Input", "Output"),   # paddle_tpu/passes fusion
    "pool2d": ("X", "Out"),
    "batch_norm": ("X", "Y"),
}

# layout-transparent ops: rank-4 inputs/outputs all share one layout
AGNOSTIC = {
    "relu", "leaky_relu", "relu6", "sigmoid", "tanh", "sqrt", "square",
    "abs", "exp", "scale", "cast", "dropout", "clip", "swish",
    "hard_sigmoid", "elu", "pow", "soft_relu", "brelu", "sum",
}

from paddle_tpu.ops.basic import ELEMENTWISE_OPS as ELEMENTWISE


def _op_bcast_kind(op, var_lookup):
    """_bcast_kind over an elementwise OpDesc — the one extraction point
    (Y slot, shape, axis attr) shared by the residency fixpoint and the
    tagging pass, so the two can never classify the same op
    differently."""
    y = (op.inputs.get("Y") or [None])[0]
    yv = var_lookup(y)
    ys = yv.shape if (yv is not None and yv.shape is not None) else None
    return _bcast_kind(ys, op.attrs.get("axis", -1))


def _bcast_kind(ys, axis):
    """Classify an elementwise op's Y-broadcast against a rank-4 X — the
    SINGLE source shared by the residency fixpoint and the tagging pass
    (and mirrored by the emitter re-aims in ops/basic.py):
    'scalar'  — rank-0/[1] Y, layout-free;
    'chan'    — rank-1 [C] at axis=1 (re-aims to the last axis);
    'bc'      — rank-2 [B, C] at axis=0 (squeeze-excitation gates,
                re-aims to [B, 1, 1, C]);
    'full'    — rank-4 Y (same-layout group constraint);
    None      — positional broadcast the emitter cannot re-aim."""
    if ys is None:
        return None
    if len(ys) == 0 or (len(ys) == 1 and ys[0] == 1):
        return "scalar"
    if len(ys) == 1 and axis == 1:
        return "chan"
    if len(ys) == 2 and axis == 0:
        return "bc"
    if len(ys) >= 4:
        return "full"
    return None


def rewrite_program_nhwc(program=None):
    """Tag maximal NHWC regions in block 0. Returns #ops tagged."""
    from paddle_tpu.fluid import framework
    program = program or framework.default_main_program()
    blk = program.desc.global_block
    ops = list(blk.ops)

    def _var(name):
        return blk.var(name) if name and blk.has_var(name) else None

    def activation4(name):
        """rank-4 float non-param var — a candidate for NHWC residency."""
        v = _var(name)
        return (v is not None and v.shape is not None and len(v.shape) == 4
                and v.dtype.startswith(("float", "bfloat"))
                and not v.persistable and not v.is_parameter)

    producers = {}
    for oi, op in enumerate(ops):
        for slot, names in op.outputs.items():
            for n in names:
                producers[n] = oi

    # optimistic assignment: every produced rank-4 activation starts NHWC;
    # constraints below falsify until fixpoint. Feed vars (no producer)
    # stay out — the first conv transposes in.
    nhwc = {n: True for n in producers if activation4(n)}

    def rank4_var(name):
        v = _var(name)
        return (v is not None and v.shape is not None
                and len(v.shape) == 4)

    def group_all_or_none(names):
        """Equality constraint: the named rank-4 vars share one layout.
        A rank-4 var NOT in `nhwc` (a feed var, a parameter) is fixed
        NCHW and falsifies the whole group."""
        present = [n for n in names if n in nhwc]
        fixed_nchw = any(n not in nhwc and rank4_var(n)
                         for n in names if n)
        if present and (fixed_nchw
                        or not all(nhwc[n] for n in present)):
            changed = False
            for n in present:
                if nhwc[n]:
                    nhwc[n] = False
                    changed = True
            return changed
        return False

    def run_fixpoint():
        changed = True
        while changed:
            changed = False
            for op in ops:
                changed |= constrain_op(op)

    def constrain_op(op):
            changed = False
            t = op.type
            if t in CONVERT_SLOTS or t == "__vjp__":
                # convertible ops accept either layout on their data slot;
                # __vjp__ mirrors its forward op's tags
                return False
            ins = [n for names in op.inputs.values() for n in names]
            outs = [n for names in op.outputs.values() for n in names]
            if t in AGNOSTIC:
                changed |= group_all_or_none(ins + outs)
            elif t == "concat":
                if op.attrs.get("axis", 0) == 1:
                    # channel concat: transparent, emitter re-aims axis
                    changed |= group_all_or_none(ins + outs)
                else:
                    for n in ins + outs:
                        if nhwc.get(n):
                            nhwc[n] = False
                            changed = True
            elif t in ELEMENTWISE:
                x = (op.inputs.get("X") or [None])[0]
                y = (op.inputs.get("Y") or [None])[0]
                o = (op.outputs.get("Out") or [None])[0]
                kind = _op_bcast_kind(op, _var)
                if kind in ("scalar", "chan", "bc"):
                    # layout-free or emitter-re-aimable broadcasts
                    changed |= group_all_or_none([x, o])
                elif kind is None:
                    # positional broadcasts the emitter cannot re-aim:
                    # X/Out must stay NCHW
                    for n in (x, o):
                        if nhwc.get(n):
                            nhwc[n] = False
                            changed = True
                else:                       # 'full': same-layout group
                    changed |= group_all_or_none([x, y, o])
            else:
                # unconvertible op: all its rank-4 vars must be NCHW
                for n in ins + outs:
                    if nhwc.get(n):
                        nhwc[n] = False
                        changed = True
            return changed

    run_fixpoint()
    # Gradient vars' PHYSICAL layout is dictated by the __vjp__ re-trace:
    # cotangents mirror the forward var's layout (jax.vjp). If the
    # fixpoint concluded a grad var must be NCHW (some unconvertible
    # non-__vjp__ op consumes it) while its forward var is NHWC-resident,
    # the layouts would disagree — falsify the FORWARD var and re-run
    # until consistent (round-1 advisor finding: the old code
    # unconditionally overrode the grad's residency with the forward's).
    while True:
        conflicted = False
        for n in list(nhwc):
            if "@GRAD" in n and not nhwc[n]:
                fwd = n.split("@GRAD")[0]
                if nhwc.get(fwd):
                    nhwc[fwd] = False
                    conflicted = True
        if not conflicted:
            break
        run_fixpoint()

    # --- tagging ---
    tags = {}                       # fwd op index -> attr dict
    n_tagged = 0
    for oi, op in enumerate(ops):
        t = op.type
        if t in CONVERT_SLOTS:
            in_slot, out_slot = CONVERT_SLOTS[t]
            xin = (op.inputs.get(in_slot) or [None])[0]
            xout = (op.outputs.get(out_slot) or [None])[0]
            in_ready = bool(nhwc.get(xin))
            out_keep = bool(nhwc.get(xout))
            if in_ready or out_keep:
                tags[oi] = {"__nhwc__": True,
                            "__nhwc_in_ready__": in_ready,
                            "__nhwc_out_keep__": out_keep}
            if t == "conv2d_fusion":
                # the residual operand's own residency is independent of
                # the op's data slot — record it so the emitter knows
                # which transpose (if any) the region edge needs
                resid = (op.inputs.get("ResidualData") or [None])[0]
                if resid is not None and (nhwc.get(resid)
                                          or oi in tags):
                    tags.setdefault(oi, {})["__nhwc_resid_ready__"] = \
                        bool(nhwc.get(resid))
        elif t in ELEMENTWISE:
            x = (op.inputs.get("X") or [None])[0]
            kind = _op_bcast_kind(op, _var)
            if nhwc.get(x) and kind == "chan":
                tags[oi] = {"__nhwc_bcast__": True}
            elif nhwc.get(x) and kind == "bc":
                # [B, C] gate at axis=0 broadcasts as [B, 1, 1, C] when X
                # is NHWC-resident (squeeze-excitation)
                tags[oi] = {"__nhwc_bcast_bc__": True}
        elif t == "concat":
            first_in = (op.inputs.get("X") or [None])[0]
            if nhwc.get(first_in) and op.attrs.get("axis", 0) == 1:
                tags[oi] = {"__nhwc_concat__": True}
    for oi, attrs in tags.items():
        ops[oi].attrs.update(attrs)
        n_tagged += 1
    # stamp residency on the var descs: the executor transposes fetched
    # NHWC-resident vars back to the declared NCHW layout (lowering.py).
    # Gradient vars are produced by __vjp__ re-traces, whose cotangents
    # mirror the FORWARD var's physical layout (jax.vjp), so their
    # residency is the forward var's — the fixpoint (which skips __vjp__)
    # never constrained them.
    for n in list(nhwc):
        if "@GRAD" in n:
            nhwc[n] = bool(nhwc.get(n.split("@GRAD")[0]))
    for n, resident in nhwc.items():
        if resident:
            blk.var(n).attrs["__nhwc__"] = True
    # mirror into backward snapshots (grad_ops.py __vjp__ re-trace).
    # Match by the shared snapshot identity (type, sorted outputs) —
    # NOT by fwd_op_index: a pass pipeline that ran before this rewrite
    # (paddle_tpu/passes) renumbers ops, so the snapshot index no
    # longer addresses the forward op it was taken from.
    from paddle_tpu.fluid.ir_pass import vjp_snapshot_key
    snap_tags = {vjp_snapshot_key(ops[oi].type, ops[oi].outputs): t_attrs
                 for oi, t_attrs in tags.items()}
    for op in ops:
        if op.type == "__vjp__":
            snap = op.attrs.get("fwd_op", {})
            key = vjp_snapshot_key(snap.get("type"), snap.get("outputs"))
            if key in snap_tags:
                snap.setdefault("attrs", {}).update(snap_tags[key])
    program.desc.bump_version()
    return n_tagged
