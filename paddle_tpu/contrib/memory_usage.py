"""Program memory-usage estimator.

Capability parity with the reference's contrib memory_usage_calc
(python/paddle/fluid/contrib/memory_usage_calc.py — sums var sizes with
the batch dim resolved, reporting a low/high band). TPU-native notes
folded in: params + optimizer state are persistent HBM residents; under
buffer donation the optimizer update aliases in place (no 2x); and the
activation working set is the compiler's to schedule, so the per-var sum
is an UPPER bound on activations (XLA reuses buffers by liveness).
"""

from __future__ import annotations

import re

DTYPE_BYTES = {
    "float32": 4, "float64": 8, "float16": 2, "bfloat16": 2,
    "int64": 8, "int32": 4, "int16": 2, "int8": 1, "uint8": 1,
    "bool": 1,
}


# optimizer accumulators are named '<param>_<kind>_N' (fluid/optimizer.py
# _add_accumulator); the kind list mirrors observability.memory's census
# classifier
_ACC_RE = re.compile(
    r"_(velocity|moment1|moment2|beta1_pow_acc|beta2_pow_acc|moment|"
    r"inf_norm|avg_squared_grad|avg_squared_update|mean_square|momentum|"
    r"mean_grad|squared|linear)_\d+$")


def _is_accumulator(name: str) -> bool:
    return bool(_ACC_RE.search(name))


def _var_bytes(v, batch_size):
    if v.shape is None:
        return 0
    n = 1
    for d in v.shape:
        n *= batch_size if d is None or int(d) < 0 else int(d)
    return n * DTYPE_BYTES.get(v.dtype, 4)


def memory_usage(program, batch_size: int, optimizer_slots: int = 0):
    """Estimated HBM bytes for one training step of `program`.

    Returns a dict {persistent, activations, total_low, total_high}:
    - persistent: parameters + every persistable. Optimizer accumulators
      are ALREADY persistable vars at graph-build time (minimize() adds
      them, fluid/optimizer.py _add_accumulator), so they are counted
      here directly; `optimizer_slots` exists only for forward-only
      programs whose optimizer state lives elsewhere (default 0 — a
      nonzero value on a minimized program would double-count).
    - activations: per-var upper bound of non-persistable tensors.
    - total_low/total_high: the reference reported a +-15% band
      (memory_usage_calc.py DEBUG band); the low end here is persistent
      + half the activation bound (XLA liveness reuse), the high end the
      straight sum.
    """
    desc = program.desc if hasattr(program, "desc") else program
    persistent = 0
    activations = 0
    params = 0
    has_opt_state = False
    seen = set()
    # every block: while/RNN bodies and Pipeline stages hold their own
    # activation vars (one live iteration under lax.scan/while). A name
    # declared in several blocks (a sub-block shadowing or re-declaring
    # its parent's var) counts ONCE — dedup by NAME across blocks
    for block in desc.blocks:
        for v in block.vars.values():
            if v.name in seen:
                continue
            seen.add(v.name)
            b = _var_bytes(v, batch_size)
            if v.persistable:
                persistent += b
                if getattr(v, "is_parameter", False):
                    params += b
                elif (getattr(v, "attrs", None) or {}).get(
                        "optimizer_state") or _is_accumulator(v.name):
                    has_opt_state = True
            else:
                activations += b
    # a minimized program already holds its accumulators as persistables
    # (counted above) — a caller-passed optimizer_slots would double-
    # count them, which the compiled memory_analysis() reconciliation
    # caught (tools/mem_probe.py); the estimate only adds slots when the
    # program provably has no optimizer state of its own
    est_opt_state = 0 if has_opt_state else params * optimizer_slots
    persistent_total = persistent + est_opt_state
    return {
        "parameters": params,
        "persistent": persistent_total,
        "activations": activations,
        "total_low": persistent_total + activations // 2,
        "total_high": persistent_total + activations,
    }


def memory_usage_gb(program, batch_size: int, **kw):
    u = memory_usage(program, batch_size, **kw)
    return {k: v / (1 << 30) for k, v in u.items()}
