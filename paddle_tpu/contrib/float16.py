"""Reduced-precision inference transpiler (reference:
contrib/float16/float16_transpiler.py — rewrites a test-mode program to
fp16: params cast once, compute in half precision, fetches cast back).

TPU-native: the reduced dtype is **bfloat16** — same exponent range as
fp32, so the reference's black-list/overflow bookkeeping is unnecessary;
the MXU natively consumes bf16 operands. The transpile is:
  1. cast persistable float32 params in the scope to bf16,
  2. insert cast(feed -> bf16) after feeds and cast(fetch -> fp32) before
     fetches by rewriting the program desc,
XLA then runs the interior in bf16 (fp32 islands where dtype promotion
demands, e.g. batch-norm statistics)."""

from __future__ import annotations

import numpy as np

from paddle_tpu.core import ir


class BF16Transpiler:
    """reference: float16_transpiler.py Float16Transpiler.transpile
    (program, place, scope)."""

    target_dtype = "bfloat16"

    def transpile(self, program, place=None, scope=None,
                  feed_names=None, fetch_names=None):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.core.scope import global_scope
        scope = scope or global_scope()
        block = program.desc.global_block

        # 1. params -> bf16 in the scope (cast once, like the reference's
        #    one-time weight conversion)
        for name, vd in block.vars.items():
            if not vd.persistable or vd.dtype != "float32":
                continue
            val = scope.find_var(name)
            if val is None:
                continue
            scope.set_var(name, jax.device_put(
                jnp.asarray(np.asarray(val),
                            dtype=jnp.dtype(self.target_dtype))))
            vd.dtype = self.target_dtype

        # 2. cast feeds in / fetches out
        feed_names = list(feed_names or [])
        fetch_names = list(fetch_names or [])
        renames = {}
        new_ops = []
        for fname in feed_names:
            if not block.has_var(fname):
                continue
            vd = block.var(fname)
            if vd.dtype != "float32":
                continue                       # int feeds stay integral
            half = fname + "@BF16"
            block.add_var(ir.VarDesc(name=half, shape=vd.shape,
                                     dtype=self.target_dtype))
            new_ops.append(ir.OpDesc(
                type="cast", inputs={"X": [fname]}, outputs={"Out": [half]},
                attrs={"in_dtype": "float32",
                       "out_dtype": self.target_dtype}))
            renames[fname] = half

        for op in block.ops:
            op.inputs = {slot: [renames.get(n, n) for n in names]
                         for slot, names in op.inputs.items()}
        block.ops[:0] = new_ops

        for fname in fetch_names:
            if not block.has_var(fname):
                continue
            vd = block.var(fname)
            if vd.dtype not in ("float32", self.target_dtype):
                continue                       # int fetches stay integral
            has_producer = any(
                fname in names for op in block.ops
                for names in op.outputs.values())
            if not has_producer:
                continue  # direct feed / param fetch: nothing to rewrite
            half = fname + "@PREF32"
            # the op producing the fetch now writes the @PREF32 temp (and
            # every interior consumer reads it); a trailing cast
            # materializes the fp32 fetch under the original name
            for op in block.ops:
                op.outputs = {slot: [half if n == fname else n
                                     for n in names]
                              for slot, names in op.outputs.items()}
                op.inputs = {slot: [half if n == fname else n
                                    for n in names]
                             for slot, names in op.inputs.items()}
            block.add_var(ir.VarDesc(name=half, shape=vd.shape,
                                     dtype=self.target_dtype))
            vd.dtype = "float32"
            block.append_op(ir.OpDesc(
                type="cast", inputs={"X": [half]}, outputs={"Out": [fname]},
                attrs={"in_dtype": self.target_dtype,
                       "out_dtype": "float32"}))

        program.desc.bump_version()
        return program


# the reference spelling; fp16 proper is available for completeness but
# bf16 is the TPU-correct choice
class Float16Transpiler(BF16Transpiler):
    target_dtype = "float16"
