"""contrib high-level APIs (reference: python/paddle/fluid/contrib/
trainer.py, inferencer.py, op_frequence.py). The Trainer/Inferencer
pair is the fluid-era "simple API" used by the book notebooks; events
mirror the v2 trainer's (paddle_tpu/trainer.py is the v2 form)."""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

import paddle_tpu.fluid as fluid


class BeginEpochEvent:
    """reference: contrib/trainer.py:40."""

    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class Trainer:
    """reference: contrib/trainer.py Trainer — builds the program from a
    `train_func() -> loss (or [loss, ...metrics])`, owns its scope, runs
    epochs over a reader with event callbacks, save/load via
    fluid.io."""

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None):
        self.place = place or fluid.CPUPlace()
        self.scope = fluid.Scope()
        self.train_program = fluid.Program()
        self.startup_program = fluid.Program()
        from paddle_tpu.fluid import unique_name
        with unique_name.guard():
            with fluid.program_guard(self.train_program,
                                     self.startup_program):
                out = train_func()
                self.train_outputs = (list(out)
                                      if isinstance(out, (list, tuple))
                                      else [out])
                loss = self.train_outputs[0]
                optimizer_func().minimize(loss)
        self.exe = fluid.Executor(self.place)
        self.exe.run(self.startup_program, scope=self.scope)
        if param_path:
            fluid.io.load_persistables(self.exe, param_path,
                                       self.train_program, scope=self.scope)

    def train(self, num_epochs, event_handler, reader=None, feed_order=None):
        feed_order = feed_order or []
        for epoch in range(num_epochs):
            event_handler(BeginEpochEvent(epoch))
            for step, data in enumerate(reader()):
                begin = BeginStepEvent(epoch, step)
                event_handler(begin)
                feed = self._to_feed(data, feed_order)
                # the handler may clear fetch_metrics to skip the
                # device->host metric transfer (reference
                # contrib/trainer.py:508 checks it before fetching)
                fetch = self.train_outputs if begin.fetch_metrics else []
                vals = self.exe.run(self.train_program, feed=feed,
                                    fetch_list=fetch, scope=self.scope)
                event_handler(EndStepEvent(
                    epoch, step, [np.asarray(v) for v in vals]))
            event_handler(EndEpochEvent(epoch))

    def _to_feed(self, data, feed_order):
        if isinstance(data, dict):
            return data
        if data and isinstance(data[0], (list, tuple)):
            cols = list(zip(*data))
            return OrderedDict(
                (name, np.stack([np.asarray(v) for v in col]))
                for name, col in zip(feed_order, cols))
        return OrderedDict((name, np.asarray(v))
                           for name, v in zip(feed_order, data))

    def save_params(self, param_path):
        fluid.io.save_persistables(self.exe, param_path,
                                   self.train_program, scope=self.scope)

    def stop(self):
        pass


class Inferencer:
    """reference: contrib/inferencer.py — rebuild the inference graph
    from `infer_func()`, load params from `param_path`, run feeds."""

    def __init__(self, infer_func, param_path, place=None, parallel=False):
        self.place = place or fluid.CPUPlace()
        self.scope = fluid.Scope()
        self.inference_program = fluid.Program()
        startup = fluid.Program()
        from paddle_tpu.fluid import unique_name
        with unique_name.guard():
            with fluid.program_guard(self.inference_program, startup):
                out = infer_func()
                self.fetch = (list(out) if isinstance(out, (list, tuple))
                              else [out])
        self.exe = fluid.Executor(self.place)
        self.exe.run(startup, scope=self.scope)
        fluid.io.load_params(self.exe, param_path, self.inference_program,
                             scope=self.scope)
        self.inference_program = self.inference_program.clone(for_test=True)

    def infer(self, inputs, return_numpy=True):
        vals = self.exe.run(self.inference_program, feed=inputs,
                            fetch_list=self.fetch, scope=self.scope,
                            return_numpy=return_numpy)
        return vals


def op_freq_statistic(program):
    """reference: contrib/op_frequence.py op_freq_statistic — (uni-op,
    adjacent-op-pair) frequency tables over a program."""
    uni_op_freq = OrderedDict()
    adj_2_op_freq = OrderedDict()
    prev = None
    for op in program.global_block().ops:
        uni_op_freq[op.type] = uni_op_freq.get(op.type, 0) + 1
        if prev is not None:
            key = prev + "->" + op.type
            adj_2_op_freq[key] = adj_2_op_freq.get(key, 0) + 1
        prev = op.type
    uni = sorted(uni_op_freq.items(), key=lambda x: -x[1])
    adj = sorted(adj_2_op_freq.items(), key=lambda x: -x[1])
    return uni, adj
