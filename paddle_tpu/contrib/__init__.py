"""paddle_tpu.contrib (reference: paddle/contrib + python contrib/ —
float16 inference transpiler contrib/float16/float16_transpiler.py, mixed
precision utilities). bfloat16 replaces float16 throughout: it is the
MXU-native reduced precision and needs no loss-scaling tricks for
inference."""

from paddle_tpu.contrib import layout  # noqa: F401
from paddle_tpu.contrib import mixed_precision  # noqa: F401
from paddle_tpu.contrib import recompute  # noqa: F401
from paddle_tpu.contrib import slim  # noqa: F401
from paddle_tpu.contrib.memory_usage import (  # noqa: F401
    memory_usage, memory_usage_gb)
from paddle_tpu.contrib.float16 import BF16Transpiler, Float16Transpiler

from paddle_tpu.contrib.quantize_transpiler import QuantizeTranspiler  # noqa: F401
from paddle_tpu.contrib.high_level import (  # noqa: F401
    BeginEpochEvent, BeginStepEvent, EndEpochEvent, EndStepEvent,
    Inferencer, Trainer, op_freq_statistic)

__all__ = ["BF16Transpiler", "Float16Transpiler", "QuantizeTranspiler",
           "Trainer", "Inferencer", "op_freq_statistic",
           "layout", "mixed_precision", "slim"]
