"""Quantization-aware-training transpiler (reference:
python/paddle/fluid/contrib/quantize/quantize_transpiler.py:81
QuantizeTranspiler — training_transpile inserts fake_quantize/
fake_dequantize pairs around conv2d/depthwise_conv2d/mul;
freeze_program rewrites for int8 inference).

TPU note: the fake-quant ops are plain jnp emitters, so after transpile the
whole quantize→op→dequantize chain is one fused XLA computation — QAT costs
one extra abs-max reduction per quantized tensor."""

from __future__ import annotations

from typing import Optional

from paddle_tpu.core import ir
from paddle_tpu.fluid import framework, unique_name

_QUANTIZABLE_OP_TYPES = ("conv2d", "depthwise_conv2d", "mul")
# input slots carrying quantizable tensors per op type
_QUANT_SLOTS = {"conv2d": ("Input", "Filter"),
                "depthwise_conv2d": ("Input", "Filter"),
                "mul": ("X", "Y")}


class QuantizeTranspiler:
    """reference: quantize_transpiler.py:81."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type="abs_max",
                 weight_quantize_type="abs_max", window_size=10000):
        if activation_quantize_type not in ("abs_max", "range_abs_max"):
            raise ValueError(activation_quantize_type)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.act_type = activation_quantize_type
        self.weight_type = weight_quantize_type
        self.window_size = window_size

    def training_transpile(self, program: Optional[framework.Program] = None,
                           startup_program=None):
        """Insert fake_quant(+dequant) before every quantizable input of
        conv2d/depthwise_conv2d/mul ops, in place. range_abs_max state
        buffers are zero-initialized in the startup program (two-program
        convention)."""
        program = program or framework.default_main_program()
        self._startup = startup_program or framework.default_startup_program()
        block = program.desc.global_block
        params = {v.name for v in block.vars.values()
                  if getattr(v, "persistable", False)}
        new_ops = []
        quanted = {}          # var name -> dequantized replacement name
        for op in block.ops:
            if op.type in _QUANTIZABLE_OP_TYPES:
                for slot in _QUANT_SLOTS[op.type]:
                    names = op.inputs.get(slot, [])
                    for i, name in enumerate(names):
                        if name not in quanted:
                            is_w = name in params
                            bits = self.weight_bits if is_w \
                                else self.activation_bits
                            qtype = self.weight_type if is_w \
                                else self.act_type
                            quanted[name] = self._insert_quant_dequant(
                                block, new_ops, name, bits, qtype, program)
                        names[i] = quanted[name]
            new_ops.append(op)
        block.ops[:] = new_ops
        program.desc.bump_version()
        return program

    def _insert_quant_dequant(self, block, new_ops, name, bits, qtype,
                              program):
        vd = block.var(name)
        qname = unique_name.generate(name + ".quantized")
        sname = unique_name.generate(name + ".scale")
        dqname = unique_name.generate(name + ".dequantized")
        for nm in (qname, dqname):
            block.add_var(ir.VarDesc(name=nm, shape=vd.shape,
                                     dtype=vd.dtype))
        block.add_var(ir.VarDesc(name=sname, shape=[1], dtype=vd.dtype))
        if qtype == "range_abs_max":
            # running-window scale state: persistable ring buffer + step
            # counter, updated in place through the state-output round-trip
            # (same convention as batch_norm's MeanOut/VarianceOut)
            scales_name = unique_name.generate(name + ".scales_window")
            iter_name = unique_name.generate(name + ".quant_iter")
            block.add_var(ir.VarDesc(name=scales_name,
                                     shape=[self.window_size],
                                     dtype=vd.dtype, persistable=True))
            block.add_var(ir.VarDesc(name=iter_name, shape=[1],
                                     dtype="int32", persistable=True))
            sb = self._startup.desc.global_block
            for nm, shape, dtype in ((scales_name, [self.window_size],
                                      vd.dtype), (iter_name, [1], "int32")):
                sb.add_var(ir.VarDesc(name=nm, shape=shape, dtype=dtype,
                                      persistable=True))
                sb.append_op(ir.OpDesc(
                    type="fill_constant", outputs={"Out": [nm]},
                    attrs={"shape": shape, "dtype": dtype, "value": 0.0}))
            new_ops.append(ir.OpDesc(
                type="fake_quantize_range_abs_max",
                inputs={"X": [name], "InScales": [scales_name],
                        "Iter": [iter_name]},
                outputs={"Out": [qname], "OutScale": [sname],
                         "OutScales": [scales_name],
                         "OutIter": [iter_name]},
                attrs={"bit_length": bits,
                       "window_size": self.window_size}))
        else:
            new_ops.append(ir.OpDesc(
                type="fake_quantize_abs_max",
                inputs={"X": [name]},
                outputs={"Out": [qname], "OutScale": [sname]},
                attrs={"bit_length": bits}))
        new_ops.append(ir.OpDesc(
            type="fake_dequantize_max_abs",
            inputs={"X": [qname], "Scale": [sname]},
            outputs={"Out": [dqname]},
            attrs={"max_range": float(2 ** (bits - 1) - 1)}))
        return dqname
