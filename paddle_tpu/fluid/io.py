"""Model/checkpoint I/O.

Capability parity with the reference (python/paddle/fluid/io.py —
save_vars/save_persistables :222,270, load_persistables :490,
save_inference_model :570, load_inference_model :704). The reference builds
save/load op programs executed by the C++ Executor (operators/save_op.cc);
TPU-native design: persistables live as device arrays in the Scope, saved
host-side as one .npy per var plus a JSON manifest (one-file-per-var matches
the reference's default layout), and the inference export serializes the
pruned ProgramDesc (ir.py JSON) — the analogue of the binary __model__ file.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

import numpy as np

from paddle_tpu.core import ir
from paddle_tpu.core.scope import global_scope
from paddle_tpu.fluid import framework

_MODEL_FILENAME = "__model__.json"
_MANIFEST = "__manifest__.json"


def _persistable_names(program) -> List[str]:
    names = []
    for vd in program.desc.global_block.vars.values():
        if vd.persistable:
            names.append(vd.name)
    return sorted(set(names))


def _write_snapshot_dir(dirname: str, snapshot) -> List[str]:
    """Serialize {name: ndarray} to dirname with the manifest — the single
    definition of the on-disk layout shared by save_vars and the async
    checkpointer (load_vars reads this layout back). Each file's CRC32 is
    recorded in the manifest and re-verified by load_vars, so a var file
    torn after the save looked complete fails loudly instead of loading
    garbage weights."""
    import time
    from paddle_tpu.fluid import sharded_io
    from paddle_tpu.fluid.sharded_io import _crc32_file
    from paddle_tpu.utils import faults
    t_start = time.perf_counter()
    os.makedirs(dirname, exist_ok=True)
    crcs = {}
    n_bytes = 0
    for name, arr in snapshot.items():
        path = os.path.join(dirname, name.replace("/", "__") + ".npy")
        faults.inject("ckpt.write_var")
        np.save(path, arr)
        crcs[name] = _crc32_file(path)
        faults.mutate_file("ckpt.write_var", path)   # tear post-checksum
        n_bytes += os.path.getsize(path)
    with open(os.path.join(dirname, _MANIFEST), "w") as f:
        json.dump({"vars": sorted(snapshot), "crc32": crcs}, f)
    sharded_io.CKPT_SAVE_BYTES.labels(layout="plain").inc(n_bytes)
    sharded_io.CKPT_SAVE_SECONDS.labels(layout="plain").observe(
        time.perf_counter() - t_start)
    return sorted(snapshot)


def save_vars(executor, dirname, main_program=None, vars: Optional[List[str]] = None,
              predicate=None, filename=None, scope=None, sharded=False):
    """reference: io.py:222 (scope: the fluid.scope_guard capability).

    ``sharded=True`` writes the per-shard layout (fluid.sharded_io): only
    this process's addressable shards, one file each — the multi-host-safe
    form (reference: the pserver checkpoints its own shard,
    go/pserver/service.go:47)."""
    main_program = main_program or framework.default_main_program()
    scope = scope or global_scope()
    if vars is None:
        vars = _persistable_names(main_program)
        if predicate is not None:
            vars = [v for v in vars
                    if predicate(main_program.global_block().var(v))]
    if sharded:
        if filename is not None:
            raise ValueError("sharded=True writes one file per shard; "
                             "the single-file `filename` form does not "
                             "apply")
        from paddle_tpu.fluid import sharded_io
        return sharded_io.save_sharded(
            dirname, sharded_io.snapshot_sharded(scope, vars))
    snapshot = {}
    for name in vars:
        val = scope.find_var(name)
        if val is not None:
            snapshot[name] = np.asarray(val)
    return _write_snapshot_dir(dirname, snapshot)


def save_persistables(executor, dirname, main_program=None, filename=None,
                      scope=None):
    """reference: io.py:270."""
    return save_vars(executor, dirname, main_program, filename=filename,
                     scope=scope)


def load_vars(executor, dirname, main_program=None,
              vars: Optional[List[str]] = None, predicate=None,
              filename=None, scope=None, sharding_fn=None):
    """reference: io.py load_vars. Auto-detects the sharded layout and
    reassembles it — under ``sharding_fn`` (e.g. the next mesh's
    CompiledBlock.param_sharding) each device shard is stitched from only
    the overlapping files (restore-with-resharding: save dp=4, restore
    dp=8/dp=1)."""
    scope = scope or global_scope()
    from paddle_tpu.fluid import sharded_io
    mpath = os.path.join(dirname, _MANIFEST)
    if not os.path.exists(mpath) and sharded_io.is_sharded_dir(dirname):
        return sharded_io.load_sharded(dirname, scope, vars=vars,
                                       sharding_fn=sharding_fn)
    crcs = {}
    if os.path.exists(mpath):
        with open(mpath) as f:
            mdata = json.load(f)
        crcs = mdata.get("crc32") or {}
        if vars is None:
            vars = mdata["vars"]
    elif vars is None:
        raise FileNotFoundError(f"no manifest at {mpath}")
    import time
    import jax
    t_start = time.perf_counter()
    loaded = []
    for name in vars:
        path = os.path.join(dirname, name.replace("/", "__") + ".npy")
        if not os.path.exists(path):
            raise FileNotFoundError(f"no saved tensor for var {name!r} at {path}")
        want = crcs.get(name)
        if want is not None:
            got = sharded_io._crc32_file(path)
            if got != want:
                sharded_io.CKPT_CRC_FAILURES.inc()
                raise sharded_io.ChecksumError(
                    f"var file {path} fails its manifest checksum "
                    f"(recorded {want:#010x}, file is {got:#010x}) — torn "
                    "or corrupt; restore from an older serial")
        val = np.load(path)
        target = sharding_fn(name) if sharding_fn is not None else None
        if target is not None:
            scope.set_var(name, jax.device_put(val, target))
        else:
            scope.set_var(name, jax.device_put(val))
        loaded.append(name)
    sharded_io.CKPT_RESTORE_SECONDS.labels(layout="plain").observe(
        time.perf_counter() - t_start)
    return loaded


def load_persistables(executor, dirname, main_program=None, filename=None,
                      scope=None):
    """reference: io.py:490."""
    return load_vars(executor, dirname, main_program, scope=scope)


def save_inference_model(dirname, feeded_var_names: List[str], target_vars,
                         executor, main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         scope=None):
    """reference: io.py:570 — prune to feed/fetch targets + serialize."""
    main_program = main_program or framework.default_main_program()
    os.makedirs(dirname, exist_ok=True)
    target_names = [v if isinstance(v, str) else v.name for v in target_vars]

    pruned_block = ir.prune_block(main_program.desc.global_block,
                                  target_names, feeded_var_names)
    pruned = ir.ProgramDesc()
    pruned.random_seed = main_program.desc.random_seed
    pruned.blocks = [pruned_block]

    with open(os.path.join(dirname, model_filename or _MODEL_FILENAME), "w") as f:
        json.dump({
            "program": pruned.to_dict(),
            "feed_names": list(feeded_var_names),
            "fetch_names": target_names,
        }, f)
    # save only params the pruned program references
    needed = [n for n, vd in pruned_block.vars.items() if vd.persistable]
    save_vars(executor, dirname, main_program, vars=needed, scope=scope)
    return target_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None, scope=None):
    """reference: io.py:704 — returns (program, feed_names, fetch_names)."""
    with open(os.path.join(dirname, model_filename or _MODEL_FILENAME)) as f:
        payload = json.load(f)
    desc = ir.ProgramDesc()
    restored = desc.parse_from_string(
        json.dumps(payload["program"]).encode())
    program = framework.Program()
    program.desc = restored
    program.blocks = [framework.Block(program, i)
                      for i in range(len(restored.blocks))]
    for b in program.blocks:
        for name, vd in b.desc.vars.items():
            b.vars[name] = framework.Variable(b, vd)
        b.ops = [framework.Operator(b, od) for od in b.desc.ops]
    program._is_test = True
    load_vars(executor, dirname,
              vars=[n for n, vd in restored.global_block.vars.items()
                    if vd.persistable], scope=scope)
    return program, payload["feed_names"], payload["fetch_names"]


# -- checkpointing (reference: io.py save_checkpoint/load_checkpoint era API
# + distributed checkpoint_notify capability, SURVEY §5) --------------------

def save_checkpoint(executor, checkpoint_dir, trainer_id=0,
                    main_program=None, step=None, max_num_checkpoints=3):
    main_program = main_program or framework.default_main_program()
    step = step if step is not None else _latest_step(checkpoint_dir) + 1
    d = os.path.join(checkpoint_dir, f"checkpoint_{step}")
    save_persistables(executor, d, main_program)
    # retention policy mirrors the reference's max_num_checkpoints
    steps = sorted(_all_steps(checkpoint_dir))
    for s in steps[:-max_num_checkpoints]:
        import shutil
        shutil.rmtree(os.path.join(checkpoint_dir, f"checkpoint_{s}"),
                      ignore_errors=True)
    return step


def load_checkpoint(executor, checkpoint_dir, serial=None, main_program=None):
    step = serial if serial is not None else _latest_step(checkpoint_dir)
    if step < 0:
        raise FileNotFoundError(f"no checkpoints under {checkpoint_dir}")
    d = os.path.join(checkpoint_dir, f"checkpoint_{step}")
    load_persistables(executor, d, main_program)
    return step


def _all_steps(checkpoint_dir):
    if not os.path.isdir(checkpoint_dir):
        return []
    out = []
    for name in os.listdir(checkpoint_dir):
        if name.startswith("checkpoint_"):
            try:
                out.append(int(name.split("_")[1]))
            except ValueError:
                pass
    return out


def _latest_step(checkpoint_dir):
    steps = _all_steps(checkpoint_dir)
    return max(steps) if steps else -1


class AsyncCheckpointer:
    """Async checkpoint writer (SURVEY §5 checkpoint/resume: "orbax-style
    sharded async save ... replaces (1)(3)"). `save()` snapshots device
    arrays to host (the only step that must pause training — one D2H per
    var) and hands serialization to a background thread; `wait()` joins.
    Keeps at most `max_to_keep` serials like the reference's checkpoint
    dir rotation (io.py save_checkpoint serial handling)."""

    def __init__(self, root_dir: str, max_to_keep: int = 3, sharded=True):
        import threading
        self.root = root_dir
        self.max_to_keep = max_to_keep
        self.sharded = sharded    # per-shard D2H + per-shard files; the
        # full-gather np.asarray path is kept only for sharded=False
        self._thread = None
        self._error = None
        self._threading = threading
        os.makedirs(root_dir, exist_ok=True)

    def _serial_dir(self, serial: int) -> str:
        return os.path.join(self.root, f"checkpoint_{serial}")

    def save(self, serial: int, main_program=None, scope=None,
             vars: Optional[List[str]] = None, on_complete=None):
        """Snapshot now, write in background. Returns immediately after
        the device→host copies. `on_complete` (if given) runs on the
        background thread after the _COMPLETE marker is durable — the hook
        for ordering dependent state (e.g. the elastic trainer's queue
        snapshot) behind the checkpoint without blocking training. A prior
        save's failure is raised here or in wait() — never swallowed."""
        self.wait()                       # one in-flight save at a time
        main_program = main_program or framework.default_main_program()
        scope = scope or global_scope()
        names = vars if vars is not None else _persistable_names(main_program)
        if self.sharded:
            from paddle_tpu.fluid import sharded_io
            # D2H copies only this process's addressable shards — bytes
            # owned, not model size (the reference pserver checkpoints its
            # own shard the same way, go/pserver/service.go:47)
            snap = sharded_io.snapshot_sharded(scope, names)
            writer = sharded_io.save_sharded
        else:
            snap = {}
            for name in names:
                v = scope.find_var(name)
                if v is not None:
                    snap[name] = np.asarray(v)  # full D2H gather per var
            writer = _write_snapshot_dir

        def _write(snapshot=snap, serial=serial, writer=writer,
                   on_complete=on_complete):
            try:
                d = self._serial_dir(serial)
                writer(d, snapshot)
                # mark complete LAST so partial dirs are never latest.
                # Multi-host sharded saves write PER-PROCESS markers
                # (_COMPLETE_p<i>): the serial counts as complete only
                # once every process's marker is present (serials()), so
                # one fast host can never make the dir look complete
                # while another host is still writing (or crashed).
                with open(os.path.join(d, self._marker_name()), "w") as f:
                    f.write(str(serial))
                if on_complete is not None:
                    on_complete()
                self._gc()
            except BaseException as e:   # surfaced by wait()/next save()
                self._error = e

        self._thread = self._threading.Thread(target=_write, daemon=True)
        self._thread.start()

    def wait(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("async checkpoint save failed") from err

    def _gc(self):
        serials = self.serials()
        for s in serials[:-self.max_to_keep]:
            import shutil
            shutil.rmtree(self._serial_dir(s), ignore_errors=True)

    def _marker_name(self) -> str:
        if self.sharded:
            import jax
            if jax.process_count() > 1:
                return f"_COMPLETE_p{jax.process_index()}"
        return "_COMPLETE"

    @staticmethod
    def _serial_complete(d: str) -> bool:
        """True iff every saving process finished this serial. Single
        -process saves use the legacy _COMPLETE file; multi-host sharded
        saves need one _COMPLETE_p<i> per process recorded in the shard
        manifests' process_count."""
        if os.path.exists(os.path.join(d, "_COMPLETE")):
            return True
        try:
            names = os.listdir(d)
        except OSError:
            return False
        markers = set()
        for n in names:
            if n.startswith("_COMPLETE_p"):
                suffix = n[len("_COMPLETE_p"):]
                if suffix.isdigit():   # ignore stray _COMPLETE_p0.bak etc.
                    markers.add(int(suffix))
        if not markers:
            return False
        from paddle_tpu.fluid import sharded_io
        want = sharded_io.recorded_process_count(d)
        return want is not None and markers >= set(range(want))

    def serials(self) -> List[int]:
        out = []
        if not os.path.isdir(self.root):
            return out
        for n in os.listdir(self.root):
            d = os.path.join(self.root, n)
            if n.startswith("checkpoint_") and self._serial_complete(d):
                out.append(int(n.split("_")[-1]))
        return sorted(out)

    def restore(self, executor=None, serial: Optional[int] = None,
                main_program=None, scope=None, sharding_fn=None) -> int:
        """Load the given (or latest complete) serial into the scope.
        ``sharding_fn`` restores directly into a (possibly different)
        mesh layout — save dp=4, restore dp=8.

        With no explicit ``serial``, a serial whose data turns out torn
        (a manifest CRC32 mismatch — sharded_io.ChecksumError — a missing
        manifest, or json/np parse errors from truncated files) is
        skipped and the next-older complete serial is tried — restore
        recovers automatically to the newest *verified* serial instead
        of dying on the newest dir."""
        self.wait()
        serials = self.serials()
        if not serials:
            raise FileNotFoundError(f"no complete checkpoints in {self.root}")
        if serial is not None:
            load_vars(executor, self._serial_dir(serial), main_program,
                      scope=scope, sharding_fn=sharding_fn)
            return serial
        last_err = None
        for s in reversed(serials):
            try:
                load_vars(executor, self._serial_dir(s), main_program,
                          scope=scope, sharding_fn=sharding_fn)
                return s
            except (OSError, ValueError) as e:
                # incomplete/torn serial (IOError from the manifest
                # completeness check, json/np parse errors from truncated
                # files) → fall back to the next-older serial
                last_err = e
        raise IOError(
            f"every complete-looking serial in {self.root} failed to "
            "load") from last_err


def _param_names(main_program):
    """Persistable vars that are actual Parameters (optimizer state like
    Adam moments is persistable but NOT a parameter)."""
    block = main_program.global_block()

    def is_param(v):
        return getattr(v, "is_parameter", False) or isinstance(
            v, framework.Parameter)

    return [n for n in _persistable_names(main_program)
            if block.has_var(n) and is_param(block.var(n))]


def save_params(executor, dirname, main_program=None, filename=None,
                scope=None):
    """reference: io.py save_params — parameters only (persistable
    non-parameter state like LR/step counters excluded)."""
    main_program = main_program or framework.default_main_program()
    return save_vars(executor, dirname, main_program,
                     vars=_param_names(main_program),
                     filename=filename, scope=scope)


def load_params(executor, dirname, main_program=None, filename=None,
                scope=None):
    """reference: io.py load_params."""
    main_program = main_program or framework.default_main_program()
    return load_vars(executor, dirname, main_program,
                     vars=_param_names(main_program), scope=scope)
