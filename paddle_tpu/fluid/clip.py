"""Gradient clipping (reference: python/paddle/fluid/clip.py —
GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm,
ErrorClipByValue; set_gradient_clip + append_gradient_clip_ops)."""

from __future__ import annotations

from typing import List, Optional, Tuple

from paddle_tpu.fluid import framework

_global_clip = None


class BaseGradientClipAttr:
    def _append_clip_op(self, block, grad):
        raise NotImplementedError


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def _append_clip_op(self, block, grad):
        out = block.create_var(shape=grad.shape, dtype=grad.dtype,
                               stop_gradient=True)
        block.append_op("clip", inputs={"X": [grad]}, outputs={"Out": [out]},
                        attrs={"min": self.min, "max": self.max})
        return out


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _append_clip_op(self, block, grad):
        out = block.create_var(shape=grad.shape, dtype=grad.dtype,
                               stop_gradient=True)
        block.append_op("clip_by_norm", inputs={"X": [grad]},
                        outputs={"Out": [out]},
                        attrs={"max_norm": self.clip_norm})
        return out


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """reference: clip.py GradientClipByGlobalNorm — scale all grads by
    clip_norm / max(global_norm, clip_norm). Built here as IR ops so it
    fuses into the step program."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _append_global_clip_ops(self, params_grads):
        if not params_grads:
            return params_grads
        block = params_grads[0][0].block
        sq_norms = []
        for _, g in params_grads:
            sq = block.create_var(shape=[], dtype=g.dtype, stop_gradient=True)
            block.append_op("squared_l2_norm", inputs={"X": [g]},
                            outputs={"Out": [sq]})
            sq_norms.append(sq)
        total = block.create_var(shape=[], dtype="float32", stop_gradient=True)
        block.append_op("sum", inputs={"X": sq_norms}, outputs={"Out": [total]})
        gnorm = block.create_var(shape=[], dtype="float32", stop_gradient=True)
        block.append_op("sqrt", inputs={"X": [total]}, outputs={"Out": [gnorm]})
        clipped = []
        for p, g in params_grads:
            out = block.create_var(shape=g.shape, dtype=g.dtype,
                                   stop_gradient=True)
            block.append_op("global_norm_clip_apply",
                            inputs={"X": [g], "GlobalNorm": [gnorm]},
                            outputs={"Out": [out]},
                            attrs={"clip_norm": self.clip_norm})
            clipped.append((p, out))
        return clipped


def set_gradient_clip(clip, param_list=None, program=None):
    global _global_clip
    _global_clip = clip


def append_gradient_clip_ops(params_grads):
    """reference: clip.py append_gradient_clip_ops."""
    if _global_clip is not None and isinstance(_global_clip,
                                               GradientClipByGlobalNorm):
        return _global_clip._append_global_clip_ops(params_grads)
    out = []
    for p, g in params_grads:
        clip = getattr(p, "gradient_clip_attr", None) or _global_clip
        if clip is None:
            out.append((p, g))
        else:
            out.append((p, clip._append_clip_op(p.block, g)))
    return out


class ErrorClipByValue:
    """Accepted for parity (reference: clip.py ErrorClipByValue); applied to
    @GRAD vars when set on a param's error_clip."""

    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)
