"""Optimizers (reference: python/paddle/fluid/optimizer.py — base Optimizer
:44, minimize :357 = backward + apply_gradients :286,318; 12 optimizer
classes :407-1467).

Each optimizer appends its update op(s) per (param, grad) pair; accumulators
(velocity, moments, beta powers) are persistable vars initialized in the
startup program. Because the whole train step compiles to one XLA program,
the optimizer ops fuse with the backward pass — the reference dispatches
each as a separate kernel (operators/optimizers/*.cc).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from paddle_tpu.fluid import framework, unique_name
from paddle_tpu.fluid.backward import append_backward
from paddle_tpu.fluid.initializer import ConstantInitializer
from paddle_tpu.fluid.regularizer import append_regularization_ops


class Optimizer:
    """reference: optimizer.py:44."""

    def __init__(self, learning_rate, regularization=None, name=None):
        self.regularization = regularization
        self._name = name
        self._learning_rate = learning_rate
        self._lr_var = None
        self._accumulators: Dict[str, Dict[str, framework.Variable]] = {}
        self.helper_type = type(self).__name__

    # -- learning rate -----------------------------------------------------
    def _param_lr(self, param):
        """reference: optimizer.py _create_param_lr — per-param learning
        rate. append_LARS stores a decayed-lr VARIABLE (which already
        folds in the global lr) in param.optimize_attr; a float scales
        the global lr; 1.0 is the global lr unchanged."""
        plr = getattr(param, "optimize_attr", None)
        plr = (plr or {}).get("learning_rate", 1.0)
        if isinstance(plr, framework.Variable):
            return plr
        if isinstance(plr, (int, float)) and float(plr) == 1.0:
            return self._lr_var
        from paddle_tpu.fluid.layer_helper import LayerHelper
        helper = LayerHelper("param_lr")
        out = helper.create_variable_for_type_inference("float32")
        helper.append_op("scale", inputs={"X": [self._lr_var]},
                         outputs={"Out": [out]},
                         attrs={"scale": float(plr)})
        return out

    def _create_lr_var(self):
        if self._lr_var is not None:
            return self._lr_var
        if isinstance(self._learning_rate, framework.Variable):
            self._lr_var = self._learning_rate
            return self._lr_var
        main = framework.default_main_program()
        startup = framework.default_startup_program()
        name = unique_name.generate("learning_rate")
        self._lr_var = main.global_block().create_var(
            name=name, shape=[1], dtype="float32", persistable=True,
            stop_gradient=True)
        sv = startup.global_block().create_var(
            name=name, shape=[1], dtype="float32", persistable=True)
        ConstantInitializer(float(self._learning_rate))(
            sv, startup.global_block())
        return self._lr_var

    def _global_learning_rate(self):
        return self._create_lr_var()

    # -- accumulators (reference: optimizer.py _add_accumulator) ----------
    def _add_accumulator(self, name: str, param: framework.Variable,
                         fill_value: float = 0.0, shape=None,
                         dtype=None) -> framework.Variable:
        acc_map = self._accumulators.setdefault(name, {})
        if param.name in acc_map:
            return acc_map[param.name]
        main = framework.default_main_program()
        startup = framework.default_startup_program()
        shape = list(shape if shape is not None else param.shape)
        dtype = dtype or param.dtype
        var_name = unique_name.generate(f"{param.name}_{name}")
        v = main.global_block().create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True,
            stop_gradient=True)
        sv = startup.global_block().create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True)
        ConstantInitializer(fill_value)(sv, startup.global_block())
        acc_map[param.name] = v
        return v

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- to be overridden --------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    # -- public API --------------------------------------------------------
    def apply_gradients(self, params_grads):
        """reference: optimizer.py:318."""
        main = framework.default_main_program()
        block = main.global_block()
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        from paddle_tpu.fluid import clip as clip_mod
        params_grads = clip_mod.append_gradient_clip_ops(params_grads)
        self._create_lr_var()
        self._create_accumulators(block, [p for p, _ in params_grads])
        ops = []
        for pg in params_grads:
            ops.append(self._append_optimize_op(block, pg))
        return ops

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return append_backward(loss, parameter_list, no_grad_set)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        """reference: optimizer.py:357."""
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        if not params_grads:
            raise RuntimeError("no trainable parameters reach the loss")
        opt_ops = self.apply_gradients(params_grads)
        return opt_ops, params_grads


class SGDOptimizer(Optimizer):
    """reference: optimizer.py SGDOptimizer → sgd_op.cc."""

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "sgd",
            inputs={"Param": [p], "Grad": [g],
                    "LearningRate": [self._param_lr(p)]},
            outputs={"ParamOut": [p]})


class MomentumOptimizer(Optimizer):
    """reference: optimizer.py MomentumOptimizer → momentum_op.cc."""

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            "momentum",
            inputs={"Param": [p], "Grad": [g], "Velocity": [v],
                    "LearningRate": [self._param_lr(p)]},
            outputs={"ParamOut": [p], "VelocityOut": [v]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov})


class LarsMomentumOptimizer(Optimizer):
    """reference: optimizer.py LarsMomentumOptimizer → lars_momentum_op.cc."""

    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            "lars_momentum",
            inputs={"Param": [p], "Grad": [g], "Velocity": [v],
                    "LearningRate": [self._param_lr(p)]},
            outputs={"ParamOut": [p], "VelocityOut": [v]},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay})


class AdamOptimizer(Optimizer):
    """reference: optimizer.py AdamOptimizer → adam_op.h."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        # lazy_mode rides to the adam op: with a row-sparse gradient
        # (core/selected_rows.py) only touched rows update their moments/
        # param (adam_op.h lazy_mode semantics — untouched rows' moments
        # don't decay); with a dense gradient it is a no-op, like the
        # reference
        self._lazy_mode = bool(lazy_mode)

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=[1])
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        return block.append_op(
            "adam",
            inputs={"Param": [p], "Grad": [g], "Moment1": [m1],
                    "Moment2": [m2], "Beta1Pow": [b1p], "Beta2Pow": [b2p],
                    "LearningRate": [self._param_lr(p)]},
            outputs={"ParamOut": [p], "Moment1Out": [m1], "Moment2Out": [m2],
                     "Beta1PowOut": [b1p], "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "lazy_mode": self._lazy_mode})


class AdamaxOptimizer(Optimizer):
    """reference: optimizer.py AdamaxOptimizer → adamax_op.cc."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "adamax",
            inputs={"Param": [p], "Grad": [g],
                    "Moment": [self._get_accumulator("moment", p)],
                    "InfNorm": [self._get_accumulator("inf_norm", p)],
                    "Beta1Pow": [self._get_accumulator("beta1_pow_acc", p)],
                    "LearningRate": [self._param_lr(p)]},
            outputs={"ParamOut": [p],
                     "MomentOut": [self._get_accumulator("moment", p)],
                     "InfNormOut": [self._get_accumulator("inf_norm", p)]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})


class AdagradOptimizer(Optimizer):
    """reference: optimizer.py AdagradOptimizer → adagrad_op.cc."""

    def __init__(self, learning_rate, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        mom = self._get_accumulator("moment", p)
        return block.append_op(
            "adagrad",
            inputs={"Param": [p], "Grad": [g], "Moment": [mom],
                    "LearningRate": [self._param_lr(p)]},
            outputs={"ParamOut": [p], "MomentOut": [mom]},
            attrs={"epsilon": self._epsilon})


class DecayedAdagradOptimizer(Optimizer):
    """reference: optimizer.py DecayedAdagradOptimizer."""

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        mom = self._get_accumulator("moment", p)
        return block.append_op(
            "decayed_adagrad",
            inputs={"Param": [p], "Grad": [g], "Moment": [mom],
                    "LearningRate": [self._param_lr(p)]},
            outputs={"ParamOut": [p], "MomentOut": [mom]},
            attrs={"decay": self._decay, "epsilon": self._epsilon})


class AdadeltaOptimizer(Optimizer):
    """reference: optimizer.py AdadeltaOptimizer → adadelta_op.cc."""

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        asg = self._get_accumulator("avg_squared_grad", p)
        asu = self._get_accumulator("avg_squared_update", p)
        return block.append_op(
            "adadelta",
            inputs={"Param": [p], "Grad": [g], "AvgSquaredGrad": [asg],
                    "AvgSquaredUpdate": [asu]},
            outputs={"ParamOut": [p], "AvgSquaredGradOut": [asg],
                     "AvgSquaredUpdateOut": [asu]},
            attrs={"epsilon": self._epsilon, "rho": self._rho})


class RMSPropOptimizer(Optimizer):
    """reference: optimizer.py RMSPropOptimizer → rmsprop_op.cc."""

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("momentum", p)
            if self._centered:
                self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        ms = self._get_accumulator("mean_square", p)
        mom = self._get_accumulator("momentum", p)
        ins = {"Param": [p], "Grad": [g], "MeanSquare": [ms], "Moment": [mom],
               "LearningRate": [self._param_lr(p)]}
        outs = {"ParamOut": [p], "MeanSquareOut": [ms], "MomentOut": [mom]}
        if self._centered:
            mg = self._get_accumulator("mean_grad", p)
            ins["MeanGrad"] = [mg]
            outs["MeanGradOut"] = [mg]
        return block.append_op(
            "rmsprop", inputs=ins, outputs=outs,
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered})


class FtrlOptimizer(Optimizer):
    """reference: optimizer.py FtrlOptimizer → ftrl_op.cc."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        sq = self._get_accumulator("squared", p)
        lin = self._get_accumulator("linear", p)
        return block.append_op(
            "ftrl",
            inputs={"Param": [p], "Grad": [g], "SquaredAccumulator": [sq],
                    "LinearAccumulator": [lin],
                    "LearningRate": [self._param_lr(p)]},
            outputs={"ParamOut": [p], "SquaredAccumOut": [sq],
                     "LinearAccumOut": [lin]},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power})


class ModelAverage(Optimizer):
    """reference: optimizer.py ModelAverage — keeps an EMA copy of params;
    TPU-native form: a single fused ema_accumulate op per param, applied as
    a post-step program."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, **kw):
        super().__init__(learning_rate=0.0, **kw)
        decay = 1.0 - average_window_rate
        self._decay = min(max(decay, 0.0), 0.9999)

    def apply_ema(self, params):
        main = framework.default_main_program()
        block = main.global_block()
        ops = []
        for p in params:
            ema = self._add_accumulator("ema", p)
            ops.append(block.append_op(
                "ema_accumulate", inputs={"Param": [p], "Ema": [ema]},
                outputs={"EmaOut": [ema]}, attrs={"decay": self._decay}))
        return ops


# fluid-style aliases (reference: optimizer.py bottom-of-file aliases)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
Adagrad = AdagradOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
LarsMomentum = LarsMomentumOptimizer
