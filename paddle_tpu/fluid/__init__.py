"""paddle_tpu.fluid — the user-facing API namespace, mirroring the
reference's `paddle.fluid` (python/paddle/fluid/__init__.py) so a reference
user finds the same entry points: Executor, Program/program_guard, layers,
optimizer, initializer, ParamAttr, nets, backward, io, metrics, profiler."""

from paddle_tpu.core.executor import (CPUPlace, CUDAPlace, EOFException,
                                      Executor, TPUPlace)
from paddle_tpu.core.scope import Scope, global_scope
from paddle_tpu import core  # fluid.core.EOFException, reference spelling
from paddle_tpu.fluid import backward, clip, initializer, layers, nets
from paddle_tpu.fluid import optimizer, param_attr, regularizer, unique_name
from paddle_tpu.fluid import (io, learning_rate_scheduler, metrics,
                              profiler)
from paddle_tpu.fluid import evaluator
from paddle_tpu.fluid.batch_merge import apply_batch_merge
from paddle_tpu.fluid.data_feeder import DataFeeder
from paddle_tpu.fluid.framework import (Program, default_main_program,
                                        default_startup_program,
                                        program_guard)
from paddle_tpu.fluid.param_attr import ParamAttr, WeightNormParamAttr
from paddle_tpu.fluid.lod_tensor import (LoDTensor, create_lod_tensor,
                                         create_random_int_lodtensor)
from paddle_tpu.fluid.compiler import (BuildStrategy, CompiledProgram,
                                       ExecutionStrategy)
from paddle_tpu.fluid.parallel_executor import ParallelExecutor
from paddle_tpu.data.datafeed import AsyncExecutor, DataFeedDesc
from paddle_tpu.fluid import transpiler
from paddle_tpu.fluid.transpiler import (DistributeTranspiler,
                                         DistributeTranspilerConfig,
                                         memory_optimize, release_memory)

__all__ = [
    "CPUPlace", "CUDAPlace", "Executor", "TPUPlace",
    "Scope", "global_scope",
    "backward", "clip", "initializer", "layers", "nets", "optimizer",
    "param_attr", "regularizer", "unique_name",
    "Program", "default_main_program", "default_startup_program",
    "program_guard", "ParamAttr",
    "BuildStrategy", "CompiledProgram", "ExecutionStrategy",
    "io", "learning_rate_scheduler", "metrics", "profiler", "DataFeeder",
    "ParallelExecutor", "memory_optimize", "release_memory",
    "transpiler", "DistributeTranspiler", "DistributeTranspilerConfig",
    "AsyncExecutor", "DataFeedDesc",
]

from paddle_tpu.fluid import debugger  # noqa: F401,E402

import contextlib as _contextlib  # noqa: E402


@_contextlib.contextmanager
def scope_guard(scope):
    """reference: executor.py scope_guard — run exe.run against `scope`
    as the global scope."""
    from paddle_tpu.core.scope import _switch_scope
    old = _switch_scope(scope)
    try:
        yield
    finally:
        _switch_scope(old)


from paddle_tpu.fluid.framework import name_scope  # noqa: F401,E402

__all__ += ["scope_guard", "name_scope", "WeightNormParamAttr",
            "LoDTensor", "create_lod_tensor",
            "create_random_int_lodtensor", "EOFException"]
