"""Learning-rate decay schedules (reference: python/paddle/fluid/
learning_rate_scheduler.py — noam_decay, exponential_decay,
natural_exp_decay, inverse_time_decay, polynomial_decay, piecewise_decay,
cosine_decay — each a subgraph over a global step counter).

The step counter is a persistable [1] var incremented inside the compiled
train step, so the whole schedule fuses into the step executable (the
reference appends the same ops interpreted per step)."""

from __future__ import annotations

import math

from paddle_tpu.fluid import framework, unique_name
from paddle_tpu.fluid.initializer import ConstantInitializer
from paddle_tpu.fluid.layer_helper import LayerHelper


def _global_step_var():
    """Create (once) the auto-incremented global step counter
    (reference: _decay_step_counter in learning_rate_scheduler.py)."""
    main = framework.default_main_program()
    startup = framework.default_startup_program()
    name = "@lr_decay_counter@"
    gblock = main.global_block()
    if gblock.has_var(name):
        return gblock.var(name)
    step = gblock.create_var(name=name, shape=[1], dtype="float32",
                             persistable=True, stop_gradient=True)
    sv = startup.global_block().create_var(name=name, shape=[1],
                                           dtype="float32", persistable=True)
    ConstantInitializer(0.0)(sv, startup.global_block())
    gblock.append_op("increment", inputs={"X": [step]},
                     outputs={"Out": [step]}, attrs={"step": 1.0})
    return step


def _tmp(helper, dtype="float32"):
    return helper.create_variable_for_type_inference(dtype)


def _op(helper, op_type, ins, attrs=None):
    out = _tmp(helper)
    helper.append_op(op_type, inputs=ins, outputs={"Out": [out]},
                     attrs=attrs or {})
    return out


def _const(helper, value):
    out = _tmp(helper)
    helper.append_op("fill_constant", outputs={"Out": [out]},
                     attrs={"shape": [1], "dtype": "float32",
                            "value": float(value)})
    return out


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    """reference: learning_rate_scheduler.py noam_decay —
    lr = d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)."""
    helper = LayerHelper("noam_decay")
    step = _global_step_var()
    a = _op(helper, "pow", {"X": [step]}, {"factor": -0.5})
    b = _op(helper, "scale", {"X": [step]},
            {"scale": warmup_steps ** -1.5})
    m = _op(helper, "elementwise_min", {"X": [a], "Y": [b]})
    return _op(helper, "scale", {"X": [m]},
               {"scale": learning_rate * d_model ** -0.5})


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """lr * decay_rate^(step/decay_steps)."""
    helper = LayerHelper("exponential_decay")
    step = _global_step_var()
    div = _op(helper, "scale", {"X": [step]}, {"scale": 1.0 / decay_steps})
    if staircase:
        div = _op(helper, "floor", {"X": [div]})
    rate = _const(helper, decay_rate)
    powed = _op(helper, "elementwise_pow", {"X": [rate], "Y": [div]})
    return _op(helper, "scale", {"X": [powed]}, {"scale": learning_rate})


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """lr * exp(-decay_rate * step/decay_steps)."""
    helper = LayerHelper("natural_exp_decay")
    step = _global_step_var()
    div = _op(helper, "scale", {"X": [step]}, {"scale": 1.0 / decay_steps})
    if staircase:
        div = _op(helper, "floor", {"X": [div]})
    e = _op(helper, "scale", {"X": [div]}, {"scale": -decay_rate})
    powed = _op(helper, "exp", {"X": [e]})
    return _op(helper, "scale", {"X": [powed]}, {"scale": learning_rate})


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    """lr / (1 + decay_rate * step/decay_steps)."""
    helper = LayerHelper("inverse_time_decay")
    step = _global_step_var()
    div = _op(helper, "scale", {"X": [step]}, {"scale": 1.0 / decay_steps})
    if staircase:
        div = _op(helper, "floor", {"X": [div]})
    denom = _op(helper, "scale", {"X": [div]},
                {"scale": decay_rate, "bias": 1.0})
    recip = _op(helper, "reciprocal", {"X": [denom]})
    return _op(helper, "scale", {"X": [recip]}, {"scale": learning_rate})


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    helper = LayerHelper("polynomial_decay")
    step = _global_step_var()
    if cycle:
        div = _op(helper, "scale", {"X": [step]},
                  {"scale": 1.0 / decay_steps})
        ceiled = _op(helper, "ceil", {"X": [div]})
        one = _const(helper, 1.0)
        mult = _op(helper, "elementwise_max", {"X": [ceiled], "Y": [one]})
        total = _op(helper, "scale", {"X": [mult]}, {"scale": decay_steps})
    else:
        total = _const(helper, decay_steps)
        step = _op(helper, "elementwise_min", {"X": [step], "Y": [total]})
    frac = _op(helper, "elementwise_div", {"X": [step], "Y": [total]})
    one = _const(helper, 1.0)
    rem = _op(helper, "elementwise_sub", {"X": [one], "Y": [frac]})
    powed = _op(helper, "pow", {"X": [rem]}, {"factor": power})
    scaled = _op(helper, "scale", {"X": [powed]},
                 {"scale": learning_rate - end_learning_rate})
    return _op(helper, "scale", {"X": [scaled]},
               {"scale": 1.0, "bias": end_learning_rate})


def piecewise_decay(boundaries, values):
    """values[i] while step < boundaries[i] (reference builds this with
    control-flow ops; here a fused select chain)."""
    assert len(values) == len(boundaries) + 1
    helper = LayerHelper("piecewise_decay")
    step = _global_step_var()
    lr = _const(helper, values[-1])
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        bound = _const(helper, float(b))
        cond = _op(helper, "less_than", {"X": [step], "Y": [bound]})
        val = _const(helper, v)
        lr = _op(helper, "select",
                 {"Condition": [cond], "X": [val], "Y": [lr]})
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    """lr/2 * (cos(pi * epoch/epochs) + 1)."""
    helper = LayerHelper("cosine_decay")
    step = _global_step_var()
    epoch = _op(helper, "scale", {"X": [step]},
                {"scale": 1.0 / step_each_epoch})
    epoch = _op(helper, "floor", {"X": [epoch]})
    ang = _op(helper, "scale", {"X": [epoch]}, {"scale": math.pi / epochs})
    c = _op(helper, "cos", {"X": [ang]})
    half = _op(helper, "scale", {"X": [c]},
               {"scale": 0.5, "bias": 0.5})
    return _op(helper, "scale", {"X": [half]}, {"scale": learning_rate})


def append_LARS(params_grads, learning_rate, weight_decay):
    """reference: layers/learning_rate_scheduler.py:310 — layer-wise
    adaptive rate scaling: per-param decayed lr =
    lr * ||w|| / (||g|| + wd * ||w||), written into the param's
    optimize_attr; the optimizer's _param_lr feeds that Variable to the
    update op in place of the global lr (reference _create_param_lr).
    The LARS-momentum optimizer (ops/optimizer_ops.py lars_momentum) is
    the fused form."""
    helper = LayerHelper("lars")

    def _norm(v):
        sq = _op(helper, "square", {"X": [v]})
        s = _op(helper, "reduce_sum", {"X": [sq]}, {"reduce_all": True})
        return _op(helper, "sqrt", {"X": [s]})

    for param, grad in params_grads:
        param_norm = _norm(param)
        grad_norm = _norm(grad)
        if weight_decay == 1.0:
            denom = _op(helper, "elementwise_add",
                        {"X": [grad_norm], "Y": [param_norm]})
        else:
            scaled = _op(helper, "scale", {"X": [param_norm]},
                         {"scale": float(weight_decay)})
            denom = _op(helper, "elementwise_add",
                        {"X": [grad_norm], "Y": [scaled]})
        num = _op(helper, "elementwise_mul",
                  {"X": [learning_rate], "Y": [param_norm]})
        decayed = _op(helper, "elementwise_div",
                      {"X": [num], "Y": [denom]})
        param_lr = param.optimize_attr.get("learning_rate", 1.0)
        if not (isinstance(param_lr, float) and param_lr == 1.0):
            decayed = _op(helper, "scale", {"X": [decayed]},
                          {"scale": float(param_lr)})
        param.optimize_attr["learning_rate"] = decayed
