"""Unique name generator (reference: python/paddle/fluid/unique_name.py —
per-prefix counters with a guard() context that isolates name scopes, used
by every layer to name parameters/temporaries)."""

from __future__ import annotations

import contextlib
from collections import defaultdict


class NameGenerator:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self.ids = defaultdict(int)

    def __call__(self, key: str) -> str:
        i = self.ids[key]
        self.ids[key] += 1
        return "_".join(x for x in (self.prefix, key, str(i)) if x != "")


_generator = NameGenerator()


def generate(key: str) -> str:
    return _generator(key)


@contextlib.contextmanager
def guard(new_prefix: str = ""):
    global _generator
    old = _generator
    _generator = NameGenerator(new_prefix)
    try:
        yield
    finally:
        _generator = old


def switch(new_generator=None):
    """reference: unique_name.py switch — swap the global generator,
    returning the previous one (guard() composes this)."""
    global _generator
    old = _generator
    _generator = new_generator or NameGenerator()
    return old
