"""Python-facing autodiff entry (reference: python/paddle/fluid/backward.py:394
append_backward). The heavy lifting is the IR-level reverse walk in
paddle_tpu.ops.grad_ops.append_backward_desc; this wrapper resolves
Parameters and returns (param, grad) Variable pairs for the optimizer."""

from __future__ import annotations

from typing import List, Optional, Tuple

from paddle_tpu.fluid import framework
from paddle_tpu.ops.grad_ops import append_backward_desc


def append_backward(loss, parameter_list: Optional[List[str]] = None,
                    no_grad_set=None, callbacks=None
                    ) -> List[Tuple[framework.Variable, framework.Variable]]:
    program = loss.block.program
    block = program.desc.global_block
    grad_map = append_backward_desc(block, loss.name, no_grad_set)
    program.desc.bump_version()

    gblock = program.global_block()
    params_grads = []
    for p in gblock.all_parameters():
        if not getattr(p, "trainable", True):
            continue
        if parameter_list is not None and p.name not in parameter_list:
            continue
        gname = grad_map.get(p.name)
        if gname:
            params_grads.append((p, gblock.var(gname)))
    return params_grads
