"""Python-side streaming metrics (reference: python/paddle/fluid/metrics.py
— MetricBase, CompositeMetric, Precision, Recall, Accuracy, Auc,
EditDistance; host accumulators updated from fetched numpy values)."""

from __future__ import annotations

import numpy as np


class MetricBase:
    def __init__(self, name=None):
        self._name = name or type(self).__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    """reference: metrics.py Accuracy — weighted running mean of batch
    accuracies (pairs with layers.accuracy fetches)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: no updates yet")
        return self.value / self.weight


class Precision(MetricBase):
    """reference: metrics.py Precision (binary)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    """reference: metrics.py Recall (binary)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(MetricBase):
    """reference: metrics.py Auc — trapezoidal AUC over threshold buckets."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds + 1)
        self._stat_neg = np.zeros(self._num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, -1] if preds.ndim == 2 else preds.reshape(-1)
        bucket = np.clip((pos_prob * self._num_thresholds).astype(int), 0,
                         self._num_thresholds)
        for b, l in zip(bucket, labels):
            if l > 0:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def eval(self):
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tot_pos, tot_neg = tp[-1], fp[-1]
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        tp_prev = np.concatenate([[0.0], tp[:-1]])
        fp_prev = np.concatenate([[0.0], fp[:-1]])
        area = np.sum((fp - fp_prev) * (tp + tp_prev) / 2.0)
        return float(area / (tot_pos * tot_neg))


class EditDistance(MetricBase):
    """reference: metrics.py EditDistance — running mean distance +
    sequence-error rate."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num=None):
        distances = np.asarray(distances).reshape(-1)
        self.total_distance += float(distances.sum())
        self.seq_num += len(distances)
        self.instance_error += int(np.sum(distances > 0))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance: no updates yet")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class ChunkEvaluator(MetricBase):
    """Accumulate layers.chunk_eval's per-batch chunk counts and compute
    precision/recall/F1 over the whole pass (reference: metrics.py:359 —
    update() takes the three NumChunks outputs of chunk_eval)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        for name, v in (("num_infer_chunks", num_infer_chunks),
                        ("num_label_chunks", num_label_chunks),
                        ("num_correct_chunks", num_correct_chunks)):
            if not isinstance(v, (int, float, np.integer, np.floating,
                                  np.ndarray)):
                raise ValueError(
                    f"ChunkEvaluator.update: {name} must be a number or "
                    f"numpy array, got {type(v).__name__}")
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).sum())
        self.num_label_chunks += int(np.asarray(num_label_chunks).sum())
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).sum())

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class DetectionMAP(MetricBase):
    """Running mean of per-batch detection mAP values (reference:
    metrics.py:566 accumulates the detection_map evaluator's output;
    the in-graph accumulating variant is fluid.evaluator.DetectionMAP)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self._sum = 0.0
        self._n = 0

    def update(self, value, weight=1):
        v = np.asarray(value, dtype=np.float64).reshape(-1)
        w = np.asarray(weight, dtype=np.float64).reshape(-1)
        self._sum += float((v * w).sum())
        self._n += float(w.sum())

    def eval(self):
        if self._n == 0:
            raise ValueError("DetectionMAP: no updates yet")
        return self._sum / self._n
