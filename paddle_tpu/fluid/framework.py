"""Python graph builder: Program / Block / Variable / Parameter.

Capability parity with the reference's python mirrors of the proto IR
(reference: python/paddle/fluid/framework.py — Variable :232, Operator :546,
Block :992, Program :1510; two-program convention; Program.clone :1711;
program_guard). These wrappers mutate the paddle_tpu.core.ir descs directly;
shape inference happens once at append_op time by abstract evaluation of the
op's JAX emitter (replacing the reference's C++ InferShape calls).
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional

import numpy as np

from paddle_tpu.core import ir
from paddle_tpu.core.shape_inference import abstract_eval_op
from paddle_tpu.fluid import unique_name


class Variable:
    """reference: framework.py:232 — a symbolic tensor in a Block."""

    def __init__(self, block: "Block", desc: ir.VarDesc):
        self.block = block
        self.desc = desc

    # -- properties mirrored from the reference API ------------------------
    @property
    def name(self) -> str:
        return self.desc.name

    @property
    def shape(self):
        return tuple(self.desc.shape) if self.desc.shape is not None else None

    @property
    def dtype(self) -> str:
        return self.desc.dtype

    @property
    def lod_level(self) -> int:
        return self.desc.lod_level

    @property
    def persistable(self) -> bool:
        return self.desc.persistable

    @persistable.setter
    def persistable(self, v: bool):
        self.desc.persistable = v

    @property
    def stop_gradient(self) -> bool:
        return self.desc.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v: bool):
        self.desc.stop_gradient = v

    def __repr__(self):
        return (f"Variable(name={self.name!r}, shape={self.shape}, "
                f"dtype={self.dtype}, persistable={self.persistable})")

    # numpy-style sugar on symbolic vars lowers to ops
    def __add__(self, other):
        from paddle_tpu.fluid.layers import elementwise_add
        return elementwise_add(self, _to_variable(other, self))

    def __sub__(self, other):
        from paddle_tpu.fluid.layers import elementwise_sub
        return elementwise_sub(self, _to_variable(other, self))

    def __mul__(self, other):
        from paddle_tpu.fluid.layers import elementwise_mul
        return elementwise_mul(self, _to_variable(other, self))

    def __truediv__(self, other):
        from paddle_tpu.fluid.layers import elementwise_div
        return elementwise_div(self, _to_variable(other, self))


def _to_variable(x, like: Variable) -> Variable:
    if isinstance(x, Variable):
        return x
    from paddle_tpu.fluid.layers import fill_constant
    return fill_constant(shape=[1], dtype=like.dtype, value=float(x))


class Parameter(Variable):
    """reference: framework.py Parameter — a persistable trainable var with
    optimizer/regularizer attributes."""

    def __init__(self, block, desc, trainable=True, optimize_attr=None,
                 regularizer=None, gradient_clip_attr=None, do_model_average=False):
        super().__init__(block, desc)
        self.trainable = trainable
        self.optimize_attr = optimize_attr or {"learning_rate": 1.0}
        self.regularizer = regularizer
        self.gradient_clip_attr = gradient_clip_attr
        self.do_model_average = do_model_average
        desc.is_parameter = True
        desc.persistable = True
        desc.stop_gradient = False


class Operator:
    """reference: framework.py:546 — thin wrapper over an OpDesc."""

    def __init__(self, block: "Block", desc: ir.OpDesc):
        self.block = block
        self.desc = desc

    @property
    def type(self) -> str:
        return self.desc.type

    def input(self, slot):
        return self.desc.input(slot)

    def output(self, slot):
        return self.desc.output(slot)

    @property
    def attrs(self):
        return self.desc.attrs


class Block:
    """reference: framework.py:992."""

    def __init__(self, program: "Program", idx: int):
        self.program = program
        self.idx = idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def desc(self) -> ir.BlockDesc:
        return self.program.desc.block(self.idx)

    # -- var management ----------------------------------------------------
    def create_var(self, name: Optional[str] = None, shape=None, dtype="float32",
                   lod_level: int = 0, persistable: bool = False,
                   stop_gradient: bool = False,
                   type: ir.VarType = ir.VarType.LOD_TENSOR) -> Variable:
        if name is None:
            name = unique_name.generate("_generated_var")
        desc = ir.VarDesc(name=name, type=type,
                          shape=list(shape) if shape is not None else None,
                          dtype=dtype, lod_level=lod_level,
                          persistable=persistable, stop_gradient=stop_gradient)
        self.desc.add_var(desc)
        v = Variable(self, desc)
        self.vars[name] = v
        self.program.desc.bump_version()
        return v

    def create_parameter(self, name: str, shape, dtype="float32",
                         **kwargs) -> Parameter:
        desc = ir.VarDesc(name=name, shape=list(shape), dtype=dtype,
                          persistable=True)
        self.desc.add_var(desc)
        p = Parameter(self, desc, **kwargs)
        self.vars[name] = p
        self.program.desc.bump_version()
        return p

    def var(self, name: str) -> Variable:
        v = self.vars.get(name)
        if v is None:
            if self.desc.has_var(name):
                v = Variable(self, self.desc.var(name))
                self.vars[name] = v
            else:
                raise KeyError(f"variable {name!r} not found in block {self.idx}")
        return v

    def has_var(self, name: str) -> bool:
        return name in self.vars or self.desc.has_var(name)

    def var_recursive(self, name: str) -> Variable:
        """Look up `name` here or in ancestor blocks (reference:
        framework.py Block._var_recursive — sub-block ops may reference
        parent-scope variables)."""
        b = self
        while True:
            if b.has_var(name):
                return b.var(name)
            pidx = b.desc.parent_idx
            if pidx < 0 or b.idx == pidx:
                raise KeyError(f"variable {name!r} not found in block "
                               f"{self.idx} or its ancestors")
            b = self.program.blocks[pidx]

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- op management -----------------------------------------------------
    def append_op(self, type: str, inputs: Optional[Dict[str, Any]] = None,
                  outputs: Optional[Dict[str, Any]] = None,
                  attrs: Optional[Dict[str, Any]] = None) -> Operator:
        op_desc = ir.OpDesc(
            type=type,
            inputs=_names_of(inputs),
            outputs=_names_of(outputs),
            attrs=dict(attrs or {}),
        )
        self.desc.append_op(op_desc)
        op = Operator(self, op_desc)
        self.ops.append(op)
        self.program.desc.bump_version()
        self._infer_shapes(op_desc)
        return op

    def _infer_shapes(self, op_desc: ir.OpDesc):
        def lookup(name):
            return ir.find_var_recursive(self.program.desc, self.desc, name)

        # benign skips (control flow, concrete-value emitters) leave the
        # declared shapes alone; genuine emitter failures are debug-logged
        # by shape_inference and surface with provenance through
        # Program.analyze() / FLAGS_verify_program (shape-infer-error)
        res = abstract_eval_op(self.desc, op_desc, lookup=lookup)
        if not res.ok or not res.outputs:
            return
        for name, (shape, dtype) in res.outputs.items():
            if self.desc.has_var(name):
                vd = self.desc.var(name)
                if vd.shape is None or tuple(vd.shape) != shape:
                    vd.shape = list(shape)
                vd.dtype = dtype


def _names_of(slot_map) -> Dict[str, List[str]]:
    out: Dict[str, List[str]] = {}
    for slot, vals in (slot_map or {}).items():
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        names = [v.name if isinstance(v, Variable) else str(v) for v in vals]
        if names:
            out[slot] = names
    return out


class Program:
    """reference: framework.py:1510 — the user-visible program object."""

    def __init__(self):
        self.desc = ir.ProgramDesc()
        self.blocks = [Block(self, 0)]
        self._current_block_idx = 0
        self._is_test = False
        self._seed = 0

    @property
    def random_seed(self) -> int:
        return self.desc.random_seed

    @random_seed.setter
    def random_seed(self, s: int):
        self.desc.random_seed = int(s)
        self.desc.bump_version()

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self._current_block_idx]

    def create_block(self) -> Block:
        parent = self._current_block_idx
        self.desc.append_block(parent)
        b = Block(self, len(self.blocks))
        self.blocks.append(b)
        self._current_block_idx = b.idx
        return b

    def rollback(self):
        parent = self.desc.block(self._current_block_idx).parent_idx
        self._current_block_idx = max(parent, 0)

    def clone(self, for_test: bool = False) -> "Program":
        """reference: framework.py:1711 Program.clone(for_test=True) —
        the inference-graph convention; test mode flips is_test semantics
        of dropout/batch_norm at lowering."""
        p = Program()
        p.desc = self.desc.clone()
        p.blocks = [Block(p, i) for i in range(len(p.desc.blocks))]
        for b in p.blocks:
            for name, vd in b.desc.vars.items():
                src_block = self.blocks[b.idx] if b.idx < len(self.blocks) else None
                if src_block is not None and isinstance(src_block.vars.get(name), Parameter):
                    b.vars[name] = Parameter(b, vd)
                else:
                    b.vars[name] = Variable(b, vd)
            b.ops = [Operator(b, od) for od in b.desc.ops]
        p._is_test = for_test
        p._seed = self._seed
        p.desc.random_seed = self.desc.random_seed
        return p

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def all_parameters(self):
        return self.global_block().all_parameters()

    def analyze(self, feed_names=None, fetch_names=None,
                suppress=()):
        """Run the build-time program verifier over this program and
        return the diagnostics (errors first) — the interactive form of
        ``FLAGS_verify_program`` / ``tools/proglint.py``
        (docs/static_analysis.md)."""
        from paddle_tpu import analysis
        return analysis.analyze_program(
            self, feed_names=feed_names, fetch_names=fetch_names,
            is_test=self._is_test, suppress=suppress)

    def to_string(self, throw_on_error=False) -> str:
        import json
        return json.dumps(self.desc.to_dict(), indent=1)

    def __repr__(self):
        nops = sum(len(b.desc.ops) for b in self.blocks)
        return f"Program(blocks={len(self.blocks)}, ops={nops})"


# ---------------------------------------------------------------------------
# two-program convention + guards (reference: framework.py
# default_main_program/default_startup_program, program_guard)
# ---------------------------------------------------------------------------

_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def switch_main_program(p: Program) -> Program:
    global _main_program
    old, _main_program = _main_program, p
    return old


def switch_startup_program(p: Program) -> Program:
    global _startup_program
    old, _startup_program = _startup_program, p
    return old


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)


def reset_default_programs():
    """Test hook: fresh default programs (the reference gets this by
    constructing new Programs per test via program_guard)."""
    global _main_program, _startup_program
    _main_program = Program()
    _startup_program = Program()


# dtype helper mirroring fluid's convert_np_dtype_to_dtype_
def convert_dtype(dtype) -> str:
    if isinstance(dtype, str):
        return dtype
    return np.dtype(dtype).name


@contextlib.contextmanager
def name_scope(prefix=None):
    """reference: framework.py:107 — nests a name prefix for ops created
    inside (debug/visualization aid; here it prefixes unique_name keys).
    The per-key COUNTERS are shared with the enclosing generator, so two
    same-prefix scopes still produce unique names (a scope annotates,
    it never resets uniqueness)."""
    from paddle_tpu.fluid import unique_name as un
    token = f"{prefix or ''}/"
    old = un._generator
    scoped = un.NameGenerator(getattr(old, "prefix", "") + token)
    scoped.ids = old.ids               # shared counters
    un._generator = scoped
    try:
        yield
    finally:
        un._generator = old
