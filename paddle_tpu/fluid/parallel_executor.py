"""ParallelExecutor front-end (reference: python/paddle/fluid/
parallel_executor.py:41). Thin wrapper over CompiledProgram.with_data_parallel
+ Executor — on TPU there is no separate multi-device engine to construct;
the same XLA path runs with sharded inputs over the mesh."""

from __future__ import annotations

from typing import List, Optional

from paddle_tpu.core.executor import Executor, TPUPlace
from paddle_tpu.fluid import framework
from paddle_tpu.fluid.compiler import (BuildStrategy, CompiledProgram,
                                       ExecutionStrategy)


class ParallelExecutor:
    def __init__(self, use_cuda: bool = False, loss_name: Optional[str] = None,
                 main_program=None, share_vars_from=None,
                 exec_strategy: Optional[ExecutionStrategy] = None,
                 build_strategy: Optional[BuildStrategy] = None,
                 num_trainers: int = 1, trainer_id: int = 0, scope=None,
                 verify_program: bool = False):
        self._program = main_program or framework.default_main_program()
        if verify_program:
            # per-executor opt-in to the build-time verifier
            # (paddle_tpu.analysis) without flipping FLAGS_verify_program
            # process-wide; the BuildStrategy carries it to CompiledBlock.
            # Copy before mutating — a caller-shared strategy object must
            # not leak verification into unrelated executors.
            import dataclasses
            build_strategy = (
                dataclasses.replace(build_strategy, verify_program=True)
                if build_strategy is not None
                else BuildStrategy(verify_program=True))
        self._compiled = CompiledProgram(self._program).with_data_parallel(
            loss_name=loss_name, build_strategy=build_strategy,
            exec_strategy=exec_strategy)
        self._exe = Executor(TPUPlace())
        self._scope = scope

    def run(self, fetch_list: List, feed=None, feed_dict=None,
            return_numpy: bool = True):
        feed = feed if feed is not None else feed_dict
        return self._exe.run(self._compiled, feed=feed,
                             fetch_list=fetch_list, scope=self._scope,
                             return_numpy=return_numpy)
