"""CompiledProgram: build/exec strategy front-end.

Capability parity with the reference (python/paddle/fluid/compiler.py:33
CompiledProgram, :72 with_data_parallel; BuildStrategy/ExecutionStrategy
from framework/details/build_strategy.h:34). TPU-native semantics:
`with_data_parallel` attaches a DistributeConfig (mesh + data axis) instead
of constructing a C++ ParallelExecutor; the Executor lowers the same program
with sharded feeds and XLA inserts the gradient reductions over ICI — the
loss-scale (1/nranks, multi_devices_graph_pass.cc:422) falls out of `mean`
over the global batch, so no explicit ScaleLossGrad op exists.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from paddle_tpu.parallel.mesh import DistributeConfig, get_default_mesh, make_mesh


@dataclass
class BuildStrategy:
    """reference: build_strategy.h:34. The fuse_* knobs select IR passes
    (fluid/ir_pass.py) that BuildStrategy::Apply-style run over the program
    before lowering (reference wiring: details/build_strategy.h:113 —
    CreatePassesFromStrategy). Memory-reuse knobs are XLA's job and no-op.
    On a training program (post-minimize) only grad-aware passes apply;
    the rest warn and skip — the reference draws the same line between its
    BuildStrategy pipeline and the inference Analysis pipeline."""

    reduce_strategy: str = "all_reduce"          # kAllReduce | kReduce
    gradient_scale_strategy: str = "coeff_one"   # loss scaling is implicit
    memory_optimize: bool = False
    enable_inplace: bool = False
    fuse_elewise_add_act_ops: bool = False       # grad-aware
    fuse_fc_ops: bool = False                    # mul+add(+relu) → fc
    fuse_conv_ops: bool = False                  # conv epilogues → conv2d_fusion
    fuse_seq_ops: bool = False                   # seqpool/seqconv/seq_concat_fc/tfc
    fuse_rnn_ops: bool = False                   # fc_lstm/fc_gru/embedding_fc_lstm
    # TPU-semantic pipeline (paddle_tpu/passes): grad-aware conv-region
    # fusion with vjp merge, reshape/transpose chain canonicalization,
    # and the inference-only conv+BN statistics fold — the rewritten
    # program is re-verified by paddle_tpu.analysis post-pass
    fuse_conv_blocks: bool = False               # grad-aware, vjp merge
    canonicalize_layouts: bool = False           # grad-aware chain compose
    fold_conv_bn: bool = False                   # inference-only, needs scope
    # run the build-time program verifier (paddle_tpu.analysis) on this
    # program at CompiledBlock build — the per-program opt-in to what
    # FLAGS_verify_program enables process-wide (docs/static_analysis.md)
    verify_program: bool = False
    debug_graphviz_path: str = ""
    # explicit pass pipeline prefix (PassBuilder escape hatch, reference
    # compiler.py BuildStrategy._create_passes_from_strategy)
    ir_passes: List[str] = field(default_factory=list)

    @classmethod
    def tuned(cls, model: str = None, batch_size: int = None,
              is_test: bool = False, verify_program: bool = True):
        """The measured-default strategy: pass selection comes from the
        committed autotune table (paddle_tpu/passes pipeline_for — the
        per-model winner when one is committed, the static default
        otherwise), with post-pass verification on."""
        from paddle_tpu import passes as tpu_passes
        tpu_passes.register_all()
        return cls(ir_passes=tpu_passes.pipeline_for(
            is_test=is_test, model=model, batch_size=batch_size),
            verify_program=verify_program)

    def pass_names(self) -> List[str]:
        names = list(self.ir_passes)
        if self.fuse_elewise_add_act_ops:
            names.append("fuse_elewise_add_act_pass")
        # TPU-semantic pipeline (paddle_tpu/passes): region fusion first
        # (absorbs the conv's separate bias add), then the BN fold
        # (handles conv2d_fusion heads, absorbs the trailing act), then
        # layout canonicalization over whatever chains remain
        if self.fuse_conv_blocks:
            names.append("conv_block_fuse_pass")
        if self.fold_conv_bn:
            names.append("conv_bn_fold_pass")
        if self.canonicalize_layouts:
            names.append("layout_assignment_pass")
        # rnn/seq fusions must run BEFORE fc_fuse: their patterns start at
        # the mul+add gate projection that fc_fuse would consume
        # (reference pipeline keeps the same order for the same reason)
        if self.fuse_rnn_ops:
            names += ["embedding_fc_lstm_fuse_pass", "fc_lstm_fuse_pass",
                      "fc_gru_fuse_pass"]
        if self.fuse_seq_ops:
            names += ["seqconv_eltadd_relu_fuse_pass",
                      "seqpool_concat_fuse_pass",
                      "seq_concat_fc_fuse_pass",
                      "transpose_flatten_concat_fuse_pass"]
        if self.fuse_conv_ops:
            names += ["conv_elementwise_add2_act_fuse_pass",
                      "conv_elementwise_add_act_fuse_pass",
                      "conv_elementwise_add_fuse_pass"]
        if self.fuse_fc_ops:
            names.append("fc_fuse_pass")
        if self.debug_graphviz_path:
            names.append("graph_viz_pass")
        return names


@dataclass
class ExecutionStrategy:
    """reference: execution_strategy.h — thread counts are meaningless under
    XLA's single-executable dispatch; kept for API parity."""

    num_threads: int = 0
    num_iteration_per_drop_scope: int = 1
    allow_op_delay: bool = False


class CompiledProgram:
    """reference: compiler.py:33."""

    def __init__(self, program):
        self._program = program
        self._dist: Optional[DistributeConfig] = None
        self.build_strategy: Optional[BuildStrategy] = None
        self.exec_strategy: Optional[ExecutionStrategy] = None

    @property
    def program(self):
        return self._program

    @property
    def desc(self):
        return self._program.desc

    @property
    def _is_test(self):
        return getattr(self._program, "_is_test", False)

    @property
    def dist_config(self) -> Optional[DistributeConfig]:
        return self._dist

    def with_data_parallel(self, loss_name: Optional[str] = None,
                           build_strategy: Optional[BuildStrategy] = None,
                           exec_strategy: Optional[ExecutionStrategy] = None,
                           share_vars_from=None, places=None,
                           mesh=None, data_axis: str = "dp"):
        """reference: compiler.py:72 — returns self, configured to run the
        program data-parallel over all devices (or the given mesh)."""
        self.build_strategy = build_strategy or BuildStrategy()
        self.exec_strategy = exec_strategy or ExecutionStrategy()
        if mesh is None:
            mesh = get_default_mesh()
        if mesh is None:
            mesh = make_mesh(devices=places)
        reduce = (self.build_strategy.reduce_strategy
                  if self.build_strategy else "all_reduce")
        self._dist = DistributeConfig(mesh=mesh, data_axis=data_axis,
                                      reduce_strategy=reduce)
        return self

    def with_sharding(self, dist: DistributeConfig):
        """TPU-native extension: arbitrary mesh/param shardings (tp/pp/sp
        axes) — the capability superset of the transpiler modes."""
        self._dist = dist
        return self

    def with_build_strategy(self, build_strategy: BuildStrategy):
        """Attach a BuildStrategy without data-parallel execution (e.g. a
        single-chip program that wants the fusion passes)."""
        self.build_strategy = build_strategy
        return self

    def _apply_build_strategy(self, scope=None):
        """Run the strategy's IR-pass pipeline over the program, once —
        called by the Executor before (re)compiling, the moment the
        reference runs BuildStrategy::Apply (parallel_executor.cc:191).
        Scope-dependent folds (conv_bn, conv_affine_channel,
        embedding_fc_lstm) see the startup-initialized params."""
        bs = self.build_strategy
        if bs is None or getattr(self, "_passes_applied", False):
            return
        self._passes_applied = True
        if bs.verify_program:
            # flag the desc so CompiledBlock verifies AFTER the pass
            # pipeline mutates the program (verify what actually lowers)
            self._program.desc._verify_requested = True
        names = bs.pass_names()
        if not names:
            return
        from paddle_tpu import passes as tpu_passes
        tpu_passes.register_all()
        from paddle_tpu.fluid import ir_pass as irp
        block = self._program.desc.global_block
        tpu_passes.pin_op_indices(block)   # rewrites keep the rng stream
        has_vjp = any(op.type == "__vjp__" for op in block.ops)
        applied = []
        tpu_semantic = set(tpu_passes.register_all())
        for name in names:
            p = irp.get_pass(name)
            if has_vjp and not getattr(p, "grad_aware", False):
                import warnings
                warnings.warn(
                    f"BuildStrategy: pass {name!r} is not grad-aware and "
                    f"the program has backward ops — skipped. Apply it "
                    f"before minimize(), or to the inference program.",
                    stacklevel=3)
                continue
            if getattr(p, "inference_only", False) and scope is None:
                continue    # statistics fold without materialized params
            if name == "graph_viz_pass":
                p.path = bs.debug_graphviz_path or None
            tpu_passes.run_pass(p, name, block, scope=scope)
            applied.append(name)
        if applied:
            self._program.desc.bump_version()
            if tpu_semantic & set(applied):
                # every TPU-semantic rewrite is re-verified by the
                # build-time program verifier before lowering — a pass
                # bug surfaces as a named diagnostic, not wrong training
                self._program.desc._verify_requested = True
        return applied
