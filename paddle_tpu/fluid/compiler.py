"""CompiledProgram: build/exec strategy front-end.

Capability parity with the reference (python/paddle/fluid/compiler.py:33
CompiledProgram, :72 with_data_parallel; BuildStrategy/ExecutionStrategy
from framework/details/build_strategy.h:34). TPU-native semantics:
`with_data_parallel` attaches a DistributeConfig (mesh + data axis) instead
of constructing a C++ ParallelExecutor; the Executor lowers the same program
with sharded feeds and XLA inserts the gradient reductions over ICI — the
loss-scale (1/nranks, multi_devices_graph_pass.cc:422) falls out of `mean`
over the global batch, so no explicit ScaleLossGrad op exists.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from paddle_tpu.parallel.mesh import DistributeConfig, get_default_mesh, make_mesh


@dataclass
class BuildStrategy:
    """reference: build_strategy.h:34 — accepted knobs; TPU-meaningful ones
    map onto DistributeConfig, the rest are no-ops under XLA (fusion and
    memory-reuse passes are the compiler's job here)."""

    reduce_strategy: str = "all_reduce"          # kAllReduce | kReduce
    gradient_scale_strategy: str = "coeff_one"   # loss scaling is implicit
    memory_optimize: bool = False
    enable_inplace: bool = False
    fuse_elewise_add_act_ops: bool = False
    debug_graphviz_path: str = ""


@dataclass
class ExecutionStrategy:
    """reference: execution_strategy.h — thread counts are meaningless under
    XLA's single-executable dispatch; kept for API parity."""

    num_threads: int = 0
    num_iteration_per_drop_scope: int = 1
    allow_op_delay: bool = False


class CompiledProgram:
    """reference: compiler.py:33."""

    def __init__(self, program):
        self._program = program
        self._dist: Optional[DistributeConfig] = None
        self.build_strategy: Optional[BuildStrategy] = None
        self.exec_strategy: Optional[ExecutionStrategy] = None

    @property
    def program(self):
        return self._program

    @property
    def desc(self):
        return self._program.desc

    @property
    def _is_test(self):
        return getattr(self._program, "_is_test", False)

    @property
    def dist_config(self) -> Optional[DistributeConfig]:
        return self._dist

    def with_data_parallel(self, loss_name: Optional[str] = None,
                           build_strategy: Optional[BuildStrategy] = None,
                           exec_strategy: Optional[ExecutionStrategy] = None,
                           share_vars_from=None, places=None,
                           mesh=None, data_axis: str = "dp"):
        """reference: compiler.py:72 — returns self, configured to run the
        program data-parallel over all devices (or the given mesh)."""
        self.build_strategy = build_strategy or BuildStrategy()
        self.exec_strategy = exec_strategy or ExecutionStrategy()
        if mesh is None:
            mesh = get_default_mesh()
        if mesh is None:
            mesh = make_mesh(devices=places)
        reduce = (self.build_strategy.reduce_strategy
                  if self.build_strategy else "all_reduce")
        self._dist = DistributeConfig(mesh=mesh, data_axis=data_axis,
                                      reduce_strategy=reduce)
        return self

    def with_sharding(self, dist: DistributeConfig):
        """TPU-native extension: arbitrary mesh/param shardings (tp/pp/sp
        axes) — the capability superset of the transpiler modes."""
        self._dist = dist
        return self
