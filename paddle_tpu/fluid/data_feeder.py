"""DataFeeder (reference: python/paddle/fluid/data_feeder.py — converts a
minibatch of python samples into the feed dict of dense arrays; the LoD
conversion becomes padding + optional sequence-length arrays, since XLA has
no ragged tensors — SURVEY §5 long-context note)."""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np


class DataFeeder:
    def __init__(self, feed_list: Sequence, place=None, program=None):
        self.feed_vars = list(feed_list)

    def feed(self, iterable) -> Dict[str, np.ndarray]:
        batch = list(iterable)
        out: Dict[str, np.ndarray] = {}
        for i, var in enumerate(self.feed_vars):
            name = var if isinstance(var, str) else var.name
            dtype = "float32" if isinstance(var, str) else var.dtype
            shape = None if isinstance(var, str) else var.shape
            cols = [sample[i] for sample in batch]
            arr = self._to_dense(cols, dtype, shape)
            out[name] = arr
        return out

    @staticmethod
    def _to_dense(cols: List, dtype: str, shape) -> np.ndarray:
        first = np.asarray(cols[0])
        if first.ndim >= 1 and any(np.asarray(c).shape != first.shape
                                   for c in cols):
            # variable-length sequences: pad to max length (LoD capability
            # via padding + masking rather than offset tables)
            maxlen = max(np.asarray(c).shape[0] for c in cols)
            trailing = np.asarray(cols[0]).shape[1:]
            out = np.zeros((len(cols), maxlen) + trailing, dtype=dtype)
            for j, c in enumerate(cols):
                c = np.asarray(c, dtype=dtype)
                out[j, :c.shape[0]] = c
            return out
        arr = np.asarray(cols, dtype=dtype)
        if shape is not None and len(shape) >= 2 and arr.ndim == 1:
            arr = arr.reshape(len(cols), *[d for d in shape[1:]])
        return arr
