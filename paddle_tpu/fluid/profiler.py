"""Profiler (reference: python/paddle/fluid/profiler.py — profiler() context
manager :221, start/stop_profiler :125,165, cuda_profiler :39, reset_profiler;
C++ side platform/profiler.cc + CUPTI DeviceTracer + tools/timeline.py).

TPU-native design: device-side tracing is jax.profiler (XPlane → TensorBoard
/ Perfetto, replacing the CUPTI→chrome-trace path); host-side per-run event
timing is kept as a lightweight table with the reference's sorted-summary
report (EventSortingKey profiler.h:114)."""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Optional

_events = defaultdict(lambda: {"calls": 0, "total": 0.0, "min": float("inf"),
                               "max": 0.0})
_spans = []          # (name, start_s, end_s) while active — timeline source
_active = False


@contextlib.contextmanager
def record_event(name: str):
    """Host-side RAII event (reference: platform/profiler.h:27 RecordEvent)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        dt = t1 - t0
        e = _events[name]
        e["calls"] += 1
        e["total"] += dt
        e["min"] = min(e["min"], dt)
        e["max"] = max(e["max"], dt)
        if _active:
            _spans.append((name, t0, t1))


def reset_profiler():
    _events.clear()
    _spans.clear()


def export_spans(path: str):
    """Write (name, start, end) span rows (csv-quoted — names are arbitrary
    caller strings) — input for tools/timeline.py."""
    import csv
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        for name, t0, t1 in _spans:
            w.writerow([name, t0, t1])


def spans_to_chrome_trace(spans, pid=0):
    """(name, start_s, end_s[, tid]) rows → chrome://tracing JSON dict
    (reference capability: tools/timeline.py output format)."""
    events = []
    for row in spans:
        name, start, end = row[0], float(row[1]), float(row[2])
        tid = int(row[3]) if len(row) > 3 else 0
        events.append({"name": name, "cat": "host", "ph": "X",
                       "ts": start * 1e6, "dur": (end - start) * 1e6,
                       "pid": pid, "tid": tid})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_chrome_trace(path: str):
    import json
    with open(path, "w") as f:
        json.dump(spans_to_chrome_trace(_spans), f)


def start_profiler(state: str = "All", tracer_option: Optional[str] = None,
                   trace_dir: Optional[str] = None):
    """reference: profiler.py:125. state/tracer_option accepted for parity;
    device tracing delegates to jax.profiler when a trace_dir is given."""
    global _active
    _active = True
    if trace_dir:
        import jax
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key: Optional[str] = "total",
                  profile_path: Optional[str] = None, trace_dir=None):
    """reference: profiler.py:165 — prints the per-event summary table."""
    global _active
    if trace_dir:
        import jax
        jax.profiler.stop_trace()
    if not _active:
        return
    _active = False
    rows = []
    for name, e in _events.items():
        ave = e["total"] / max(e["calls"], 1)
        rows.append((name, e["calls"], e["total"], ave, e["min"], e["max"]))
    key_idx = {"calls": 1, "total": 2, "ave": 3, "min": 4, "max": 5}.get(
        sorted_key or "total", 2)
    rows.sort(key=lambda r: r[key_idx], reverse=True)
    if rows:
        print(f"{'Event':<40}{'Calls':>8}{'Total(s)':>12}{'Ave(s)':>12}"
              f"{'Min(s)':>12}{'Max(s)':>12}")
        for r in rows:
            print(f"{r[0]:<40}{r[1]:>8}{r[2]:>12.6f}{r[3]:>12.6f}"
                  f"{r[4]:>12.6f}{r[5]:>12.6f}")
    if profile_path:
        with open(profile_path, "w") as f:
            for r in rows:
                f.write(",".join(str(x) for x in r) + "\n")


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             profile_path: Optional[str] = None,
             trace_dir: Optional[str] = None):
    """reference: profiler.py:221 fluid.profiler.profiler()."""
    reset_profiler()
    start_profiler(state, trace_dir=trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path, trace_dir=trace_dir)


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """reference: profiler.py:39 — nvprof passthrough; no TPU analogue
    (use trace_dir→TensorBoard instead). Accepted as a no-op for parity."""
    yield
