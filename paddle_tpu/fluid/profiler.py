"""Profiler (reference: python/paddle/fluid/profiler.py — profiler() context
manager :221, start/stop_profiler :125,165, cuda_profiler :39, reset_profiler;
C++ side platform/profiler.cc + CUPTI DeviceTracer + tools/timeline.py).

TPU-native design: device-side tracing is jax.profiler (XPlane → TensorBoard
/ Perfetto, replacing the CUPTI→chrome-trace path); host-side span
recording delegates to ``paddle_tpu.observability.tracing`` (the
process-default :class:`Tracer`) — lock-protected and thread-id-aware,
fixing the old module-global ``_events``/``_spans`` lists that raced the
DataLoader's produce thread and stacked every span on tid 0. The public
API here is unchanged; the sorted-summary report keeps the reference's
shape (EventSortingKey profiler.h:114)."""

from __future__ import annotations

import contextlib
from typing import Optional

from paddle_tpu.observability import tracing as _tracing

_tracer = _tracing.default_tracer()


def record_event(name: str):
    """Host-side RAII event (reference: platform/profiler.h:27 RecordEvent).
    Thread-safe: aggregates update under the tracer's lock and spans carry
    the recording thread's real id."""
    return _tracer.span(name)


def reset_profiler():
    _tracer.reset()


def export_spans(path: str):
    """Write (name, start, end, tid) span rows (csv-quoted — names are
    arbitrary caller strings) — input for tools/timeline.py."""
    import csv
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        for s in _tracer.spans():
            w.writerow([s.name, s.start_s, s.end_s, s.tid])


def spans_to_chrome_trace(spans, pid=0):
    """(name, start_s, end_s[, tid]) rows → chrome://tracing JSON dict
    (reference capability: tools/timeline.py output format). Rows from
    :func:`export_spans` carry the real thread id in column 4."""
    events = []
    for row in spans:
        name, start, end = row[0], float(row[1]), float(row[2])
        tid = int(row[3]) if len(row) > 3 else 0
        events.append({"name": name, "cat": "host", "ph": "X",
                       "ts": start * 1e6, "dur": (end - start) * 1e6,
                       "pid": pid, "tid": tid})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_chrome_trace(path: str):
    _tracer.export_chrome_trace(path)


def start_profiler(state: str = "All", tracer_option: Optional[str] = None,
                   trace_dir: Optional[str] = None):
    """reference: profiler.py:125. state/tracer_option accepted for parity;
    device tracing delegates to jax.profiler when a trace_dir is given."""
    _tracer.start()
    if trace_dir:
        import jax
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key: Optional[str] = "total",
                  profile_path: Optional[str] = None, trace_dir=None):
    """reference: profiler.py:165 — prints the per-event summary table."""
    if trace_dir:
        import jax
        jax.profiler.stop_trace()
    if not _tracer.enabled:
        return
    _tracer.stop()
    rows = []
    for name, e in _tracer.event_stats().items():
        ave = e["total"] / max(e["calls"], 1)
        rows.append((name, e["calls"], e["total"], ave, e["min"], e["max"]))
    key_idx = {"calls": 1, "total": 2, "ave": 3, "min": 4, "max": 5}.get(
        sorted_key or "total", 2)
    rows.sort(key=lambda r: r[key_idx], reverse=True)
    if rows:
        print(f"{'Event':<40}{'Calls':>8}{'Total(s)':>12}{'Ave(s)':>12}"
              f"{'Min(s)':>12}{'Max(s)':>12}")
        for r in rows:
            print(f"{r[0]:<40}{r[1]:>8}{r[2]:>12.6f}{r[3]:>12.6f}"
                  f"{r[4]:>12.6f}{r[5]:>12.6f}")
    if profile_path:
        with open(profile_path, "w") as f:
            for r in rows:
                f.write(",".join(str(x) for x in r) + "\n")


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             profile_path: Optional[str] = None,
             trace_dir: Optional[str] = None):
    """reference: profiler.py:221 fluid.profiler.profiler()."""
    reset_profiler()
    start_profiler(state, trace_dir=trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path, trace_dir=trace_dir)


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """reference: profiler.py:39 — nvprof passthrough; no TPU analogue
    (use trace_dir→TensorBoard instead). Accepted as a no-op for parity."""
    yield


def device_op_stats(trace_dir: str, top: int = 0):
    """Per-HLO-op DEVICE time attribution from a jax.profiler trace
    captured via start_profiler(trace_dir=...) — the TPU delivery of the
    reference's CUPTI DeviceTracer per-op device table
    (platform/device_tracer.h:39 correlates device events back to ops;
    here the XPlane protos are parsed through xprof's hlo_stats tool).

    With multi-step device loops (exe.run iterations=N) host-side spans
    can no longer attribute time per op — the whole window is one
    dispatch; this is the device-side view that can. Returns rows of
    {name, category, self_time_us, occurrences, flop_rate, bound_by,
    bandwidth_gbs}, sorted by self time (top rows if top > 0)."""
    import glob
    import json as _json

    try:
        from xprof.convert import raw_to_tool_data as _rtd
    except ImportError as e:                       # pragma: no cover
        raise RuntimeError(
            "device_op_stats needs the xprof package (baked into this "
            "environment; pip install xprof elsewhere)") from e

    run_dirs = sorted(glob.glob(trace_dir + "/plugins/profile/*"))
    if not run_dirs:
        raise FileNotFoundError(
            f"no profile runs under {trace_dir!r} — call "
            f"start_profiler(trace_dir=...) / stop_profiler first")
    files = glob.glob(run_dirs[-1] + "/*.xplane.pb")
    if not files:
        raise FileNotFoundError(
            f"profile run {run_dirs[-1]!r} has no .xplane.pb — the "
            f"capture was interrupted before stop_profiler flushed it; "
            f"re-capture the trace")
    data, _ = _rtd.xspace_to_tool_data(files, "hlo_stats", {})
    raw = _json.loads(data)
    cols = [c["label"] for c in raw["cols"]]
    idx = {c: i for i, c in enumerate(cols)}

    def col(row, label, default=None):
        cell = row["c"][idx[label]] if label in idx else None
        return cell.get("v", default) if cell else default

    rows = []
    for r in raw["rows"]:
        rows.append({
            "name": col(r, "HLO op name", ""),
            "category": col(r, "HLO op category", ""),
            "self_time_us": float(col(r, "Total self time (us)", 0.0) or 0),
            "occurrences": int(col(r, "#Occurrences", 0) or 0),
            "flop_rate": col(r, "Model GFLOP/s"),
            "bound_by": col(r, "Bound by"),
            "bandwidth_gbs": col(r, "Measured memory BW (GiB/s)"),
        })
    rows.sort(key=lambda x: -x["self_time_us"])
    return rows[:top] if top else rows


def print_device_op_stats(trace_dir: str, top: int = 20):
    """Sorted per-op device-time table (the reference's sorted profiler
    report, but for DEVICE time — EventSortingKey profiler.h:114)."""
    all_rows = device_op_stats(trace_dir)      # parse ONCE
    total = sum(r["self_time_us"] for r in all_rows)
    rows = all_rows[:top] if top else all_rows
    print(f"{'HLO op':<44}{'Category':<22}{'Self(us)':>10}{'%':>7}"
          f"{'Bound':>9}")
    for r in rows:
        pct = 100.0 * r["self_time_us"] / total if total else 0.0
        print(f"{r['name'][:43]:<44}{r['category'][:21]:<22}"
              f"{r['self_time_us']:>10.0f}{pct:>6.1f}%"
              f"{str(r['bound_by'] or ''):>9}")
    return rows
