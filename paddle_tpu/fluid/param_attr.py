"""ParamAttr / WeightNormParamAttr (reference: python/paddle/fluid/param_attr.py)."""

from __future__ import annotations

from typing import Optional

from paddle_tpu.fluid import initializer as init_mod


class ParamAttr:
    def __init__(self, name: Optional[str] = None, initializer=None,
                 learning_rate: float = 1.0, regularizer=None,
                 trainable: bool = True, gradient_clip=None,
                 do_model_average: bool = False):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        self.do_model_average = do_model_average

    @staticmethod
    def _to_attr(arg) -> "ParamAttr":
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, init_mod.Initializer):
            return ParamAttr(initializer=arg)
        if isinstance(arg, bool):
            # fluid convention: bias_attr=False means "no bias"
            raise ValueError("use None/False checks before _to_attr")
        if isinstance(arg, (list, tuple)):
            return [ParamAttr._to_attr(a) for a in arg]
        raise TypeError(f"cannot convert {arg!r} to ParamAttr")


class WeightNormParamAttr(ParamAttr):
    """reference: param_attr.py WeightNormParamAttr — weight
    normalization (Salimans & Kingma): the layer's weight is
    reparameterized as w = g * v / ||v||, with the norm taken over every
    axis except `dim` (dim=None: one scalar g). LayerHelper detects this
    attr and appends the reparam ops; gradients flow to g and v."""

    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim
