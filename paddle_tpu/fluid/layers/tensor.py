"""Tensor-manipulation layers (reference: python/paddle/fluid/layers/tensor.py)."""

from __future__ import annotations

from paddle_tpu.fluid.layer_helper import LayerHelper


def fill_constant(shape, dtype, value, out=None, name=None):
    helper = LayerHelper("fill_constant", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("fill_constant", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "value": float(value)})
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("fill_constant_batch_size_like",
                     inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "value": float(value),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    out.stop_gradient = True
    return out


def cast(x, dtype):
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("cast", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"out_dtype": dtype})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("concat", inputs={"X": list(input)},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def sums(input, out=None):
    helper = LayerHelper("sums")
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("sum", inputs={"X": list(input)}, outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if output is None:
        output = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("assign", inputs={"X": [input]}, outputs={"Out": [output]})
    return output


def zeros(shape, dtype="float32"):
    return fill_constant(shape, dtype, 0.0)


def ones(shape, dtype="float32"):
    return fill_constant(shape, dtype, 1.0)


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_zeros_like", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def argmax(x, axis=-1):
    helper = LayerHelper("argmax")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("argmax", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def argmin(x, axis=-1):
    helper = LayerHelper("argmin")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("argmin", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference("int32")
    helper.append_op("shape", inputs={"Input": [input]}, outputs={"Out": [out]})
    return out
