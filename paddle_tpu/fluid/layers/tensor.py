"""Tensor-manipulation layers (reference: python/paddle/fluid/layers/tensor.py)."""

from __future__ import annotations

from paddle_tpu.fluid.layer_helper import LayerHelper


def fill_constant(shape, dtype, value, out=None, name=None):
    helper = LayerHelper("fill_constant", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("fill_constant", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "value": float(value)})
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("fill_constant_batch_size_like",
                     inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "value": float(value),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    out.stop_gradient = True
    return out


def cast(x, dtype):
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("cast", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"out_dtype": dtype})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("concat", inputs={"X": list(input)},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def sums(input, out=None):
    helper = LayerHelper("sums")
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("sum", inputs={"X": list(input)}, outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if output is None:
        output = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("assign", inputs={"X": [input]}, outputs={"Out": [output]})
    return output


def zeros(shape, dtype="float32"):
    return fill_constant(shape, dtype, 0.0)


def ones(shape, dtype="float32"):
    return fill_constant(shape, dtype, 1.0)


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_zeros_like", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def argmax(x, axis=-1):
    helper = LayerHelper("argmax")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("argmax", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def argmin(x, axis=-1):
    helper = LayerHelper("argmin")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("argmin", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference("int32")
    helper.append_op("shape", inputs={"Input": [input]}, outputs={"Out": [out]})
    return out


def create_tensor(dtype, name=None, persistable=False):
    """reference: tensor.py:35."""
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_global_variable(shape=[1], dtype=dtype,
                                         name=name, persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """reference: tensor.py:59."""
    from paddle_tpu.fluid.param_attr import ParamAttr
    helper = LayerHelper("create_parameter")
    attr = attr or ParamAttr(name=name)
    return helper.create_parameter(attr, shape=list(shape), dtype=dtype,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """reference: tensor.py:97 — a global var initialized by the startup
    program."""
    from paddle_tpu.fluid.initializer import ConstantInitializer
    helper = LayerHelper("global_var")
    var = helper.create_global_variable(shape=list(shape), dtype=dtype,
                                        name=name, persistable=persistable)
    startup_block = helper.startup_program.global_block()
    if not startup_block.has_var(var.name):
        sp = startup_block.create_var(name=var.name, shape=list(shape),
                                      dtype=dtype, persistable=persistable)
        ConstantInitializer(float(value))(sp, startup_block)
    return var


def reverse(x, axis):
    """reference: tensor.py:608 → reverse_op.cc."""
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("reverse", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": [axis] if isinstance(axis, int)
                            else list(axis)})
    return out


def _overflow_check(op, x):
    helper = LayerHelper(op)
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op(op, inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def has_inf(x):
    """reference: tensor.py:714."""
    return _overflow_check("has_inf", x)


def has_nan(x):
    """reference: tensor.py:730."""
    return _overflow_check("has_nan", x)


def isfinite(x):
    """reference: tensor.py:746."""
    return _overflow_check("isfinite", x)


def load(out, file_path, load_as_fp16=None):
    """reference: tensor.py load() → load_op.cc."""
    helper = LayerHelper("load")
    helper.append_op("load", inputs={}, outputs={"Out": [out]},
                     attrs={"file_path": file_path})
    return out


def is_empty(x, cond=None):
    """reference: control_flow.py is_empty → is_empty_op.cc."""
    helper = LayerHelper("is_empty")
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
    helper.append_op("is_empty", inputs={"X": [x]}, outputs={"Out": [cond]})
    return cond
