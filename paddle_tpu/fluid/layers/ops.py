"""Auto-generated layer wrappers for unary activations and elementwise
binary ops (reference: python/paddle/fluid/layers/ops.py +
layer_function_generator.py — wrappers generated from OpProto; here
generated from the emitter registry)."""

from __future__ import annotations

import sys

from paddle_tpu.fluid.layer_helper import LayerHelper

_UNARY = [
    "sigmoid", "logsigmoid", "exp", "tanh", "tanh_shrink", "sqrt", "rsqrt",
    "abs", "ceil", "floor", "cos", "sin", "round", "reciprocal", "square",
    "softplus", "softsign", "relu", "gelu",
]

_BINARY = [
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow", "elementwise_mod",
]

_COMPARE = [
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal",
]

_mod = sys.modules[__name__]


def _make_unary(op):
    def layer(x, name=None):
        helper = LayerHelper(op, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(op, inputs={"X": [x]}, outputs={"Out": [out]})
        return out
    layer.__name__ = op
    return layer


def _make_binary(op):
    def layer(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(op, inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [out]}, attrs={"axis": axis})
        return helper.append_activation(out, act)
    layer.__name__ = op
    return layer


def _make_compare(op):
    def layer(x, y, cond=None, force_cpu=None):
        helper = LayerHelper(op)
        if cond is None:
            cond = helper.create_variable_for_type_inference("bool")
        helper.append_op(op, inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [cond]})
        cond.stop_gradient = True
        cond.desc.dtype = "bool"
        return cond
    layer.__name__ = op
    return layer


for _op in _UNARY:
    setattr(_mod, _op, _make_unary(_op))
for _op in _BINARY:
    setattr(_mod, _op, _make_binary(_op))
for _op in _COMPARE:
    setattr(_mod, _op, _make_compare(_op))


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper("leaky_relu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("leaky_relu", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"alpha": alpha})
    return out


def relu6(x, threshold=6.0, name=None):
    helper = LayerHelper("relu6", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("relu6", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"threshold": threshold})
    return out


def elu(x, alpha=1.0, name=None):
    helper = LayerHelper("elu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("elu", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"alpha": alpha})
    return out


def swish(x, beta=1.0, name=None):
    helper = LayerHelper("swish", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("swish", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"beta": beta})
    return out


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    helper = LayerHelper("hard_sigmoid", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("hard_sigmoid", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"slope": slope, "offset": offset})
    return out


def pow(x, factor=1.0, name=None):
    helper = LayerHelper("pow", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("pow", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"factor": factor})
    return out


# -- misc-batch wrappers (reference: layers/nn.py + layers/ops.py entries
# for selu nn.py, hard_shrink/softshrink/thresholded_relu/brelu/stanh
# generated in layers/ops.py from OpProto) --------------------------------

def _make_attr_unary(op, defaults, in_slot="X"):
    def layer(x, name=None, **kwargs):
        attrs = dict(defaults)
        for k in kwargs:
            if k not in attrs:
                raise TypeError(f"{op}() got unexpected kwarg {k!r}")
        attrs.update(kwargs)
        helper = LayerHelper(op, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(op, inputs={in_slot: [x]}, outputs={"Out": [out]},
                         attrs=attrs)
        return out
    layer.__name__ = op
    return layer


_ATTR_UNARY = {
    "selu": {"scale": 1.0507009873554805, "alpha": 1.6732632423543772},
    "hard_shrink": {"threshold": 0.5},
    "thresholded_relu": {"threshold": 1.0},
    "brelu": {"t_min": 0.0, "t_max": 24.0},
    "stanh": {"scale_a": 2.0 / 3.0, "scale_b": 1.7159},
    "maxout": {"groups": 2},
    "flatten": {"axis": 1},
    "space_to_depth": {"blocksize": 2},
    "l1_norm": {},
}

for _op, _defaults in _ATTR_UNARY.items():
    setattr(_mod, _op, _make_attr_unary(_op, _defaults))


def soft_shrink(x, alpha=0.5, name=None):
    """The op attr is named 'lambda' (a Python keyword), so the layer
    exposes it as `alpha` like the reference's generated softshrink."""
    helper = LayerHelper("soft_shrink", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("soft_shrink", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"lambda": alpha})
    return out


# `softshrink` is the reference's public layer name for soft_shrink
softshrink = soft_shrink
