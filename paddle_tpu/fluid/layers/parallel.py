"""Pipeline / expert parallelism from the fluid Program API.

Round-2 verdict item 5: every parallelism mode must be drivable from the
user program (the reference's bar — every mode it has is reachable via
transpiler/ParallelExecutor, distribute_transpiler.py:276). PP and EP are
TPU-first extensions (the reference has neither — SURVEY §2 parallelism
inventory), so the fluid surface here is new design, not parity:

- `Pipeline`: a StaticRNN-style context that builds the repeated stage
  body as a sub-block; its parameters get a leading [n_stages] dim and a
  single `pipeline` op lowers to the GPipe schedule over the mesh's pp
  axis (parallel/pipeline.py) — or to a sequential stage scan off-mesh,
  with identical math (homogeneous stages, e.g. transformer blocks).
- `switch_moe`: a switch (top-1) MoE FFN layer whose expert weights
  carry a leading [n_experts] dim; the `moe_ffn` op lowers to the
  all-to-all expert-parallel kernel over the mesh's ep axis
  (parallel/moe.py) — or to the same routing math densely off-mesh.
"""

from __future__ import annotations

import contextlib

from paddle_tpu.fluid import framework
from paddle_tpu.fluid import unique_name
from paddle_tpu.fluid.layer_helper import LayerHelper
from paddle_tpu.fluid.layers.control_flow import _analyze_subblock

__all__ = ["Pipeline", "switch_moe"]


class Pipeline:
    """Homogeneous-stage pipeline section.

        pipe = layers.Pipeline(n_stages=2, n_microbatches=4)
        with pipe.stage(x) as h:
            h1 = layers.fc(h, d, bias_attr=False)
            pipe.set_output(layers.relu(h1))
        y = pipe.output

    The body traces ONCE; parameters created inside get a leading
    [n_stages] dim (each stage owns its slice — under a pp mesh axis the
    stack shards one stage per rank). The stage body must preserve the
    activation's shape/dtype and be per-sample (no cross-batch ops like
    batch_norm: microbatches would see different statistics). The batch
    dim must divide n_microbatches.
    """

    def __init__(self, n_stages: int, n_microbatches: int, name=None):
        if n_stages < 1 or n_microbatches < 1:
            raise ValueError("n_stages and n_microbatches must be >= 1")
        self.n_stages = n_stages
        self.n_micro = n_microbatches
        self.program = framework.default_main_program()
        self._out_name = None
        self.output = None

    def set_output(self, var):
        self._out_name = var.name

    @contextlib.contextmanager
    def stage(self, x):
        parent_block = self.program.current_block()
        pre_existing = {n for n, v in
                        self.program.desc.global_block.vars.items()
                        if v.is_parameter}
        sub = self.program.create_block()
        stage_in = self.program.current_block().create_var(
            name=unique_name.generate("pipeline_stage_in"),
            shape=list(x.shape), dtype=x.dtype)
        try:
            yield stage_in
        finally:
            self.program.rollback()
        if self._out_name is None:
            raise ValueError("Pipeline.stage body must call set_output()")
        ext_reads, writes = _analyze_subblock(
            self.program, sub.idx, preset_defined=(stage_in.name,))
        if writes:
            raise ValueError(
                f"Pipeline stage body must not assign ancestor vars "
                f"(got {writes}); produce the stage output and "
                f"set_output() it")
        params, others = [], []
        for n in ext_reads:
            v = parent_block.var_recursive(n)
            (params if v.desc.is_parameter else others).append(n)
        if others:
            raise ValueError(
                f"Pipeline stage body may only close over parameters; it "
                f"reads non-parameter vars {others} — feed them through "
                f"the stage activation instead")
        # prepend the stage dim to every body parameter, in the main
        # program AND its startup initializer (each stage owns its slice).
        # Only params CREATED INSIDE the stage body may be stacked: a
        # pre-existing/shared parameter would corrupt its other consumers
        # (and a param read by two Pipeline sections would double-stack)
        shared = [n for n in params if n in pre_existing]
        if shared:
            raise ValueError(
                f"Pipeline stage body reuses parameters created outside "
                f"the stage: {shared} — stage parameters must be created "
                f"inside the stage body (they get a leading [n_stages] "
                f"dim that other consumers cannot see)")
        # (the pre_existing check above also rules out a param shared
        # between two Pipeline sections — the second section would see it
        # as pre-existing)
        startup = framework.default_startup_program()
        for n in params:
            v = parent_block.var_recursive(n)
            v.desc.shape = [self.n_stages] + list(v.desc.shape)
            sblk = startup.desc.global_block
            if sblk.has_var(n):
                sblk.var(n).shape = [self.n_stages] + list(
                    sblk.var(n).shape)
            for op in sblk.ops:
                if n in op.output_names() and "shape" in op.attrs:
                    op.attrs = dict(op.attrs)
                    op.attrs["shape"] = [self.n_stages] + list(
                        op.attrs["shape"])
        out = parent_block.create_var(
            name=unique_name.generate("pipeline_out"),
            shape=list(x.shape), dtype=x.dtype)
        parent_block.append_op(
            "pipeline",
            inputs={"X": [x],
                    "Params": [parent_block.var_recursive(n)
                               for n in params]},
            outputs={"Out": [out]},
            attrs={"sub_block": sub.idx,
                   "n_microbatches": self.n_micro,
                   "n_stages": self.n_stages,
                   "stage_in": stage_in.name,
                   "stage_out": self._out_name,
                   "param_names": list(params)})
        self.output = out


def switch_moe(x, n_experts, d_ff, capacity_factor=2.0, param_attr=None,
               name=None):
    """Switch (top-1) mixture-of-experts FFN: x [B, D] (or [B, T, D],
    flattened over tokens) -> (y same shape, aux_loss scalar). Expert
    weights carry a leading [n_experts] dim; under a mesh with an ep axis
    the experts shard and tokens all-to-all (parallel/moe.py); off-mesh
    the same routing math runs densely."""
    helper = LayerHelper(name or "switch_moe")
    d = int(x.shape[-1])
    from paddle_tpu.fluid.initializer import NormalInitializer
    init = NormalInitializer(0.0, d ** -0.5)
    gate_w = helper.create_parameter(param_attr, shape=[d, n_experts],
                                     dtype=x.dtype,
                                     default_initializer=init)
    w1 = helper.create_parameter(param_attr, shape=[n_experts, d, d_ff],
                                 dtype=x.dtype, default_initializer=init)
    b1 = helper.create_parameter(param_attr, shape=[n_experts, d_ff],
                                 dtype=x.dtype, is_bias=True)
    w2 = helper.create_parameter(param_attr, shape=[n_experts, d_ff, d],
                                 dtype=x.dtype, default_initializer=init)
    b2 = helper.create_parameter(param_attr, shape=[n_experts, d],
                                 dtype=x.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(x.dtype)
    aux = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "moe_ffn",
        inputs={"X": [x], "GateW": [gate_w], "W1": [w1], "B1": [b1],
                "W2": [w2], "B2": [b2]},
        outputs={"Out": [out], "AuxLoss": [aux]},
        attrs={"n_experts": n_experts,
               "capacity_factor": float(capacity_factor)})
    return out, aux
