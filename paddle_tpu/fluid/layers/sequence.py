"""Sequence layers (reference: python/paddle/fluid/layers/nn.py —
sequence_conv, sequence_pool, sequence_softmax, sequence_expand,
sequence_concat, sequence_reshape, sequence_slice, sequence_pad/unpad,
sequence_mask, sequence_enumerate, sequence_erase, sequence_reverse,
edit_distance).

LoD divergence: the reference threads sequence lengths implicitly through
LoDTensor metadata; under XLA tensors are padded ``[B, T, ...]`` and lengths
travel as an explicit ``seq_lens`` int tensor argument (see
paddle_tpu/ops/sequence_ops.py).
"""

from __future__ import annotations

from paddle_tpu.fluid.layer_helper import LayerHelper


def _seq_inputs(x, seq_lens, slot="X"):
    ins = {slot: [x]}
    if seq_lens is not None:
        ins["SeqLens"] = [seq_lens]
    return ins


def sequence_pool(input, pool_type, seq_lens=None):
    """reference: nn.py sequence_pool — SUM/AVERAGE/SQRT/MAX/LAST/FIRST."""
    helper = LayerHelper("sequence_pool")
    out = helper.create_variable_for_type_inference(input.dtype)
    outs = {"Out": [out]}
    if pool_type.upper() == "MAX":
        idx = helper.create_variable_for_type_inference("int32")
        outs["MaxIndex"] = [idx]
    helper.append_op("sequence_pool", inputs=_seq_inputs(input, seq_lens),
                     outputs=outs, attrs={"pooltype": pool_type.upper()})
    return out


def sequence_first_step(input, seq_lens=None):
    return sequence_pool(input, "FIRST", seq_lens)


def sequence_last_step(input, seq_lens=None):
    return sequence_pool(input, "LAST", seq_lens)


def sequence_softmax(input, seq_lens=None):
    helper = LayerHelper("sequence_softmax")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("sequence_softmax", inputs=_seq_inputs(input, seq_lens),
                     outputs={"Out": [out]})
    return out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None,
                  seq_lens=None):
    """reference: nn.py sequence_conv over context windows."""
    if filter_stride != 1:
        raise ValueError("sequence_conv only supports filter_stride=1 "
                         "(the reference enforces the same, "
                         "sequence_conv_op.cc contextStride check)")
    helper = LayerHelper("sequence_conv")
    D = input.shape[-1]
    filter_shape = [filter_size * D, num_filters]
    w = helper.create_parameter(param_attr, shape=filter_shape,
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = _seq_inputs(input, seq_lens)
    ins["Filter"] = [w]
    helper.append_op("sequence_conv", inputs=ins, outputs={"Out": [out]},
                     attrs={"contextLength": filter_size,
                            "contextStart": -(filter_size - 1) // 2,
                            "contextStride": filter_stride})
    pre_act = helper.append_bias_op(out, bias_attr, num_filters, dim_start=2)
    return helper.append_activation(pre_act, act)


def sequence_expand(x, y, seq_lens=None, ref_level=-1):
    helper = LayerHelper("sequence_expand")
    out = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": [x], "Y": [y]}
    if seq_lens is not None:
        ins["SeqLens"] = [seq_lens]
    helper.append_op("sequence_expand", inputs=ins, outputs={"Out": [out]},
                     attrs={"ref_level": ref_level})
    return out


def sequence_expand_as(x, y, seq_lens=None):
    helper = LayerHelper("sequence_expand_as")
    out = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": [x], "Y": [y]}
    if seq_lens is not None:
        ins["SeqLens"] = [seq_lens]
    helper.append_op("sequence_expand_as", inputs=ins, outputs={"Out": [out]})
    return out


def sequence_concat(input, seq_lens=None, name=None):
    """input: list of [B,Ti,D]; seq_lens: matching list of [B] length
    tensors. Returns (Out [B, sum Ti, D], NewLens [B])."""
    helper = LayerHelper("sequence_concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    new_lens = helper.create_variable_for_type_inference("int32")
    ins = {"X": list(input)}
    if seq_lens is not None:
        ins["SeqLens"] = list(seq_lens)
    helper.append_op("sequence_concat", inputs=ins,
                     outputs={"Out": [out], "NewLens": [new_lens]})
    return out, new_lens


def sequence_reverse(x, seq_lens=None, name=None):
    helper = LayerHelper("sequence_reverse", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sequence_reverse", inputs=_seq_inputs(x, seq_lens),
                     outputs={"Y": [out]})
    return out


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper("sequence_slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    new_lens = helper.create_variable_for_type_inference("int32")
    helper.append_op("sequence_slice",
                     inputs={"X": [input], "Offset": [offset],
                             "Length": [length]},
                     outputs={"Out": [out], "NewLens": [new_lens]})
    return out


def sequence_erase(input, tokens, seq_lens=None, name=None):
    helper = LayerHelper("sequence_erase", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    new_lens = helper.create_variable_for_type_inference("int32")
    helper.append_op("sequence_erase", inputs=_seq_inputs(input, seq_lens),
                     outputs={"Out": [out], "NewLens": [new_lens]},
                     attrs={"tokens": list(tokens)})
    return out, new_lens


def sequence_enumerate(input, win_size, pad_value=0, seq_lens=None,
                       name=None):
    helper = LayerHelper("sequence_enumerate", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("sequence_enumerate",
                     inputs=_seq_inputs(input, seq_lens),
                     outputs={"Out": [out]},
                     attrs={"win_size": win_size, "pad_value": pad_value})
    return out


def sequence_pad(x, pad_value=None, maxlen=None, seq_lens=None, name=None):
    helper = LayerHelper("sequence_pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    length = helper.create_variable_for_type_inference("int32")
    ins = _seq_inputs(x, seq_lens)
    attrs = {"padded_length": int(maxlen) if maxlen is not None else -1}
    if pad_value is not None and not hasattr(pad_value, "name"):
        attrs["pad_value"] = float(pad_value)
    elif pad_value is not None:
        ins["PadValue"] = [pad_value]
    helper.append_op("sequence_pad", inputs=ins,
                     outputs={"Out": [out], "Length": [length]}, attrs=attrs)
    return out, length


def sequence_unpad(x, length, name=None):
    helper = LayerHelper("sequence_unpad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    out_len = helper.create_variable_for_type_inference("int32")
    helper.append_op("sequence_unpad",
                     inputs={"X": [x], "Length": [length]},
                     outputs={"Out": [out], "Length": [out_len]})
    return out


def sequence_reshape(input, new_dim, seq_lens=None):
    helper = LayerHelper("sequence_reshape")
    out = helper.create_variable_for_type_inference(input.dtype)
    new_lens = helper.create_variable_for_type_inference("int32")
    helper.append_op("sequence_reshape", inputs=_seq_inputs(input, seq_lens),
                     outputs={"Out": [out], "NewLens": [new_lens]},
                     attrs={"new_dim": new_dim})
    return out


def sequence_mask(x, maxlen, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("sequence_mask", inputs={"X": [x]},
                     outputs={"Y": [out]},
                     attrs={"maxlen": maxlen, "out_dtype": dtype})
    return out


def edit_distance(input, label, normalized=True, input_length=None,
                  label_length=None, name=None):
    """reference: nn.py edit_distance (operators/edit_distance_op.cc)."""
    helper = LayerHelper("edit_distance", name=name)
    out = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int32")
    ins = {"Hyps": [input], "Refs": [label]}
    if input_length is not None:
        ins["HypsLens"] = [input_length]
    if label_length is not None:
        ins["RefsLens"] = [label_length]
    helper.append_op("edit_distance", inputs=ins,
                     outputs={"Out": [out], "SequenceNum": [seq_num]},
                     attrs={"normalized": normalized})
    return out, seq_num


def sequence_scatter(input, index, updates, seq_lens=None, name=None):
    """reference: nn.py sequence_scatter → sequence_scatter_op.cc (padded
    ids+updates per row with seq_lens replacing the updates LoD)."""
    helper = LayerHelper("sequence_scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "Ids": [index], "Updates": [updates]}
    if seq_lens is not None:
        inputs["SeqLens"] = [seq_lens]
    helper.append_op("sequence_scatter", inputs=inputs,
                     outputs={"Out": [out]})
    return out
