"""Control-flow DSL: While / Switch / IfElse / StaticRNN / DynamicRNN.

Capability parity with the reference's control-flow layer DSL
(reference: python/paddle/fluid/layers/control_flow.py — While, StaticRNN,
DynamicRNN, IfElse, Switch; lowered there to while_op/conditional_block
interpreted with per-iteration scopes, operators/controlflow/while_op.cc:50).

TPU-native redesign:
- While       -> `while` op -> lax.while_loop (non-differentiable loops)
- StaticRNN / DynamicRNN -> `scan` op -> lax.scan (differentiable; grads via
  lax.scan's VJP instead of while_grad's kept scopes, executor.cc:466)
- IfElse      -> dense both-branch compute + elementwise `select` (XLA-
  idiomatic replacement for the reference's batch gather/scatter split)
- Switch      -> chain of `cond` ops (lax.cond), first matching case wins
- DynamicRNN's variable-length handling uses padded [B, T, ...] + seq_lens
  masking (the segment-ids LoD redesign) instead of LoD shrinking batches.
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

from paddle_tpu.core import ir
from paddle_tpu.fluid import framework, unique_name
from paddle_tpu.fluid.layer_helper import LayerHelper


__all__ = [
    "While", "Switch", "IfElse", "StaticRNN", "DynamicRNN",
    "increment", "less_than", "less_equal", "greater_than", "greater_equal",
    "equal", "not_equal", "array_write", "array_read", "array_length",
    "create_array",
]


# ---------------------------------------------------------------------------
# small layer helpers used across the DSL
# ---------------------------------------------------------------------------

def increment(x, value=1.0, in_place=True):
    """reference: layers/control_flow.py increment / increment_op.cc."""
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("increment", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"step": float(value)})
    return out


# comparison layers are the shared implementations from layers.ops
# (single source; reference keeps them in layers/control_flow.py)
from paddle_tpu.fluid.layers.ops import (  # noqa: E402,F401
    equal, greater_equal, greater_than, less_equal, less_than, not_equal)


# ---------------------------------------------------------------------------
# tensor arrays (fixed capacity — see ops/control_flow.py)
# ---------------------------------------------------------------------------

def create_array(dtype, capacity, elem_shape):
    """Create a fixed-capacity tensor array as a [capacity, *elem_shape]
    tensor (reference: layers/control_flow.py create_array; redesigned with
    declared capacity for XLA static shapes)."""
    from paddle_tpu.fluid.layers.tensor import fill_constant
    return fill_constant(shape=[capacity] + list(elem_shape), dtype=dtype,
                         value=0.0)


def array_write(x, i, array):
    """reference: layers/control_flow.py array_write."""
    helper = LayerHelper("array_write")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op("array_write",
                     inputs={"X": [x], "I": [i], "Array": [array]},
                     outputs={"Out": [out]})
    if array.shape is not None:
        out.desc.shape = list(array.shape)
    return out


def array_read(array, i):
    """reference: layers/control_flow.py array_read."""
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op("array_read", inputs={"Array": [array], "I": [i]},
                     outputs={"Out": [out]})
    if array.shape is not None:
        out.desc.shape = list(array.shape[1:])
    return out


def array_length(array):
    """reference: layers/control_flow.py array_length (returns capacity in
    the fixed-capacity design)."""
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("array_length", inputs={"Array": [array]},
                     outputs={"Out": [out]})
    out.desc.shape = [1]
    return out


# ---------------------------------------------------------------------------
# sub-block dataflow analysis
# ---------------------------------------------------------------------------

def _ancestor_has_var(program: framework.Program, sub: ir.BlockDesc,
                      name: str) -> bool:
    if sub.parent_idx < 0:
        return False
    parent = program.desc.block(sub.parent_idx)
    return ir.find_var_recursive(program.desc, parent, name) is not None


def _analyze_subblock(program: framework.Program, sub_idx: int,
                      preset_defined=()):
    """Returns (external_reads, writes_to_outer): names the sub-block reads
    from ancestor blocks, and ancestor-block names it (re)assigns — the
    closure and the loop-carried state of the structured-control-flow op."""
    sub = program.desc.block(sub_idx)
    defined = set(preset_defined)
    external_reads: List[str] = []
    writes_to_outer: List[str] = []
    for op in sub.ops:
        for n in op.input_names():
            if n in defined or n in external_reads:
                continue
            if _ancestor_has_var(program, sub, n):
                external_reads.append(n)
        for n in op.output_names():
            defined.add(n)
            if _ancestor_has_var(program, sub, n) and n not in writes_to_outer:
                writes_to_outer.append(n)
    return external_reads, writes_to_outer


# ---------------------------------------------------------------------------
# While
# ---------------------------------------------------------------------------

class While:
    """reference: layers/control_flow.py While / while_op.cc:50.

    Lowered to lax.while_loop: ancestor vars assigned inside the body are
    the loop carry; the condition var must be reassigned in the body.
    Non-differentiable (use StaticRNN/DynamicRNN for trainable recurrence).

        i = fill_constant([1], "int64", 0)
        n = fill_constant([1], "int64", 10)
        cond = less_than(i, n)
        w = While(cond)
        with w.block():
            ... body ops assigning ancestor vars ...
            increment(i)
            less_than(i, n, cond=cond)
    """

    def __init__(self, cond, is_test=False, name=None):
        self.cond_var = cond
        self.helper = LayerHelper(name or "while")
        self.program = framework.default_main_program()

    @contextlib.contextmanager
    def block(self):
        parent_block = self.program.current_block()
        sub = self.program.create_block()
        try:
            yield
        finally:
            self.program.rollback()
        ext_reads, writes = _analyze_subblock(self.program, sub.idx)
        cond_name = self.cond_var.name
        if cond_name not in writes:
            raise ValueError(
                "While body never reassigns the condition variable "
                f"{cond_name!r} — the loop would not terminate. Assign it, "
                "e.g. less_than(i, n, cond=cond).")
        carry_vars = list(writes)
        x_vars = [n for n in ext_reads if n not in carry_vars]
        carry_parent = [parent_block.var_recursive(n) for n in carry_vars]
        parent_block.append_op(
            "while",
            inputs={"Condition": [self.cond_var],
                    "Carry": carry_parent,
                    "X": [parent_block.var_recursive(n) for n in x_vars]},
            outputs={"Out": carry_parent},
            attrs={"sub_block": sub.idx, "cond_var": cond_name,
                   "carry_vars": carry_vars, "x_vars": x_vars})


# ---------------------------------------------------------------------------
# Switch (reference: layers/control_flow.py Switch — first matching case
# wins; used by learning-rate decay subgraphs)
# ---------------------------------------------------------------------------

class Switch:
    def __init__(self, name=None):
        self.helper = LayerHelper(name or "switch")
        self.program = framework.default_main_program()
        self.cases = []            # (cond Variable | None, sub block idx)
        self.inside = False

    @contextlib.contextmanager
    def case(self, condition):
        if self.inside:
            raise RuntimeError("nested Switch.case not allowed")
        self.inside = True
        sub = self.program.create_block()
        try:
            yield
        finally:
            self.program.rollback()
            self.inside = False
        self.cases.append((condition, sub.idx))

    @contextlib.contextmanager
    def default(self):
        with self.case(None):
            yield

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        helper = self.helper
        parent = self.program.current_block()
        # Exact first-match-wins gating: each case fires on
        # (its cond) AND NOT (any earlier cond); the default fires when no
        # case matched. At most one predicate is true, so differing write
        # sets across cases cannot interfere.
        matched = None  # symbolic "some earlier case matched"
        ordered = ([c for c in self.cases if c[0] is not None]
                   + [c for c in self.cases if c[0] is None])
        for cond_var, sub_idx in ordered:
            ext_reads, writes = _analyze_subblock(self.program, sub_idx)
            if cond_var is None:  # default case: fires when no case matched
                if matched is None:  # switch with only a default
                    from paddle_tpu.fluid.layers.tensor import fill_constant
                    eff = fill_constant(shape=[1], dtype="bool", value=True)
                else:
                    eff = self._not(matched)
            elif matched is None:
                eff = cond_var
                matched = cond_var
            else:
                eff = helper.create_variable_for_type_inference("bool")
                helper.append_op("logical_and",
                                 inputs={"X": [cond_var],
                                         "Y": [self._not(matched)]},
                                 outputs={"Out": [eff]})
                new_matched = helper.create_variable_for_type_inference("bool")
                helper.append_op("logical_or",
                                 inputs={"X": [matched], "Y": [cond_var]},
                                 outputs={"Out": [new_matched]})
                matched = new_matched
            if not writes:
                continue
            x_vars = list(dict.fromkeys(ext_reads + writes))
            out_parent = [parent.var_recursive(n) for n in writes]
            parent.append_op(
                "cond",
                inputs={"Cond": [eff],
                        "X": [parent.var_recursive(n) for n in x_vars]},
                outputs={"Out": out_parent},
                attrs={"sub_block_true": sub_idx, "sub_block_false": -1,
                       "out_vars": list(writes), "x_vars": x_vars})
        return False

    def _not(self, v):
        out = self.helper.create_variable_for_type_inference("bool")
        self.helper.append_op("logical_not", inputs={"X": [v]},
                              outputs={"Out": [out]})
        return out


# ---------------------------------------------------------------------------
# IfElse (reference: layers/control_flow.py IfElse — splits the batch by a
# [B,1] bool mask, runs each sub-net on its rows, merges. XLA redesign:
# both branches compute densely on the full batch; outputs merge with
# elementwise select. Identical results for row-wise branch nets (the
# common case); DIVERGENCE: ops that mix rows (reduce_*, batch_norm)
# see the full batch here but only their masked subset in the reference.
# ---------------------------------------------------------------------------

class IfElse:
    OUT_IF_ELSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        self.cond = cond
        self.helper = LayerHelper(name or "ifelse")
        self._phase: Optional[bool] = None
        self._outputs: Dict[bool, List[framework.Variable]] = {True: [], False: []}

    @contextlib.contextmanager
    def true_block(self):
        self._phase = True
        try:
            yield
        finally:
            self._phase = None

    @contextlib.contextmanager
    def false_block(self):
        self._phase = False
        try:
            yield
        finally:
            self._phase = None

    def input(self, x):
        if self._phase is None:
            raise RuntimeError("IfElse.input() must be called inside "
                               "true_block()/false_block()")
        return x

    def output(self, *outs):
        if self._phase is None:
            raise RuntimeError("IfElse.output() must be called inside "
                               "true_block()/false_block()")
        self._outputs[self._phase].extend(outs)

    def __call__(self):
        t, f = self._outputs[True], self._outputs[False]
        if len(t) != len(f):
            raise ValueError(
                f"IfElse true_block produced {len(t)} outputs but "
                f"false_block produced {len(f)}")
        merged = []
        for tv, fv in zip(t, f):
            out = self.helper.create_variable_for_type_inference(tv.dtype)
            self.helper.append_op(
                "select", inputs={"Condition": [self.cond], "X": [tv],
                                  "Y": [fv]},
                outputs={"Out": [out]})
            if tv.shape is not None:
                out.desc.shape = list(tv.shape)
            merged.append(out)
        return merged


# ---------------------------------------------------------------------------
# StaticRNN (reference: layers/control_flow.py StaticRNN — fixed-length
# recurrence over axis 0, [T, B, ...] inputs)
# ---------------------------------------------------------------------------

class StaticRNN:
    BEFORE_RNN, IN_RNN, AFTER_RNN = range(3)

    def __init__(self, name=None):
        self.helper = LayerHelper(name or "static_rnn")
        self.program = framework.default_main_program()
        self.status = self.BEFORE_RNN
        self.seq_inputs = []     # (parent seq var [T, ...], body var name)
        self.memories = []       # dict: body in name, parent init var, body out name
        self.step_outputs = []   # (body var, parent stacked var)
        self._sub = None
        self._parent_block = None
        self._seq_len = None

    @contextlib.contextmanager
    def step(self):
        self._parent_block = self.program.current_block()
        self._sub = self.program.create_block()
        self.status = self.IN_RNN
        try:
            yield
        finally:
            self.program.rollback()
            self.status = self.AFTER_RNN
            self._complete()

    def _in_rnn(self):
        if self.status != self.IN_RNN:
            raise RuntimeError("must be called inside StaticRNN.step()")

    def step_input(self, x):
        """x: [T, ...] ancestor var; returns the per-step [ ... ] slice."""
        self._in_rnn()
        if x.shape is None or len(x.shape) < 1 or x.shape[0] == -1:
            raise ValueError(
                f"StaticRNN.step_input needs a static leading time dim, got "
                f"shape {x.shape} for {x.name!r} (XLA static-shape regime)")
        if self._seq_len is None:
            self._seq_len = x.shape[0]
        elif self._seq_len != x.shape[0]:
            raise ValueError("all step_inputs must share the time length")
        body = self.program.current_block().create_var(
            name=unique_name.generate(self.helper.name + ".step_in"),
            shape=list(x.shape[1:]), dtype=x.dtype)
        self.seq_inputs.append((x, body.name))
        return body

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=0):
        """reference: StaticRNN.memory — carried state; init from a parent
        var or zero-filled like batch_ref."""
        self._in_rnn()
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory() needs init= or (shape=, batch_ref=)")
            # batch_ref may be a per-step body var (a step_input slice);
            # the init op must live in the parent block, so map it back to
            # its parent sequence var — whose batch dim sits after time
            ref, ref_dim = batch_ref, ref_batch_dim_idx
            for parent_x, body_name in self.seq_inputs:
                if batch_ref.name == body_name:
                    ref, ref_dim = parent_x, ref_batch_dim_idx + 1
                    break
            init = self._parent_block.create_var(
                name=unique_name.generate(self.helper.name + ".mem_init"),
                shape=[-1] + list(shape), dtype=ref.dtype)
            self._parent_block.append_op(
                "fill_constant_batch_size_like",
                inputs={"Input": [ref]}, outputs={"Out": [init]},
                attrs={"shape": [-1] + list(shape), "value": init_value,
                       "dtype": ref.dtype,
                       "input_dim_idx": ref_dim,
                       "output_dim_idx": init_batch_dim_idx})
        body = self.program.current_block().create_var(
            name=unique_name.generate(self.helper.name + ".mem"),
            shape=list(init.shape) if init.shape else None, dtype=init.dtype)
        self.memories.append({"in": body.name, "init": init, "out": None})
        return body

    def update_memory(self, mem, var):
        self._in_rnn()
        for m in self.memories:
            if m["in"] == mem.name:
                m["out"] = var.name
                return
        raise ValueError(f"{mem.name!r} is not a memory of this StaticRNN")

    def step_output(self, o):
        self._in_rnn()
        stacked = self._parent_block.create_var(
            name=unique_name.generate(self.helper.name + ".out"),
            shape=[self._seq_len] + list(o.shape or []), dtype=o.dtype)
        self.step_outputs.append((o, stacked))

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete(self):
        for m in self.memories:
            if m["out"] is None:
                raise ValueError("every memory needs update_memory()")
        preset = [n for _, n in self.seq_inputs] + [m["in"] for m in self.memories]
        ext_reads, writes = _analyze_subblock(self.program, self._sub.idx,
                                              preset_defined=preset)
        x_vars = [n for n in ext_reads]
        final_carries = [
            self._parent_block.create_var(
                name=unique_name.generate(self.helper.name + ".final_mem"),
                shape=list(m["init"].shape) if m["init"].shape else None,
                dtype=m["init"].dtype)
            for m in self.memories]
        self._parent_block.append_op(
            "scan",
            inputs={"ScanIn": [x for x, _ in self.seq_inputs],
                    "Carry": [m["init"] for m in self.memories],
                    "X": [self._parent_block.var_recursive(n) for n in x_vars]},
            outputs={"Out": [s for _, s in self.step_outputs],
                     "FinalCarry": final_carries},
            attrs={"sub_block": self._sub.idx,
                   "scan_in_vars": [n for _, n in self.seq_inputs],
                   "carry_in_vars": [m["in"] for m in self.memories],
                   "carry_out_vars": [m["out"] for m in self.memories],
                   "scan_out_vars": [o.name for o, _ in self.step_outputs],
                   "x_vars": x_vars})
        self._final_carries = final_carries

    def __call__(self):
        outs = [s for _, s in self.step_outputs]
        return outs[0] if len(outs) == 1 else outs


# ---------------------------------------------------------------------------
# DynamicRNN (reference: layers/control_flow.py DynamicRNN — variable-length
# recurrence driven by LoD; here: padded [B, T, ...] + seq_lens [B] masking)
# ---------------------------------------------------------------------------

class DynamicRNN:
    """Variable-length RNN over padded batches.

        drnn = DynamicRNN()
        with drnn.block():
            x_t = drnn.step_input(x, seq_lens)   # x: [B, T, D] -> [B, D]
            h = drnn.memory(shape=[H], value=0.0)
            h_new = some_layers(x_t, h)
            drnn.update_memory(h, h_new)         # masked past each row's len
            drnn.output(h_new)                   # zero-padded past len
        out = drnn()                             # [B, T, H]

    The reference shrinks the batch per timestep via LoDRankTable
    (layers/control_flow.py DynamicRNN, lod_rank_table); the TPU design
    keeps the batch dense and masks — constant shapes for XLA, grads flow
    through lax.scan's VJP.
    """

    def __init__(self, name=None):
        self.helper = LayerHelper(name or "dynamic_rnn")
        self.program = framework.default_main_program()
        self.inner = StaticRNN(name=(name or "dynamic_rnn") + ".scan")
        self.seq_lens = None
        self._mask = None        # [B, 1] float body var, 1.0 while t < len
        self._t = None
        self._max_len = None
        self._outputs = []
        self._batch_ref = None

    @contextlib.contextmanager
    def block(self):
        with self.inner.step():
            yield
        self._stacked = [s for _, s in self.inner.step_outputs]

    def step_input(self, x, seq_lens=None):
        """x: [B, T, ...]; seq_lens: [B] int lengths (None = all full T)."""
        if x.shape is None or len(x.shape) < 2 or x.shape[1] == -1:
            raise ValueError(
                f"DynamicRNN.step_input needs static T in [B, T, ...], got "
                f"{x.shape} for {x.name!r}")
        parent = self.inner._parent_block
        if self._max_len is None:
            self._max_len = x.shape[1]
            self._batch_ref = x
        # transpose to [T, B, ...] in the parent block for the scan
        perm = [1, 0] + list(range(2, len(x.shape)))
        xt = parent.create_var(
            name=unique_name.generate(self.helper.name + ".xt"),
            shape=[x.shape[1], x.shape[0]] + list(x.shape[2:]), dtype=x.dtype)
        xshape = parent.create_var(
            name=unique_name.generate(self.helper.name + ".xt_shape"),
            shape=[0] + list(x.shape), dtype=x.dtype, stop_gradient=True)
        parent.append_op("transpose2", inputs={"X": [x]},
                         outputs={"Out": [xt], "XShape": [xshape]},
                         attrs={"axis": perm})
        if seq_lens is not None and self.seq_lens is None:
            self.seq_lens = seq_lens
        return self.inner.step_input(xt)

    def _ensure_mask(self):
        """Body-side [B, 1] validity mask from the step counter (an implicit
        int32 memory incremented each step) and seq_lens."""
        if self._mask is not None:
            return self._mask
        from paddle_tpu.fluid.layers.tensor import fill_constant
        sub_block = self.program.current_block()
        if self._t is None:
            # step counter: carried int32 scalar, init 0 (parent side)
            with _block_guard(self.program, self.inner._parent_block.idx):
                t0 = fill_constant(shape=[1], dtype="int32", value=0)
            t = self.inner.memory(init=t0)
            t_next = sub_block.create_var(
                name=unique_name.generate(self.helper.name + ".t_next"),
                shape=[1], dtype="int32")
            sub_block.append_op("increment", inputs={"X": [t]},
                                outputs={"Out": [t_next]}, attrs={"step": 1})
            self.inner.update_memory(t, sub_block.var(t_next.name))
            self._t = t
        if self.seq_lens is None:
            mask = fill_constant(shape=[1], dtype="bool", value=True)
        else:
            mask_flat = less_than(self._t, self.seq_lens)  # [B] bool
            helper = LayerHelper(self.helper.name + ".mask")
            mask = helper.create_variable_for_type_inference("bool")
            helper.append_op("reshape2", inputs={"X": [mask_flat]},
                             outputs={"Out": [mask]},
                             attrs={"shape": [-1, 1]})
            mask.desc.dtype = "bool"
        self._mask = mask
        return mask

    def memory(self, init=None, shape=None, value=0.0, dtype="float32"):
        if init is not None:
            return self.inner.memory(init=init)
        if self._batch_ref is None:
            raise RuntimeError("call step_input() before memory(shape=...)")
        return self.inner.memory(shape=shape, batch_ref=self._batch_ref,
                                 init_value=value, ref_batch_dim_idx=0)

    def update_memory(self, mem, var):
        """Masked update: rows past their sequence length keep the old
        state, so the final memory is the last *valid* state per row."""
        mask = self._ensure_mask()
        helper = LayerHelper(self.helper.name + ".sel")
        sel = helper.create_variable_for_type_inference(var.dtype)
        helper.append_op("select",
                         inputs={"Condition": [mask], "X": [var], "Y": [mem]},
                         outputs={"Out": [sel]})
        if var.shape is not None:
            sel.desc.shape = list(var.shape)
        self.inner.update_memory(mem, sel)

    def output(self, *outs):
        """Outputs are zeroed past each row's length (padded positions)."""
        mask = self._ensure_mask()
        for o in outs:
            helper = LayerHelper(self.helper.name + ".outsel")
            zeros = helper.create_variable_for_type_inference(o.dtype)
            helper.append_op("fill_zeros_like", inputs={"X": [o]},
                             outputs={"Out": [zeros]})
            if o.shape is not None:
                zeros.desc.shape = list(o.shape)
            masked = helper.create_variable_for_type_inference(o.dtype)
            helper.append_op("select",
                            inputs={"Condition": [mask], "X": [o],
                                    "Y": [zeros]},
                            outputs={"Out": [masked]})
            if o.shape is not None:
                masked.desc.shape = list(o.shape)
            self.inner.step_output(masked)

    def __call__(self):
        """Stacked outputs transposed back to [B, T, ...]."""
        outs = []
        parent = self.inner._parent_block
        for _, stacked in self.inner.step_outputs:
            shp = list(stacked.shape)
            perm = [1, 0] + list(range(2, len(shp)))
            out = parent.create_var(
                name=unique_name.generate(self.helper.name + ".out_bt"),
                shape=[shp[1], shp[0]] + shp[2:], dtype=stacked.dtype)
            xshape = parent.create_var(
                name=unique_name.generate(self.helper.name + ".out_shape"),
                shape=[0] + shp, dtype=stacked.dtype, stop_gradient=True)
            parent.append_op("transpose2", inputs={"X": [stacked]},
                             outputs={"Out": [out], "XShape": [xshape]},
                             attrs={"axis": perm})
            outs.append(out)
        return outs[0] if len(outs) == 1 else outs


@contextlib.contextmanager
def _block_guard(program: framework.Program, block_idx: int):
    """Temporarily redirect layer appends to `block_idx`."""
    old = program._current_block_idx
    program._current_block_idx = block_idx
    try:
        yield
    finally:
        program._current_block_idx = old


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """reference: control_flow.py Print → print_op.cc (identity + host
    print via jax.debug.print)."""
    from paddle_tpu.fluid.layer_helper import LayerHelper
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("print", inputs={"In": [input]},
                     outputs={"Out": [out]},
                     attrs={"message": message or ""})
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    """reference: control_flow.py reorder_lod_tensor_by_rank →
    reorder_lod_tensor_by_rank_op.cc."""
    from paddle_tpu.fluid.layer_helper import LayerHelper
    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("reorder_lod_tensor_by_rank",
                     inputs={"X": [x], "RankTable": [rank_table]},
                     outputs={"Out": [out]})
    return out


def tensor_array_to_tensor(input, axis=1, name=None):
    """reference: tensor.py tensor_array_to_tensor (concat an array)."""
    from paddle_tpu.fluid.layer_helper import LayerHelper
    helper = LayerHelper("tensor_array_to_tensor", name=name)
    out = helper.create_variable_for_type_inference("float32")
    idx = helper.create_variable_for_type_inference("int32")
    xs = input if isinstance(input, (list, tuple)) else [input]
    helper.append_op("tensor_array_to_tensor", inputs={"X": list(xs)},
                     outputs={"Out": [out], "OutIndex": [idx]},
                     attrs={"axis": axis})
    return out, idx
