"""Neural-network layers (reference: python/paddle/fluid/layers/nn.py —
~160 functions; fc :191, embedding :300, conv2d :1753, batch_norm :2713,
pool2d, dropout, layer_norm, softmax_with_cross_entropy, topk ...).

Each layer builds IR ops via LayerHelper; parameters are created with the
two-program convention. The op set lowers to JAX/XLA (see paddle_tpu.ops),
so an `fc` is a single MXU matmul with a fused bias/activation epilogue.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from paddle_tpu.fluid import framework
from paddle_tpu.fluid.layer_helper import LayerHelper
from paddle_tpu.fluid.initializer import ConstantInitializer


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, is_test=False, name=None):
    """reference: nn.py:191 — mul (+ sum for multi-input) + bias + act."""
    helper = LayerHelper("fc", name=name)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    mul_results = []
    for inp in inputs:
        in_shape = inp.shape
        param_shape = [int(np.prod(in_shape[num_flatten_dims:]))] + [size]
        w = helper.create_parameter(param_attr, shape=param_shape, dtype=inp.dtype)
        tmp = helper.create_variable_for_type_inference(inp.dtype)
        helper.append_op("mul", inputs={"X": [inp], "Y": [w]},
                         outputs={"Out": [tmp]},
                         attrs={"x_num_col_dims": num_flatten_dims,
                                "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(inputs[0].dtype)
        helper.append_op("sum", inputs={"X": mul_results},
                         outputs={"Out": [pre_bias]})
    pre_act = helper.append_bias_op(pre_bias, bias_attr, size,
                                    dim_start=num_flatten_dims)
    return helper.append_activation(pre_act, act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """reference: nn.py:300 — lookup_table. is_sparse/is_distributed are the
    pserver-sharded-table capability: on TPU the table shards over the mesh
    model axis (see paddle_tpu.parallel) and the gather is an all-to-all;
    the flags are accepted and recorded as sharding hints."""
    helper = LayerHelper("embedding")
    w = helper.create_parameter(param_attr, shape=list(size), dtype=dtype)
    if is_distributed or is_sparse:
        # record the sharding hint: table rows split over the mesh model
        # axis (resolved by DistributeConfig._axes_for; the TPU form of the
        # pserver-sharded table, distribute_transpiler.py:1051
        # _init_splited_vars + parameter_prefetch.h:26)
        w.desc.attrs["dist_hint"] = ["__model__"] + \
            [None] * (len(size) - 1)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "lookup_table", inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [out]},
        attrs={"is_sparse": is_sparse, "is_distributed": is_distributed,
               "padding_idx": -1 if padding_idx is None else padding_idx})
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    """reference: nn.py:1753 — NCHW conv; use_cudnn accepted for parity
    (XLA autotunes, conv_cudnn_op.cu.cc has no TPU analogue)."""
    helper = LayerHelper("conv2d", name=name)
    num_channels = input.shape[1]
    fsize = filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 2
    filter_shape = [num_filters, num_channels // groups] + list(fsize)
    std = (2.0 / (fsize[0] * fsize[1] * num_channels)) ** 0.5
    from paddle_tpu.fluid.initializer import NormalInitializer
    w = helper.create_parameter(param_attr, shape=filter_shape,
                                dtype=input.dtype,
                                default_initializer=NormalInitializer(0.0, std))
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "conv2d", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": _pair(stride), "paddings": _pair(padding),
               "dilations": _pair(dilation), "groups": groups})
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, shape=[num_filters],
                                    dtype=input.dtype, is_bias=True)
        with_b = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("elementwise_add", inputs={"X": [out], "Y": [b]},
                         outputs={"Out": [with_b]}, attrs={"axis": 1})
        out = with_b
    return helper.append_activation(out, act)


def _transpose_filter_size(filter_size, output_size, in_spatial, stride,
                           padding, dilation, nd):
    """reference: nn.py conv2d_transpose — when filter_size is omitted,
    derive it from output_size:
    f[i] = (out[i] + 2*pad[i] - (in[i]-1)*stride[i] - 1) // dil[i] + 1."""
    if filter_size is not None:
        return (list(filter_size) if isinstance(filter_size, (list, tuple))
                else [filter_size] * nd)
    if output_size is None:
        raise ValueError(
            "conv_transpose: give filter_size or output_size")
    out = (list(output_size) if isinstance(output_size, (list, tuple))
           else [output_size] * nd)
    pad = padding if isinstance(padding, (list, tuple)) else [padding] * nd
    st = stride if isinstance(stride, (list, tuple)) else [stride] * nd
    dil = dilation if isinstance(dilation, (list, tuple)) else [dilation] * nd
    return [(out[i] + 2 * pad[i] - (in_spatial[i] - 1) * st[i] - 1)
            // dil[i] + 1 for i in range(nd)]


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv2d_transpose", name=name)
    num_channels = input.shape[1]
    fsize = _transpose_filter_size(filter_size, output_size, input.shape[2:],
                                   stride, padding, dilation, 2)
    filter_shape = [num_channels, num_filters // groups] + list(fsize)
    w = helper.create_parameter(param_attr, shape=filter_shape, dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "conv2d_transpose", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": _pair(stride), "paddings": _pair(padding),
               "dilations": _pair(dilation), "groups": groups})
    if bias_attr is not False:
        out = helper.append_bias_op(out, bias_attr, num_filters, dim_start=1)
    return helper.append_activation(out, act)


def _pair(v):
    return list(v) if isinstance(v, (list, tuple)) else [v, v]


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False,
           exclusive=True, name=None):
    """reference: nn.py pool2d → pool_op.cc."""
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "pool2d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": _pair(pool_size),
               "strides": _pair(pool_stride), "paddings": _pair(pool_padding),
               "global_pooling": global_pooling, "ceil_mode": ceil_mode,
               "exclusive": exclusive})
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=False,
               use_global_stats=False):
    """reference: nn.py:2713 → batch_norm_op.cc. Scale/Bias trainable;
    Mean/Variance are persistable running stats updated in the compiled step
    (written back to the Scope by the executor's state-return path)."""
    helper = LayerHelper("batch_norm", name=name)
    c = input.shape[1]
    scale = helper.create_parameter(param_attr, shape=[c], dtype=input.dtype,
                                    default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, shape=[c], dtype=input.dtype,
                                   is_bias=True)
    from paddle_tpu.fluid import unique_name
    mean_name = moving_mean_name or unique_name.generate(helper.name + ".mean")
    var_name = moving_variance_name or unique_name.generate(helper.name + ".var")
    block = helper.main_program.global_block()
    mean = block.create_var(name=mean_name, shape=[c], dtype=input.dtype,
                            persistable=True, stop_gradient=True)
    variance = block.create_var(name=var_name, shape=[c], dtype=input.dtype,
                                persistable=True, stop_gradient=True)
    sb = helper.startup_program.global_block()
    if not sb.has_var(mean_name):
        ConstantInitializer(0.0)(sb.create_var(name=mean_name, shape=[c],
                                               dtype=input.dtype, persistable=True), sb)
        ConstantInitializer(1.0)(sb.create_var(name=var_name, shape=[c],
                                               dtype=input.dtype, persistable=True), sb)
    saved_mean = helper.create_variable_for_type_inference(input.dtype)
    saved_var = helper.create_variable_for_type_inference(input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [variance]},
        outputs={"Y": [out], "MeanOut": [mean], "VarianceOut": [variance],
                 "SavedMean": [saved_mean], "SavedVariance": [saved_var]},
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
               "use_global_stats": use_global_stats})
    return helper.append_activation(out, act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    """reference: nn.py layer_norm → layer_norm_op.cc."""
    helper = LayerHelper("layer_norm", name=name)
    norm_size = int(np.prod(input.shape[begin_norm_axis:]))
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(param_attr, shape=[norm_size],
                                    dtype=input.dtype,
                                    default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(bias_attr, shape=[norm_size],
                                    dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(input.dtype)
    mean = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("layer_norm", inputs=inputs,
                     outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
                     attrs={"begin_norm_axis": begin_norm_axis,
                            "epsilon": epsilon})
    return helper.append_activation(out, act)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("dropout", inputs={"X": [x]},
                     outputs={"Out": [out], "Mask": [mask]},
                     attrs={"dropout_prob": dropout_prob, "is_test": is_test,
                            "seed": seed or 0,
                            "dropout_implementation": dropout_implementation})
    return out


# -- losses -----------------------------------------------------------------

def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("cross_entropy",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, label_smoothing=0.0):
    """label_smoothing (extension beyond the reference op): uniform-prior
    smoothing folded into the loss in closed form — equivalent to
    one_hot + label_smooth + soft_label CE but without materializing the
    [N, V] one-hot (several full-width passes at large V)."""
    if soft_label and label_smoothing:
        raise ValueError(
            "label_smoothing applies to hard integer labels; for soft "
            "labels smooth the distribution yourself (layers.label_smooth)")
    helper = LayerHelper("softmax_with_cross_entropy")
    loss = helper.create_variable_for_type_inference(logits.dtype)
    softmax = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op("softmax_with_cross_entropy",
                     inputs={"Logits": [logits], "Label": [label]},
                     outputs={"Loss": [loss], "Softmax": [softmax]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index,
                            "label_smoothing": float(label_smoothing)})
    if return_softmax:
        return loss, softmax
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sigmoid_cross_entropy_with_logits",
                     inputs={"X": [x], "Label": [label]},
                     outputs={"Out": [out]},
                     attrs={"ignore_index": ignore_index,
                            "normalize": normalize})
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("square_error_cost",
                     inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out]})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32"):
    helper = LayerHelper("label_smooth")
    out = helper.create_variable_for_type_inference(dtype)
    ins = {"X": [label]}
    if prior_dist is not None:
        ins["PriorDist"] = [prior_dist]
    helper.append_op("label_smooth", inputs=ins, outputs={"Out": [out]},
                     attrs={"epsilon": epsilon})
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    residual = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("huber_loss", inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out], "Residual": [residual]},
                     attrs={"delta": delta})
    return out


# -- reductions / elementwise / math ----------------------------------------

def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def _reduce(op, input, dim, keep_dim, name):
    helper = LayerHelper(op, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    if dim is None:
        attrs = {"reduce_all": True, "keep_dim": keep_dim}
    else:
        attrs = {"dim": dim if isinstance(dim, (list, tuple)) else [dim],
                 "keep_dim": keep_dim}
    helper.append_op(op, inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim, name)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1):
    helper = LayerHelper("mul")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("mul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"x_num_col_dims": x_num_col_dims,
                            "y_num_col_dims": y_num_col_dims})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("matmul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"transpose_X": transpose_x,
                            "transpose_Y": transpose_y, "alpha": alpha})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("scale", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"scale": float(scale), "bias": float(bias),
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out, act)


def softmax(input, use_cudnn=True, name=None):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("softmax", inputs={"X": [input]}, outputs={"Out": [out]})
    return out


def log(x):
    helper = LayerHelper("log")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("log", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64")
    helper.append_op("top_k", inputs={"X": [input]},
                     outputs={"Out": [values], "Indices": [indices]},
                     attrs={"k": k})
    return values, indices


# -- shape ------------------------------------------------------------------

def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("reshape", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape)})
    return helper.append_activation(out, act)


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("squeeze", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"axes": list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("unsqueeze", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"axes": list(axes)})
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("transpose", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": list(perm)})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    dim = dim % len(input.shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
        n_out = num
    else:
        num = 0
        sections = list(num_or_sections)
        n_out = len(sections)
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(n_out)]
    helper.append_op("split", inputs={"X": [input]}, outputs={"Out": outs},
                     attrs={"axis": dim, "num": num, "sections": sections})
    return outs


def stack(x, axis=0):
    helper = LayerHelper("stack")
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op("stack", inputs={"X": list(x)}, outputs={"Y": [out]},
                     attrs={"axis": axis})
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("expand", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"expand_times": list(expand_times)})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("slice", inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends)})
    return out


def gather(input, index):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gather", inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("one_hot", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"depth": depth})
    return out


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("clip", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"min": float(min), "max": float(max)})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("norm", inputs={"X": [x]},
                     outputs={"Out": [out], "Norm": [norm]},
                     attrs={"axis": axis, "epsilon": epsilon})
    return out


# -- metrics ----------------------------------------------------------------

def accuracy(input, label, k=1, correct=None, total=None):
    """reference: layers/metric_op.py accuracy — top_k + accuracy op."""
    helper = LayerHelper("accuracy")
    values, indices = topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference("float32")
    if correct is None:
        correct = helper.create_variable_for_type_inference("int32")
    if total is None:
        total = helper.create_variable_for_type_inference("int32")
    helper.append_op("accuracy",
                     inputs={"Out": [values], "Indices": [indices],
                             "Label": [label]},
                     outputs={"Accuracy": [acc_out], "Correct": [correct],
                              "Total": [total]})
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1, slide_steps=1):
    """reference: layers/metric_op.py auc — streaming stat vars persist in
    the scope and the op returns the running AUC."""
    helper = LayerHelper("auc")
    stat_shape = [num_thresholds + 1]
    stat_pos = helper.create_global_variable(stat_shape, "float32",
                                             persistable=True)
    stat_neg = helper.create_global_variable(stat_shape, "float32",
                                             persistable=True)
    sb = helper.startup_program.global_block()
    for v in (stat_pos, stat_neg):
        if not sb.has_var(v.name):
            ConstantInitializer(0.0)(
                sb.create_var(name=v.name, shape=stat_shape, dtype="float32",
                              persistable=True), sb)
    auc_out = helper.create_variable_for_type_inference("float32")
    helper.append_op("auc",
                     inputs={"Predict": [input], "Label": [label],
                             "StatPos": [stat_pos], "StatNeg": [stat_neg]},
                     outputs={"AUC": [auc_out], "StatPosOut": [stat_pos],
                              "StatNegOut": [stat_neg]},
                     attrs={"curve": curve, "num_thresholds": num_thresholds})
    return auc_out, [stat_pos, stat_neg]


def scaled_dot_product_attention(q, k, v, bias=None, causal=False,
                                 scale=None, sp="auto", sp_impl="ring",
                                 dropout_prob=0.0, layout="bhtd",
                                 name=None):
    """Fused attention over [B, H, T, D] tensors (TPU-native extension —
    the reference composes matmul+softmax+matmul; see ops.attention). With
    a mesh sp axis configured, computes ring attention / Ulysses over the
    sequence shards (parallel/ring_attention.py). dropout_prob applies
    attention-weight dropout (upscale_in_train — the reference's composed
    graph, dist_transformer.py:1044) inside the fused/flash kernels;
    disabled automatically in test-mode programs. layout="bthd" takes
    [B, T, H, D] tensors so the head split at the call site is a free
    reshape (no materialized transpose — parallel/ring_attention.py
    full_attention docstring)."""
    helper = LayerHelper("attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    ins = {"Q": [q], "K": [k], "V": [v]}
    if bias is not None:
        ins["Bias"] = [bias]
    helper.append_op("attention", inputs=ins, outputs={"Out": [out]},
                     attrs={"causal": causal, "scale": scale, "sp": sp,
                            "sp_impl": sp_impl, "layout": layout,
                            "dropout_prob": float(dropout_prob)})
    return out


def fused_multi_head_attention(q_in, kv_in, d_model, n_head, causal=False,
                               dropout_prob=0.0, param_attr=None,
                               name=None):
    """Whole attention block — q/k/v/out projections + scaled-dot
    attention — as ONE fused op (ops/attention_block.py): the custom VJP
    is spelled so no [B,T,H,D]↔[B,H,T,D] relayout is ever materialized,
    forward or backward (the composed graph's measured 7.4 ms/step copy
    band on Transformer-base, docs/performance.md). q_in [B,Tq,M],
    kv_in [B,Tk,M] (same var for self-attention) → [B,Tq,M].

    The reference composes this from fc+reshape+transpose+matmul+softmax
    (benchmark transformer prep); parameter names follow the fc
    convention so checkpoints keep the per-projection layout."""
    helper = LayerHelper("fused_multi_head_attention", name=name)
    if isinstance(param_attr, (list, tuple)):
        attrs4 = list(param_attr)           # one ParamAttr per projection
    elif param_attr is None:
        attrs4 = [None] * 4
    else:
        import copy
        attrs4 = []
        for tag in ("wq", "wk", "wv", "wo"):
            a = copy.deepcopy(param_attr)
            if a.name is not None:
                a.name = f"{a.name}.{tag}"
            attrs4.append(a)
    ws = [helper.create_parameter(a, shape=[d_model, d_model],
                                  dtype="float32") for a in attrs4]
    out = helper.create_variable_for_type_inference(q_in.dtype)
    helper.append_op("fused_attention_block",
                     inputs={"Xq": [q_in], "Xkv": [kv_in],
                             "Wq": [ws[0]], "Wk": [ws[1]],
                             "Wv": [ws[2]], "Wo": [ws[3]]},
                     outputs={"Out": [out]},
                     attrs={"n_head": int(n_head), "causal": bool(causal),
                            "dropout_prob": float(dropout_prob)})
    return out


def _attention_projection_params(helper, d_model, param_attr):
    """The four [M, M] projection weights, named exactly like
    fused_multi_head_attention's (``<base>.wq`` ... ``.wo``) so the same
    checkpoint/scope serves the training graph, the full-forward
    inference graph, AND the prefill/decode serving pair."""
    if isinstance(param_attr, (list, tuple)):
        attrs4 = list(param_attr)
    elif param_attr is None:
        attrs4 = [None] * 4
    else:
        import copy
        attrs4 = []
        for tag in ("wq", "wk", "wv", "wo"):
            a = copy.deepcopy(param_attr)
            if a.name is not None:
                a.name = f"{a.name}.{tag}"
            attrs4.append(a)
    return [helper.create_parameter(a, shape=[d_model, d_model],
                                    dtype="float32") for a in attrs4]


def kv_attention_prefill(x, d_model, n_head, cache_k, cache_v,
                         param_attr=None, name=None):
    """Causal self-attention over the whole (padded) prompt that ALSO
    populates the serving KV cache: ``cache_k``/``cache_v`` are
    persistable [B, S, H, D] vars this op writes (S from the var shape;
    CompiledBlock carries them into the serving scope, where the decode
    program reads them). x [B, T, M] -> [B, T, M]. Numerics identical to
    fused_multi_head_attention(causal=True) without dropout — a
    prefill+decode transcript matches the full-forward graph
    (ops/kv_attention.py; docs/serving.md)."""
    helper = LayerHelper("kv_attention_prefill", name=name)
    ws = _attention_projection_params(helper, d_model, param_attr)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("kv_attention_prefill",
                     inputs={"X": [x], "Wq": [ws[0]], "Wk": [ws[1]],
                             "Wv": [ws[2]], "Wo": [ws[3]]},
                     outputs={"Out": [out], "CacheK": [cache_k],
                              "CacheV": [cache_v]},
                     attrs={"n_head": int(n_head),
                            "cache_len": int(cache_k.shape[1])})
    return out


def kv_attention_prefill_slot(x, slot, d_model, n_head, pool_k, pool_v,
                              param_attr=None, name=None):
    """In-flight-batching prefill: causal self-attention over the prompt
    whose K/V rows are scattered into a LIVE pool cache
    (``pool_k``/``pool_v``, persistable [n_slots, S, H, D] vars, read
    and written under the same names — donated state) at the per-row
    ``slot`` indices, so a new request joins a running decode without
    disturbing the slots mid-flight. The whole [S, H, D] row is written
    (zeros beyond the prompt), so a reused slot never leaks its previous
    occupant. x [B, T, M], slot [B, 1] int -> [B, T, M]
    (ops/kv_attention.py; docs/serving.md)."""
    helper = LayerHelper("kv_attention_prefill_slot", name=name)
    ws = _attention_projection_params(helper, d_model, param_attr)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("kv_attention_prefill_slot",
                     inputs={"X": [x], "Wq": [ws[0]], "Wk": [ws[1]],
                             "Wv": [ws[2]], "Wo": [ws[3]],
                             "PoolK": [pool_k], "PoolV": [pool_v],
                             "Slot": [slot]},
                     outputs={"Out": [out], "PoolKOut": [pool_k],
                              "PoolVOut": [pool_v]},
                     attrs={"n_head": int(n_head)})
    return out


def kv_attention_decode(x, pos, seq_len, gen_start, active, d_model,
                        n_head, cache_k, cache_v, param_attr=None,
                        name=None):
    """One-token decode step over the static-shape KV cache with fully
    per-row geometry: writes each active row's k/v at its own ``pos``
    (in-place — the caches are read and written under the same names, so
    they are donated state) and attends over the per-row mask
    {j < seq_len} ∪ {gen_start <= j <= pos}; rows with ``active`` == 0
    (free decode slots) leave their cache row untouched. x [B, 1, M],
    pos/seq_len/gen_start/active [B, 1] int -> [B, 1, M]. The same
    executable serves every decode position and every join/leave mix —
    zero steady-state compiles (ops/kv_attention.py; docs/serving.md)."""
    helper = LayerHelper("kv_attention_decode", name=name)
    ws = _attention_projection_params(helper, d_model, param_attr)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("kv_attention_decode",
                     inputs={"X": [x], "Wq": [ws[0]], "Wk": [ws[1]],
                             "Wv": [ws[2]], "Wo": [ws[3]],
                             "CacheK": [cache_k], "CacheV": [cache_v],
                             "Pos": [pos], "SeqLen": [seq_len],
                             "GenStart": [gen_start],
                             "Active": [active]},
                     outputs={"Out": [out], "CacheKOut": [cache_k],
                              "CacheVOut": [cache_v]},
                     attrs={"n_head": int(n_head)})
    return out


def kv_attention_prefill_paged(x, rows, d_model, n_head, page_k, page_v,
                               page_ks=None, page_vs=None, codec="none",
                               param_attr=None, name=None):
    """Paged-pool prefill (ISSUE 17): causal self-attention over the
    prompt whose K/V rows scatter into the PAGED pool caches
    (``page_k``/``page_v``, persistable [n_pages, page_size, H, D] vars
    read and written under the same names — donated state) at the
    per-position flat row indices ``rows`` [B*T, 1] from the slot's
    page-table lease. Sentinel rows (>= n_pages*page_size) DROP — how
    prefix-SHARED pages are skipped (already resident, bit-identical:
    K/V at position j depends only on token j) and how copy-on-write
    stays a recompute, never a device copy. ``codec='int8'`` quantizes
    on write into ``page_ks``/``page_vs`` scale planes
    (ops/kv_attention.py; docs/serving.md 'Paged KV cache')."""
    helper = LayerHelper("kv_attention_prefill_paged", name=name)
    ws = _attention_projection_params(helper, d_model, param_attr)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x], "Wq": [ws[0]], "Wk": [ws[1]],
              "Wv": [ws[2]], "Wo": [ws[3]],
              "PageK": [page_k], "PageV": [page_v], "Rows": [rows]}
    outputs = {"Out": [out], "PageKOut": [page_k],
               "PageVOut": [page_v]}
    if codec == "int8":
        inputs["PageKS"], inputs["PageVS"] = [page_ks], [page_vs]
        outputs["PageKSOut"], outputs["PageVSOut"] = [page_ks], [page_vs]
    helper.append_op("kv_attention_prefill_paged",
                     inputs=inputs, outputs=outputs,
                     attrs={"n_head": int(n_head), "codec": str(codec)})
    return out


def kv_attention_decode_paged(x, page_table, pos, seq_len, gen_start,
                              active, d_model, n_head, page_k, page_v,
                              page_ks=None, page_vs=None, codec="none",
                              param_attr=None, name=None):
    """One-token decode over the PAGED KV pool: per-row geometry
    identical to ``kv_attention_decode``, but the cache row for logical
    position j of slot b resolves through the page-table feed
    (``page_table`` [B, max_pages] int — a STATIC-shape feed, so every
    join/leave/page mix dispatches the same executable, zero
    steady-state compiles). The gather runs the scalar-prefetch Pallas
    kernel on TPU (ops/pallas/paged_attention.py) and dequantizes
    in-gather under ``codec='int8'``. x [B, 1, M] -> [B, 1, M]
    (ops/kv_attention.py; docs/serving.md 'Paged KV cache')."""
    helper = LayerHelper("kv_attention_decode_paged", name=name)
    ws = _attention_projection_params(helper, d_model, param_attr)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x], "Wq": [ws[0]], "Wk": [ws[1]],
              "Wv": [ws[2]], "Wo": [ws[3]],
              "PageK": [page_k], "PageV": [page_v],
              "PageTable": [page_table], "Pos": [pos],
              "SeqLen": [seq_len], "GenStart": [gen_start],
              "Active": [active]}
    outputs = {"Out": [out], "PageKOut": [page_k],
               "PageVOut": [page_v]}
    if codec == "int8":
        inputs["PageKS"], inputs["PageVS"] = [page_ks], [page_vs]
        outputs["PageKSOut"], outputs["PageVSOut"] = [page_ks], [page_vs]
    helper.append_op("kv_attention_decode_paged",
                     inputs=inputs, outputs=outputs,
                     attrs={"n_head": int(n_head), "codec": str(codec)})
    return out


def kv_attention_verify(x, pos, seq_len, gen_start, active, win_len,
                        d_model, n_head, cache_k, cache_v,
                        param_attr=None, name=None):
    """Speculative-decode verify step (ISSUE 19) over the contiguous KV
    cache: score a [B, K+1] token window — position 0 the row's last
    committed token, positions 1..K the drafts — in ONE causal dispatch,
    writing window position i's k/v at cache row ``pos + i`` where
    ``active`` and ``i < win_len``. Position i attends over
    {j < seq_len} ∪ {gen_start <= j <= pos + i}, so its output is
    bit-identical to i sequential ``kv_attention_decode`` steps over the
    same tokens — the losslessness guarantee the engine's accept rule
    rests on. Rollback of rejected positions is overwrite-in-place: they
    sit above the committed frontier and the mask never admits them.
    x [B, K+1, M], pos/seq_len/gen_start/active/win_len [B, 1] int ->
    [B, K+1, M] (ops/kv_attention.py; docs/serving.md 'Speculative
    decoding')."""
    helper = LayerHelper("kv_attention_verify", name=name)
    ws = _attention_projection_params(helper, d_model, param_attr)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("kv_attention_verify",
                     inputs={"X": [x], "Wq": [ws[0]], "Wk": [ws[1]],
                             "Wv": [ws[2]], "Wo": [ws[3]],
                             "CacheK": [cache_k], "CacheV": [cache_v],
                             "Pos": [pos], "SeqLen": [seq_len],
                             "GenStart": [gen_start],
                             "Active": [active], "WinLen": [win_len]},
                     outputs={"Out": [out], "CacheKOut": [cache_k],
                              "CacheVOut": [cache_v]},
                     attrs={"n_head": int(n_head)})
    return out


def kv_attention_verify_paged(x, page_table, pos, seq_len, gen_start,
                              active, win_len, d_model, n_head, page_k,
                              page_v, page_ks=None, page_vs=None,
                              codec="none", param_attr=None, name=None):
    """Speculative-decode verify over the PAGED KV pool: window geometry
    identical to ``kv_attention_verify``, each window position's write
    row resolved through the page-table feed. Writes that fall past the
    slot's leased span resolve to the sentinel page and DROP — a draft
    window can never write another slot's pages (admission reserves the
    draft-window overshoot, ``PagePool.span_for(draft_window=K)``).
    x [B, K+1, M], page_table [B, max_pages] int,
    pos/seq_len/gen_start/active/win_len [B, 1] int -> [B, K+1, M]
    (ops/kv_attention.py; docs/serving.md 'Speculative decoding')."""
    helper = LayerHelper("kv_attention_verify_paged", name=name)
    ws = _attention_projection_params(helper, d_model, param_attr)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x], "Wq": [ws[0]], "Wk": [ws[1]],
              "Wv": [ws[2]], "Wo": [ws[3]],
              "PageK": [page_k], "PageV": [page_v],
              "PageTable": [page_table], "Pos": [pos],
              "SeqLen": [seq_len], "GenStart": [gen_start],
              "Active": [active], "WinLen": [win_len]}
    outputs = {"Out": [out], "PageKOut": [page_k],
               "PageVOut": [page_v]}
    if codec == "int8":
        inputs["PageKS"], inputs["PageVS"] = [page_ks], [page_vs]
        outputs["PageKSOut"], outputs["PageVSOut"] = [page_ks], [page_vs]
    helper.append_op("kv_attention_verify_paged",
                     inputs=inputs, outputs=outputs,
                     attrs={"n_head": int(n_head), "codec": str(codec)})
    return out


def token_sample(logits, temperature, top_k, seed, step_idx, name=None):
    """On-device next-token selection (ops/kv_attention.py): greedy
    argmax when ``temperature <= 0`` or ``top_k == 1`` (bit-identical to
    a host argmax over the same logits — the parity oracle), otherwise
    temperature-scaled top-k Gumbel sampling keyed ONLY by the
    per-request ``seed`` and the ``step_idx`` token index, so a sampled
    stream replays identically across processes and server restarts.
    logits [B, V]; temperature [B, 1] float; top_k [B, 1] int (<=0: no
    filter); seed/step_idx [B, 1] int -> [B, 1] int64."""
    helper = LayerHelper("token_sample", name=name)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("token_sample",
                     inputs={"Logits": [logits],
                             "Temperature": [temperature],
                             "TopK": [top_k], "Seed": [seed],
                             "StepIdx": [step_idx]},
                     outputs={"Out": [out]})
    return out


def fused_linear_cross_entropy(input, label, num_classes, label_smoothing=0.0,
                               ignore_index=-100, param_attr=None,
                               name=None):
    """Classifier head: `fc(input, num_classes)` + label-smoothed
    softmax-cross-entropy, fused so the [N, num_classes] logits never
    materialize in HBM (Pallas streaming kernel, ops/pallas/fused_ce.py;
    composed-op fallback off-TPU). input [N, D] (flatten upstream), label
    [N, 1] int. Returns per-row Loss [N, 1]. TPU-native extension of the
    reference's softmax_with_cross_entropy
    (softmax_with_cross_entropy_op.cc) that also fuses the projection."""
    helper = LayerHelper("fused_linear_ce", name=name)
    d = input.shape[-1]
    w = helper.create_parameter(param_attr, shape=[d, num_classes],
                                dtype="float32")
    loss = helper.create_variable_for_type_inference("float32")
    helper.append_op("fused_linear_ce",
                     inputs={"X": [input], "W": [w], "Label": [label]},
                     outputs={"Loss": [loss]},
                     attrs={"label_smoothing": float(label_smoothing),
                            "ignore_index": ignore_index})
    return loss


def cos_sim(X, Y, name=None):
    """reference: nn.py cos_sim / operators/cos_sim_op.cc."""
    helper = LayerHelper("cos_sim", name=name)
    out = helper.create_variable_for_type_inference(X.dtype)
    xn = helper.create_variable_for_type_inference(X.dtype)
    yn = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op("cos_sim", inputs={"X": [X], "Y": [Y]},
                     outputs={"Out": [out], "XNorm": [xn], "YNorm": [yn]})
    return out


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None):
    """reference: nn.py nce / operators/nce_op.cc — NCE loss with a uniform
    noise sampler. Returns the per-example Cost [B, 1]."""
    helper = LayerHelper("nce", name=name)
    dim = input.shape[1]
    w = helper.create_parameter(param_attr, shape=[num_total_classes, dim],
                                dtype=input.dtype)
    cost = helper.create_variable_for_type_inference(input.dtype)
    sample_logits = helper.create_variable_for_type_inference(input.dtype)
    sample_labels = helper.create_variable_for_type_inference("int32")
    ins = {"Input": [input], "Label": [label], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, shape=[num_total_classes],
                                    dtype=input.dtype, is_bias=True)
        ins["Bias"] = [b]
    if sample_weight is not None:
        ins["SampleWeight"] = [sample_weight]
    helper.append_op(
        "nce", inputs=ins,
        outputs={"Cost": [cost], "SampleLogits": [sample_logits],
                 "SampleLabels": [sample_labels]},
        attrs={"num_total_classes": num_total_classes,
               "num_neg_samples": num_neg_samples or 10})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    """reference: nn.py hsigmoid / operators/hierarchical_sigmoid_op.cc —
    complete-binary-tree hierarchical softmax cost [B, 1]."""
    helper = LayerHelper("hsigmoid", name=name)
    dim = input.shape[1]
    w = helper.create_parameter(param_attr, shape=[num_classes - 1, dim],
                                dtype=input.dtype)
    ins = {"X": [input], "Label": [label], "W": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, shape=[1, num_classes - 1],
                                    dtype=input.dtype, is_bias=True)
        ins["Bias"] = [b]
    out = helper.create_variable_for_type_inference(input.dtype)
    pre_out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "hierarchical_sigmoid", inputs=ins,
        outputs={"Out": [out], "PreOut": [pre_out]},
        attrs={"num_classes": num_classes})
    return out


def linear_chain_crf(input, label, seq_lens=None, param_attr=None, name=None):
    """reference: nn.py linear_chain_crf / operators/linear_chain_crf_op.cc.
    `input` is the padded emission [B, T, N] (+ seq_lens mask, the LoD
    replacement). Returns the per-sequence negative log-likelihood [B, 1];
    the learned Transition parameter is `<name>.w_0`-style and is what
    crf_decoding consumes."""
    helper = LayerHelper("linear_chain_crf", name=name)
    num_tags = input.shape[-1]
    transition = helper.create_parameter(param_attr,
                                         shape=[num_tags + 2, num_tags],
                                         dtype=input.dtype)
    ll = helper.create_variable_for_type_inference(input.dtype)
    alpha = helper.create_variable_for_type_inference(input.dtype)
    em_exps = helper.create_variable_for_type_inference(input.dtype)
    tr_exps = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Emission": [input], "Transition": [transition], "Label": [label]}
    if seq_lens is not None:
        ins["SeqLens"] = [seq_lens]
    helper.append_op(
        "linear_chain_crf", inputs=ins,
        outputs={"LogLikelihood": [ll], "Alpha": [alpha],
                 "EmissionExps": [em_exps], "TransitionExps": [tr_exps]})
    return ll


def crf_decoding(input, param_attr, label=None, seq_lens=None, name=None):
    """reference: nn.py crf_decoding / operators/crf_decoding_op.cc.
    `param_attr` must name the transition parameter created by
    linear_chain_crf (pass its ParamAttr)."""
    helper = LayerHelper("crf_decoding", name=name)
    from paddle_tpu.fluid.param_attr import ParamAttr
    attr = ParamAttr._to_attr(param_attr)
    transition = helper.main_program.global_block().var(attr.name)
    path = helper.create_variable_for_type_inference("int64")
    ins = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        ins["Label"] = [label]
    if seq_lens is not None:
        ins["SeqLens"] = [seq_lens]
    helper.append_op("crf_decoding", inputs=ins,
                     outputs={"ViterbiPath": [path]})
    return path


def chunk_eval(input, label, chunk_scheme, num_chunk_types, seq_lens=None,
               excluded_chunk_types=None):
    """reference: nn.py chunk_eval / operators/metrics/chunk_eval_op.cc.
    Returns (precision, recall, f1, num_infer, num_label, num_correct)."""
    helper = LayerHelper("chunk_eval")
    p = helper.create_variable_for_type_inference("float32")
    r = helper.create_variable_for_type_inference("float32")
    f1 = helper.create_variable_for_type_inference("float32")
    ni = helper.create_variable_for_type_inference("int64")
    nl = helper.create_variable_for_type_inference("int64")
    nc = helper.create_variable_for_type_inference("int64")
    ins = {"Inference": [input], "Label": [label]}
    if seq_lens is not None:
        ins["SeqLens"] = [seq_lens]
    helper.append_op(
        "chunk_eval", inputs=ins,
        outputs={"Precision": [p], "Recall": [r], "F1-Score": [f1],
                 "NumInferChunks": [ni], "NumLabelChunks": [nl],
                 "NumCorrectChunks": [nc]},
        attrs={"num_chunk_types": num_chunk_types,
               "chunk_scheme": chunk_scheme,
               "excluded_chunk_types": list(excluded_chunk_types or [])})
    return p, r, f1, ni, nl, nc


def beam_search(pre_ids, pre_scores, scores, beam_size, end_id, name=None):
    """reference: nn.py beam_search / operators/beam_search_op.cc. Dense
    [B, W] lane layout (see ops/beam_ops.py for the LoD divergence).
    Returns (selected_ids, selected_scores, parent_idx)."""
    helper = LayerHelper("beam_search", name=name)
    ids = helper.create_variable_for_type_inference("int32")
    sc = helper.create_variable_for_type_inference(scores.dtype)
    parent = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "beam_search",
        inputs={"PreIds": [pre_ids], "PreScores": [pre_scores],
                "Scores": [scores]},
        outputs={"SelectedIds": [ids], "SelectedScores": [sc],
                 "ParentIdx": [parent]},
        attrs={"beam_size": beam_size, "end_id": end_id})
    return ids, sc, parent


def beam_search_decode(ids, parent_idx, scores, end_id=0, name=None):
    """reference: nn.py beam_search_decode /
    operators/beam_search_decode_op.cc. `ids`/`parent_idx` are the stacked
    per-step selections [T, B, W]. Returns (sentence_ids [B, W, T],
    sentence_scores [B, W])."""
    helper = LayerHelper("beam_search_decode", name=name)
    sent = helper.create_variable_for_type_inference("int32")
    ssc = helper.create_variable_for_type_inference(scores.dtype)
    helper.append_op(
        "beam_search_decode",
        inputs={"Ids": [ids], "ParentIdx": [parent_idx],
                "Scores": [scores]},
        outputs={"SentenceIds": [sent], "SentenceScores": [ssc]},
        attrs={"end_id": end_id})
    return sent, ssc


# -- misc-batch layers (reference: layers/nn.py — multiplex, log_loss,
# rank_loss, margin_rank_loss, crop, pad2d, pad_constant_like, random_crop,
# add_position_encoding, similarity_focus, bilinear_tensor_product, row_conv,
# unstack, argsort, sampling_id, bpr_loss, squared_l2_distance) ------------

def argsort(x, axis=-1, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    idx = helper.create_variable_for_type_inference("int64")
    helper.append_op("argsort", inputs={"X": [x]},
                     outputs={"Out": [out], "Indices": [idx]},
                     attrs={"axis": axis})
    return out, idx


def multiplex(inputs, index):
    helper = LayerHelper("multiplex")
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op("multiplex", inputs={"X": list(inputs), "Ids": [index]},
                     outputs={"Out": [out]})
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    loss = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("log_loss",
                     inputs={"Predicted": [input], "Labels": [label]},
                     outputs={"Loss": [loss]}, attrs={"epsilon": epsilon})
    return loss


def rank_loss(label, left, right, name=None):
    helper = LayerHelper("rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op("rank_loss",
                     inputs={"Label": [label], "Left": [left],
                             "Right": [right]},
                     outputs={"Out": [out]})
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    act = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op("margin_rank_loss",
                     inputs={"Label": [label], "X1": [left], "X2": [right]},
                     outputs={"Out": [out], "Activated": [act]},
                     attrs={"margin": margin})
    return out


def bpr_loss(input, label, name=None):
    helper = LayerHelper("bpr_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("bpr_loss", inputs={"X": [input], "Label": [label]},
                     outputs={"Out": [out]})
    return out


def crop(x, shape=None, offsets=None, name=None):
    if shape is None:
        raise ValueError("crop() requires `shape` (a Variable whose shape is "
                         "the crop target, or a list of ints)")
    helper = LayerHelper("crop", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    attrs = {}
    if hasattr(shape, "desc"):          # a Variable reference shape
        inputs["Y"] = [shape]
    else:
        attrs["shape"] = list(shape)
    if offsets is not None:
        attrs["offsets"] = list(offsets)
    helper.append_op("crop", inputs=inputs, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("pad2d", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"paddings": list(paddings), "mode": mode,
                            "pad_value": pad_value,
                            "data_format": data_format})
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    helper = LayerHelper("pad_constant_like", name=name)
    out = helper.create_variable_for_type_inference(y.dtype)
    helper.append_op("pad_constant_like", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"pad_value": pad_value})
    return out


def random_crop(x, shape, seed=None):
    helper = LayerHelper("random_crop")
    out = helper.create_variable_for_type_inference(x.dtype)
    seed_out = helper.create_variable_for_type_inference("int64")
    helper.append_op("random_crop", inputs={"X": [x]},
                     outputs={"Out": [out], "SeedOut": [seed_out]},
                     attrs={"shape": list(shape), "seed": seed or 0})
    return out


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    helper = LayerHelper("add_position_encoding", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("add_position_encoding", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"alpha": alpha, "beta": beta})
    return out


def similarity_focus(input, axis, indexes, name=None):
    helper = LayerHelper("similarity_focus", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("similarity_focus", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"axis": axis, "indexes": list(indexes)})
    return out


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", name=name,
                         param_attr=param_attr, bias_attr=bias_attr)
    dx, dy = x.shape[-1], y.shape[-1]
    w = helper.create_parameter(attr=helper.param_attr, shape=[size, dx, dy],
                                dtype=x.dtype)
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if bias_attr is not False:
        bias = helper.create_parameter(attr=helper.bias_attr, shape=[1, size],
                                       dtype=x.dtype, is_bias=True)
        inputs["Bias"] = [bias]
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out]})
    return helper.append_activation(out, act)


def row_conv(input, future_context_size, seq_lens=None, param_attr=None,
             act=None):
    helper = LayerHelper("row_conv", param_attr=param_attr)
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[future_context_size + 1,
                                       input.shape[-1]],
                                dtype=input.dtype)
    inputs = {"X": [input], "Filter": [w]}
    if seq_lens is not None:
        inputs["SeqLens"] = [seq_lens]
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("row_conv", inputs=inputs, outputs={"Out": [out]})
    return helper.append_activation(out, act)


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    if num is None:
        num = x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype)
            for _ in range(num)]
    helper.append_op("unstack", inputs={"X": [x]}, outputs={"Y": outs},
                     attrs={"axis": axis, "num": num})
    return outs


def sampling_id(x, min=0.0, max=1.0, seed=0):
    helper = LayerHelper("sampling_id")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("sampling_id", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"min": min, "max": max, "seed": seed})
    return out


# -- image-op layers (reference: layers/nn.py image_resize, resize_bilinear,
# roi_pool, roi_align (1.3 backport), affine_grid, grid_sampler, unpool;
# pool_with_index via pool2d max variant) ----------------------------------

def image_resize(input, out_shape=None, scale=None, resample="BILINEAR",
                 name=None):
    helper = LayerHelper("image_resize", name=name)
    if out_shape is None:
        out_shape = [int(input.shape[2] * scale), int(input.shape[3] * scale)]
    op = {"BILINEAR": "bilinear_interp", "NEAREST": "nearest_interp"}[resample]
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(op, inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"out_h": int(out_shape[0]),
                            "out_w": int(out_shape[1])})
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, "BILINEAR", name)


def resize_nearest(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, "NEAREST", name)


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
             rois_batch_id=None):
    helper = LayerHelper("roi_pool")
    out = helper.create_variable_for_type_inference(input.dtype)
    argmax = helper.create_variable_for_type_inference("int32")
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch_id is not None:
        inputs["RoisBatchId"] = [rois_batch_id]
    helper.append_op("roi_pool", inputs=inputs,
                     outputs={"Out": [out], "Argmax": [argmax]},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale})
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_batch_id=None):
    helper = LayerHelper("roi_align")
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch_id is not None:
        inputs["RoisBatchId"] = [rois_batch_id]
    helper.append_op("roi_align", inputs=inputs, outputs={"Out": [out]},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale,
                            "sampling_ratio": sampling_ratio})
    return out


def affine_grid(theta, out_shape, name=None):
    if hasattr(out_shape, "desc"):
        raise NotImplementedError(
            "affine_grid with a Variable out_shape is not supported on TPU "
            "(static shapes); pass a list of 4 ints")
    helper = LayerHelper("affine_grid", name=name)
    out = helper.create_variable_for_type_inference(theta.dtype)
    helper.append_op("affine_grid", inputs={"Theta": [theta]},
                     outputs={"Out": [out]},
                     attrs={"output_shape": [int(v) for v in out_shape]})
    return out


def grid_sampler(x, grid, name=None):
    helper = LayerHelper("grid_sampler", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("grid_sampler", inputs={"X": [x], "Grid": [grid]},
                     outputs={"Output": [out]})
    return out


def affine_channel(x, scale, bias, name=None):
    helper = LayerHelper("affine_channel", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("affine_channel",
                     inputs={"X": [x], "Scale": [scale], "Bias": [bias]},
                     outputs={"Out": [out]})
    return out


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    """reference: layers/nn.py warpctc → warpctc_op.cc. Padded layout:
    input [B, T, C] logits, label [B, S] (pad -1)."""
    helper = LayerHelper("warpctc")
    loss = helper.create_variable_for_type_inference(input.dtype)
    grad = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Logits": [input], "Label": [label]}
    if input_length is not None:
        inputs["LogitsLength"] = [input_length]
    if label_length is not None:
        inputs["LabelLength"] = [label_length]
    helper.append_op("warpctc", inputs=inputs,
                     outputs={"Loss": [loss], "WarpCTCGrad": [grad]},
                     attrs={"blank": blank, "norm_by_times": norm_by_times})
    return loss


def ctc_greedy_decoder(input, blank, name=None):
    """reference: layers/nn.py ctc_greedy_decoder — argmax over classes then
    merge-repeats + drop-blanks (ctc_align). input [B, T, C] probs/logits;
    output [B, T] ids padded with -1."""
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    ids = helper.create_variable_for_type_inference("int64")
    helper.append_op("argmax", inputs={"X": [input]}, outputs={"Out": [ids]},
                     attrs={"axis": 2})
    out = helper.create_variable_for_type_inference("int32")
    helper.append_op("ctc_align", inputs={"Input": [ids]},
                     outputs={"Output": [out]},
                     attrs={"blank": blank, "merge_repeated": True})
    return out


# ---------------------------------------------------------------------------
# API-surface completion (round 3): every name the reference exports from
# fluid.layers resolves here too (machine-checked by
# tests/test_layers_api_parity.py)
# ---------------------------------------------------------------------------

def _triple(v):
    return list(v) if isinstance(v, (list, tuple)) else [v, v, v]


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    """reference: nn.py:1944 — NCDHW conv."""
    helper = LayerHelper("conv3d", name=name)
    num_channels = input.shape[1]
    fsize = _triple(filter_size)
    filter_shape = [num_filters, num_channels // groups] + fsize
    std = (2.0 / (fsize[0] * fsize[1] * fsize[2] * num_channels)) ** 0.5
    from paddle_tpu.fluid.initializer import NormalInitializer
    w = helper.create_parameter(param_attr, shape=filter_shape,
                                dtype=input.dtype,
                                default_initializer=NormalInitializer(0.0, std))
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "conv3d", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": _triple(stride), "paddings": _triple(padding),
               "dilations": _triple(dilation), "groups": groups})
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, shape=[num_filters],
                                    dtype=input.dtype, is_bias=True)
        with_b = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("elementwise_add", inputs={"X": [out], "Y": [b]},
                         outputs={"Out": [with_b]}, attrs={"axis": 1})
        out = with_b
    return helper.append_activation(out, act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    """reference: nn.py:3405."""
    helper = LayerHelper("conv3d_transpose", name=name)
    num_channels = input.shape[1]
    fsize = _transpose_filter_size(filter_size, output_size, input.shape[2:],
                                   stride, padding, dilation, 3)
    w = helper.create_parameter(
        param_attr, shape=[num_channels, num_filters // groups] + fsize,
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "conv3d_transpose", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": _triple(stride), "paddings": _triple(padding),
               "dilations": _triple(dilation), "groups": groups})
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, shape=[num_filters],
                                    dtype=input.dtype, is_bias=True)
        with_b = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("elementwise_add", inputs={"X": [out], "Y": [b]},
                         outputs={"Out": [with_b]}, attrs={"axis": 1})
        out = with_b
    return helper.append_activation(out, act)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None):
    """reference: nn.py:2453."""
    helper = LayerHelper("pool3d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "pool3d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": _triple(pool_size),
               "strides": _triple(pool_stride),
               "paddings": _triple(pool_padding),
               "global_pooling": global_pooling, "ceil_mode": ceil_mode,
               "exclusive": exclusive})
    return out


def adaptive_pool2d(input, pool_size, pool_type="max",
                    require_index=False, name=None):
    """reference: nn.py:2526 (floor/ceil bin rule)."""
    if require_index:
        raise NotImplementedError(
            "adaptive_pool2d(require_index=True): use "
            "max_pool2d_with_index for the mask")
    helper = LayerHelper("adaptive_pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("adaptive_pool2d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pooled_size": _pair(pool_size),
                            "pooling_type": pool_type})
    return out


def adaptive_pool3d(input, pool_size, pool_type="max",
                    require_index=False, name=None):
    """reference: nn.py adaptive_pool3d."""
    if require_index:
        raise NotImplementedError(
            "adaptive_pool3d(require_index=True) is not supported")
    helper = LayerHelper("adaptive_pool3d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("adaptive_pool3d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pooled_size": _triple(pool_size),
                            "pooling_type": pool_type})
    return out


def group_norm(input, groups, epsilon=1e-05, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    """reference: nn.py:3137 → group_norm_op.cc."""
    helper = LayerHelper("group_norm", name=name)
    c = input.shape[1]
    from paddle_tpu.fluid.initializer import ConstantInitializer
    scale = helper.create_parameter(
        param_attr, shape=[c], dtype=input.dtype,
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, shape=[c], dtype=input.dtype,
                                   is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    mean = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("group_norm",
                     inputs={"X": [input], "Scale": [scale], "Bias": [bias]},
                     outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
                     attrs={"groups": groups, "epsilon": epsilon})
    return helper.append_activation(out, act)


def data_norm(input, act=None, epsilon=1e-05, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=False):
    """reference: nn.py data_norm → data_norm_op.cc (batch-statistics
    normalization without learned scale/shift)."""
    helper = LayerHelper("data_norm", name=name)
    c = input.shape[1]
    import copy

    from paddle_tpu.fluid.initializer import ConstantInitializer
    from paddle_tpu.fluid.param_attr import ParamAttr

    def slot_attr(suffix):
        # one attr object per slot — create_parameter mutates attr.name,
        # so sharing one object would alias all three stats into one var
        a = copy.copy(ParamAttr._to_attr(param_attr))
        a.initializer = None
        if a.name is not None:
            a.name = a.name + suffix
        return a

    batch_size = helper.create_parameter(
        slot_attr(".batch_size"), shape=[c], dtype=input.dtype,
        default_initializer=ConstantInitializer(1.0))
    batch_sum = helper.create_parameter(
        slot_attr(".batch_sum"), shape=[c], dtype=input.dtype,
        default_initializer=ConstantInitializer(0.0))
    batch_square_sum = helper.create_parameter(
        slot_attr(".batch_square_sum"), shape=[c], dtype=input.dtype,
        default_initializer=ConstantInitializer(1e4))
    out = helper.create_variable_for_type_inference(input.dtype)
    means = helper.create_variable_for_type_inference(input.dtype)
    scales = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("data_norm",
                     inputs={"X": [input], "BatchSize": [batch_size],
                             "BatchSum": [batch_sum],
                             "BatchSquareSum": [batch_square_sum]},
                     outputs={"Y": [out], "Means": [means],
                              "Scales": [scales]},
                     attrs={"epsilon": epsilon})
    return helper.append_activation(out, act)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    """reference: nn.py:6125 → lrn_op.cc."""
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("lrn", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def prelu(x, mode, param_attr=None, name=None):
    """reference: nn.py:7758; mode in {'all','channel','element'}."""
    helper = LayerHelper("prelu", name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [1, x.shape[1], 1, 1]
    elif mode == "element":
        alpha_shape = list(x.shape[1:])
    else:
        raise ValueError("prelu mode must be all|channel|element")
    from paddle_tpu.fluid.initializer import ConstantInitializer
    alpha = helper.create_parameter(
        param_attr, shape=alpha_shape, dtype=x.dtype,
        default_initializer=ConstantInitializer(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("prelu", inputs={"X": [x], "Alpha": [alpha]},
                     outputs={"Out": [out]}, attrs={"mode": mode})
    return out


def soft_relu(x, threshold=40.0, name=None):
    """reference: nn.py:7873 — log(1 + exp(clip(x, -t, t))); composed
    from clip + softplus (exact same math)."""
    helper = LayerHelper("soft_relu", name=name)
    clipped = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("clip", inputs={"X": [x]}, outputs={"Out": [clipped]},
                     attrs={"min": -threshold, "max": threshold})
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("softplus", inputs={"X": [clipped]},
                     outputs={"Out": [out]})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    """reference: nn.py:5699 → smooth_l1_loss_op.cc."""
    helper = LayerHelper("smooth_l1")
    out = helper.create_variable_for_type_inference(x.dtype)
    diff = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op("smooth_l1_loss", inputs=inputs,
                     outputs={"Out": [out], "Diff": [diff]},
                     attrs={"sigma": 1.0 if sigma is None else sigma})
    return out


def dice_loss(input, label, epsilon=1e-5):
    """reference: nn.py:6484 — composed from existing ops exactly as the
    reference composes it in python."""
    from paddle_tpu.fluid.layers.ops import (elementwise_add,
                                             elementwise_div,
                                             elementwise_mul)
    label = one_hot(label, depth=input.shape[-1])
    reduce_dim = list(range(1, len(input.shape)))
    inse = reduce_sum(elementwise_mul(input, label), dim=reduce_dim)
    dice_denominator = elementwise_add(
        reduce_sum(input, dim=reduce_dim),
        reduce_sum(label, dim=reduce_dim))
    dice_score = scale(
        elementwise_div(
            scale(inse, scale=2.0),
            scale(dice_denominator, bias=epsilon)),
        scale=-1.0, bias=1.0)
    return reduce_mean(dice_score)


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    """reference: nn.py:5383 → im2sequence_op.cc."""
    helper = LayerHelper("im2sequence", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    p = padding if isinstance(padding, (list, tuple)) else [padding] * 4
    if len(p) == 2:
        p = [p[0], p[0], p[1], p[1]]
    helper.append_op("im2sequence", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"kernels": _pair(filter_size),
                            "strides": _pair(stride), "paddings": list(p)})
    return out


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """reference: nn.py:6751 — resize so the SHORT side equals
    out_short_len, preserving aspect ratio."""
    in_shape = input.shape
    hw = in_shape[2:4]
    short_idx = hw.index(min(hw))
    out_shape = list(hw)
    out_shape[short_idx] = out_short_len
    out_shape[1 - short_idx] = int(
        round(hw[1 - short_idx] * (out_short_len / hw[short_idx])))
    return image_resize(input, out_shape=out_shape, resample=resample)


def lod_reset(x, y=None, target_lod=None):
    """reference: nn.py:6029 → lod_reset_op.cc (here: re-binds SeqLens)."""
    helper = LayerHelper("lod_reset")
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    if y is not None:
        inputs["Y"] = [y]
    helper.append_op("lod_reset", inputs=inputs, outputs={"Out": [out]},
                     attrs={} if target_lod is None
                           else {"target_lod": list(target_lod)})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    """reference: nn.py:6195 → pad_op.cc."""
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("pad", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"paddings": list(paddings),
                            "pad_value": float(pad_value)})
    return out


def scatter(input, index, updates, name=None):
    """reference: nn.py:6836 → scatter_op.cc."""
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("scatter",
                     inputs={"X": [input], "Ids": [index],
                             "Updates": [updates]},
                     outputs={"Out": [out]})
    return out


def sum(x):
    """reference: nn.py:8392 → sum_op.cc (elementwise sum of a list)."""
    helper = LayerHelper("sum")
    xs = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(xs[0].dtype)
    helper.append_op("sum", inputs={"X": list(xs)}, outputs={"Out": [out]})
    return out


def mean_iou(input, label, num_classes):
    """reference: nn.py:7086 → mean_iou_op.cc."""
    helper = LayerHelper("mean_iou")
    iou = helper.create_variable_for_type_inference("float32")
    wrong = helper.create_variable_for_type_inference("int32")
    correct = helper.create_variable_for_type_inference("int32")
    helper.append_op("mean_iou",
                     inputs={"Predictions": [input], "Labels": [label]},
                     outputs={"OutMeanIou": [iou], "OutWrong": [wrong],
                              "OutCorrect": [correct]},
                     attrs={"num_classes": num_classes})
    return iou, wrong, correct


def clip_by_norm(x, max_norm, name=None):
    """reference: nn.py:8764 → clip_by_norm_op.cc."""
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("clip_by_norm", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"max_norm": max_norm})
    return out


def _logical(op, x, y=None, out=None, name=None):
    helper = LayerHelper(op, name=name)
    if out is None:
        out = helper.create_variable_for_type_inference("bool")
    inputs = {"X": [x]} if y is None else {"X": [x], "Y": [y]}
    helper.append_op(op, inputs=inputs, outputs={"Out": [out]})
    return out


def logical_and(x, y, out=None, name=None):
    """reference: nn.py:8615."""
    return _logical("logical_and", x, y, out, name)


def logical_or(x, y, out=None, name=None):
    return _logical("logical_or", x, y, out, name)


def logical_xor(x, y, out=None, name=None):
    return _logical("logical_xor", x, y, out, name)


def logical_not(x, out=None, name=None):
    return _logical("logical_not", x, None, out, name)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    """reference: nn.py:8259 → gaussian_random_op.cc."""
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("gaussian_random", inputs={}, outputs={"Out": [out]},
                     attrs={"shape": list(shape), "mean": mean, "std": std,
                            "seed": seed, "dtype": dtype})
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    """reference: nn.py:8208."""
    helper = LayerHelper("uniform_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("uniform_random_batch_size_like",
                     inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape), "min": min, "max": max,
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx,
                            "seed": seed, "dtype": dtype})
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    """reference: nn.py:8338."""
    helper = LayerHelper("gaussian_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("gaussian_random_batch_size_like",
                     inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape), "mean": mean, "std": std,
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx,
                            "seed": seed, "dtype": dtype})
    return out


def hash(input, hash_size, num_hash=1, name=None):
    """reference: nn.py:9194 → hash_op.cc."""
    helper = LayerHelper("hash", name=name)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("hash", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"mod_by": hash_size, "num_hash": num_hash})
    return out


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """reference: nn.py:489 (the cudnn multi-layer LSTM) → cudnn_lstm op.
    `input` [T, B, D]; returns (rnn_out, last_h, last_c)."""
    helper = LayerHelper("lstm", name=name)
    d_in = input.shape[-1]
    ndir = 2 if is_bidirec else 1
    # packed W: per layer, per direction, Wx (Din,4H) | Wh (H,4H) | b (4H)
    total = 0
    cur = d_in
    for _ in range(num_layers):
        total += ndir * (cur * 4 * hidden_size
                         + hidden_size * 4 * hidden_size + 4 * hidden_size)
        cur = hidden_size * ndir
    w = helper.create_parameter(None, shape=[total], dtype=input.dtype,
                                default_initializer=default_initializer)
    out = helper.create_variable_for_type_inference(input.dtype)
    last_h = helper.create_variable_for_type_inference(input.dtype)
    last_c = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("cudnn_lstm",
                     inputs={"Input": [input], "InitH": [init_h],
                             "InitC": [init_c], "W": [w]},
                     outputs={"Out": [out], "last_h": [last_h],
                              "last_c": [last_c]},
                     attrs={"hidden_size": hidden_size,
                            "num_layers": num_layers,
                            "is_bidirec": is_bidirec,
                            "dropout_prob": dropout_prob,
                            "is_test": is_test, "seed": seed})
    return out, last_h, last_c


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """reference: nn.py:9395 → teacher_student_sigmoid_loss_op.cc."""
    helper = LayerHelper("teacher_student_sigmoid_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("teacher_student_sigmoid_loss",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]},
                     attrs={"soft_max_up_bound": soft_max_up_bound,
                            "soft_max_lower_bound": soft_max_lower_bound})
    return out


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, rois_batch_id=None, name=None):
    """reference: nn.py psroi_pool → psroi_pool_op.cc (batch ids replace
    the reference's ROI LoD)."""
    helper = LayerHelper("psroi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch_id is not None:
        inputs["RoisBatchId"] = [rois_batch_id]
    helper.append_op("psroi_pool", inputs=inputs, outputs={"Out": [out]},
                     attrs={"output_channels": output_channels,
                            "spatial_scale": spatial_scale,
                            "pooled_height": pooled_height,
                            "pooled_width": pooled_width})
    return out


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              rois_batch_id=None):
    """reference: detection/roi_perspective_transform_op.cc."""
    helper = LayerHelper("roi_perspective_transform")
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch_id is not None:
        inputs["RoisBatchId"] = [rois_batch_id]
    helper.append_op("roi_perspective_transform", inputs=inputs,
                     outputs={"Out": [out]},
                     attrs={"transformed_height": transformed_height,
                            "transformed_width": transformed_width,
                            "spatial_scale": spatial_scale})
    return out


def merge_selected_rows(x, name=None):
    """reference: merge_selected_rows_op.cc (dedup sparse rows)."""
    helper = LayerHelper("merge_selected_rows", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("merge_selected_rows", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def get_tensor_from_selected_rows(x, name=None):
    """reference: get_tensor_from_selected_rows_op.cc."""
    helper = LayerHelper("get_tensor_from_selected_rows", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("get_tensor_from_selected_rows", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    """reference: nn.py:9653 → py_func_op.cc (host callback; backward_func
    is accepted for parity — gradients flow through jax.pure_callback's
    defined vjp only when provided)."""
    helper = LayerHelper("py_func")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    helper.append_op("py_func", inputs={"X": list(xs)},
                     outputs={"Out": list(outs)},
                     attrs={"func": func,
                            "out_shapes": [list(o.shape) for o in outs],
                            "out_dtypes": [o.dtype for o in outs]})
    return out


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """reference: nn.py:5780 — a persistable int64 counter incremented
    once per executed step."""
    helper = LayerHelper("global_step_counter")
    name = counter_name or "@STEP_COUNTER@"
    block = helper.main_program.global_block()
    if block.has_var(name):
        # reuse: the increment op was appended when the counter was
        # created — appending another would advance it twice per step
        # (reference appends the increment only for a fresh counter)
        return block.var(name)
    counter = helper.create_global_variable(
        shape=[1], dtype="int64", name=name, persistable=True)
    from paddle_tpu.fluid.initializer import ConstantInitializer
    startup_block = helper.startup_program.global_block()
    if not startup_block.has_var(name):
        sp = startup_block.create_var(name=name, shape=[1],
                                      dtype="int64", persistable=True)
        ConstantInitializer(float(begin - 1))(sp, startup_block)
    one = helper.create_variable_for_type_inference("int64")
    helper.append_op("fill_constant", inputs={}, outputs={"Out": [one]},
                     attrs={"shape": [1], "dtype": "int64",
                            "value": float(step)})
    helper.append_op("elementwise_add", inputs={"X": [counter], "Y": [one]},
                     outputs={"Out": [counter]})
    counter.stop_gradient = True
    return counter
