"""Detection layers (reference: python/paddle/fluid/layers/detection.py —
prior_box, density_prior_box, multi_box_head, bipartite_match, target_assign,
detection_output, ssd_loss, detection_map, anchor_generator,
generate_proposals, rpn_target_assign, iou_similarity, box_coder,
polygon_box_transform, roi_perspective_transform).

Padded-batch convention: ground truth arrives as dense [B, G, ...] tensors
(pad label -1 / zero boxes) instead of the reference's LoD; see
paddle_tpu/ops/detection_ops.py header."""

from __future__ import annotations

import numpy as np

from paddle_tpu.fluid.layer_helper import LayerHelper


def _out(helper, dtype="float32"):
    return helper.create_variable_for_type_inference(dtype)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", name=name)
    boxes, var = _out(helper), _out(helper)
    helper.append_op(
        "prior_box", inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={"min_sizes": list(min_sizes),
               "max_sizes": list(max_sizes or []),
               "aspect_ratios": list(aspect_ratios),
               "variances": list(variance), "flip": flip, "clip": clip,
               "step_w": steps[0], "step_h": steps[1], "offset": offset,
               "min_max_aspect_ratios_order": min_max_aspect_ratios_order})
    return boxes, var


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, name=None):
    helper = LayerHelper("density_prior_box", name=name)
    boxes, var = _out(helper), _out(helper)
    helper.append_op(
        "density_prior_box", inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={"densities": list(densities), "fixed_sizes": list(fixed_sizes),
               "fixed_ratios": list(fixed_ratios), "variances": list(variance),
               "clip": clip, "step_w": steps[0], "step_h": steps[1],
               "offset": offset})
    return boxes, var


def anchor_generator(input, anchor_sizes, aspect_ratios,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset=0.5, name=None):
    helper = LayerHelper("anchor_generator", name=name)
    anchors, var = _out(helper), _out(helper)
    helper.append_op(
        "anchor_generator", inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [var]},
        attrs={"anchor_sizes": list(anchor_sizes),
               "aspect_ratios": list(aspect_ratios),
               "variances": list(variance), "stride": list(stride),
               "offset": offset})
    return anchors, var


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = _out(helper)
    helper.append_op("iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    helper = LayerHelper("box_coder", name=name)
    out = _out(helper)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op("box_coder", inputs=inputs,
                     outputs={"OutputBox": [out]},
                     attrs={"code_type": code_type,
                            "box_normalized": box_normalized})
    return out


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    helper = LayerHelper("bipartite_match", name=name)
    idx = _out(helper, "int32")
    dist = _out(helper)
    helper.append_op("bipartite_match", inputs={"DistMat": [dist_matrix]},
                     outputs={"ColToRowMatchIndices": [idx],
                              "ColToRowMatchDist": [dist]},
                     attrs={"match_type": match_type,
                            "dist_threshold": dist_threshold})
    return idx, dist


def target_assign(input, matched_indices, negative_mask=None,
                  mismatch_value=0, name=None):
    helper = LayerHelper("target_assign", name=name)
    out = _out(helper, input.dtype)
    w = _out(helper)
    inputs = {"X": [input], "MatchIndices": [matched_indices]}
    if negative_mask is not None:
        inputs["NegMask"] = [negative_mask]
    helper.append_op("target_assign", inputs=inputs,
                     outputs={"Out": [out], "OutWeight": [w]},
                     attrs={"mismatch_value": mismatch_value})
    return out, w


def mine_hard_examples(cls_loss, match_indices, match_dist, loc_loss=None,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5,
                       mining_type="max_negative", sample_size=0):
    helper = LayerHelper("mine_hard_examples")
    neg_mask = _out(helper, "int32")
    upd = _out(helper, "int32")
    inputs = {"ClsLoss": [cls_loss], "MatchIndices": [match_indices],
              "MatchDist": [match_dist]}
    if loc_loss is not None:
        inputs["LocLoss"] = [loc_loss]
    helper.append_op("mine_hard_examples", inputs=inputs,
                     outputs={"NegMask": [neg_mask],
                              "UpdatedMatchIndices": [upd]},
                     attrs={"neg_pos_ratio": neg_pos_ratio,
                            "neg_dist_threshold": neg_dist_threshold,
                            "mining_type": mining_type,
                            "sample_size": sample_size})
    return neg_mask, upd


def multiclass_nms(bboxes, scores, background_label=0, score_threshold=0.0,
                   nms_top_k=400, nms_threshold=0.3, keep_top_k=200,
                   normalized=True, nms_eta=1.0, name=None):
    helper = LayerHelper("multiclass_nms", name=name)
    out = _out(helper)
    helper.append_op("multiclass_nms",
                     inputs={"BBoxes": [bboxes], "Scores": [scores]},
                     outputs={"Out": [out]},
                     attrs={"background_label": background_label,
                            "score_threshold": score_threshold,
                            "nms_top_k": nms_top_k,
                            "nms_threshold": nms_threshold,
                            "keep_top_k": keep_top_k,
                            "normalized": normalized, "nms_eta": nms_eta})
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """reference: layers/detection.py detection_output — decode then NMS.
    loc [B, M, 4] predicted offsets, scores [B, M, C] (softmax applied
    here), prior_box [M, 4]."""
    from paddle_tpu.fluid.layers import nn
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    sm = nn.softmax(scores)                      # softmax over last dim
    perm = nn.transpose(sm, [0, 2, 1])           # [B, C, M]
    return multiclass_nms(decoded, perm, background_label=background_label,
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, nms_threshold=nms_threshold,
                          keep_top_k=keep_top_k, nms_eta=nms_eta)


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", name=name)
    out = _out(helper)
    helper.append_op("polygon_box_transform", inputs={"Input": [input]},
                     outputs={"Output": [out]})
    return out


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.5, ap_version="integral"):
    helper = LayerHelper("detection_map")
    out = _out(helper)
    helper.append_op("detection_map",
                     inputs={"DetectRes": [detect_res], "Label": [label]},
                     outputs={"MAP": [out]},
                     attrs={"class_num": class_num,
                            "background_label": background_label,
                            "overlap_threshold": overlap_threshold,
                            "ap_type": ap_version})
    return out


def rpn_target_assign(anchor_box, gt_boxes, rpn_batch_size_per_im=256,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3):
    helper = LayerHelper("rpn_target_assign")
    score_idx = _out(helper, "int32")
    tgt_box = _out(helper)
    loc_idx = _out(helper, "int32")
    tgt_lbl = _out(helper, "int32")
    helper.append_op("rpn_target_assign",
                     inputs={"Anchor": [anchor_box], "GtBoxes": [gt_boxes]},
                     outputs={"ScoreIndex": [score_idx],
                              "TargetBBox": [tgt_box],
                              "LocationIndex": [loc_idx],
                              "TargetLabel": [tgt_lbl]},
                     attrs={"rpn_batch_size_per_im": rpn_batch_size_per_im,
                            "rpn_fg_fraction": rpn_fg_fraction,
                            "rpn_positive_overlap": rpn_positive_overlap,
                            "rpn_negative_overlap": rpn_negative_overlap})
    return score_idx, tgt_box, loc_idx, tgt_lbl


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1):
    helper = LayerHelper("generate_proposals")
    rois = _out(helper)
    probs = _out(helper)
    helper.append_op("generate_proposals",
                     inputs={"Scores": [scores], "BboxDeltas": [bbox_deltas],
                             "ImInfo": [im_info], "Anchors": [anchors],
                             "Variances": [variances]},
                     outputs={"RpnRois": [rois], "RpnRoiProbs": [probs]},
                     attrs={"pre_nms_topN": pre_nms_top_n,
                            "post_nms_topN": post_nms_top_n,
                            "nms_thresh": nms_thresh, "min_size": min_size})
    return rois, probs


def yolov3_loss(x, gt_box, gt_label, anchors, class_num, ignore_thresh=0.7,
                downsample_ratio=32, name=None):
    helper = LayerHelper("yolov3_loss", name=name)
    loss = _out(helper)
    helper.append_op("yolov3_loss",
                     inputs={"X": [x], "GTBox": [gt_box],
                             "GTLabel": [gt_label]},
                     outputs={"Loss": [loss]},
                     attrs={"anchors": list(anchors), "class_num": class_num,
                            "ignore_thresh": ignore_thresh,
                            "downsample_ratio": downsample_ratio})
    return loss


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, mining_type="max_negative",
             normalize=True, sample_size=None):
    """reference: layers/detection.py ssd_loss — the composed SSD training
    objective: match priors to gt (per-prediction bipartite match), mine
    hard negatives, encode box targets, smooth-L1 loc loss + softmax conf
    loss, normalized by the matched-prior count.

    Padded-batch convention: gt_box [B, G, 4] (zero rows pad),
    gt_label [B, G, 1] (-1 pad); location [B, M, 4]; confidence [B, M, C];
    prior_box [M, 4]. Returns a scalar loss (the reference returns the
    per-prior loss tensor; callers invariably reduce it)."""
    from paddle_tpu.fluid import layers as L

    # 1. IoU of gt vs priors per batch: [B, G, M]
    similarity = iou_similarity(gt_box, prior_box)
    # 2. match priors to gt rows
    matched_idx, matched_dist = bipartite_match(similarity, "per_prediction",
                                                overlap_threshold)
    # 3. per-prior labels with current matches (background where unmatched)
    gt_label_f = L.cast(gt_label, "float32")
    lbl_for_prior, _ = target_assign(gt_label_f, matched_idx,
                                     mismatch_value=background_label)
    conf_loss = L.squeeze(L.softmax_with_cross_entropy(
        confidence, L.cast(lbl_for_prior, "int64")), [2])     # [B, M]
    # 4. mine hard negatives on that conf loss
    neg_mask, _ = mine_hard_examples(
        conf_loss, matched_idx, matched_dist, neg_pos_ratio=neg_pos_ratio,
        neg_dist_threshold=neg_overlap, mining_type=mining_type,
        sample_size=sample_size or 0)
    # 5. final conf targets: negatives forced to background, weight 1 on
    # positives + mined negatives
    target_lbl, target_lbl_w = target_assign(
        gt_label_f, matched_idx, negative_mask=neg_mask,
        mismatch_value=background_label)
    conf_loss = L.squeeze(L.softmax_with_cross_entropy(
        confidence, L.cast(target_lbl, "int64")), [2])        # [B, M]
    conf_loss = L.elementwise_mul(conf_loss, L.squeeze(target_lbl_w, [2]))
    # 6. loc targets: gather matched gt corners per prior, encode vs priors
    loc_tgt, loc_w = target_assign(gt_box, matched_idx, mismatch_value=0)
    loc_tgt_enc = box_coder(prior_box, prior_box_var, loc_tgt,
                            code_type="encode_center_size")   # [B, M, 4]
    # per-element smooth-L1 (sigma=1): 0.5*m^2 + (|d| - m), m = min(|d|, 1)
    absd = L.abs(L.elementwise_sub(location, loc_tgt_enc))
    m = L.elementwise_min(absd, L.fill_constant([1], "float32", 1.0))
    sl1 = L.elementwise_add(L.scale(L.elementwise_mul(m, m), scale=0.5),
                            L.elementwise_sub(absd, m))
    l1 = L.reduce_sum(sl1, dim=[2])                           # [B, M]
    l1 = L.elementwise_mul(l1, L.squeeze(loc_w, [2]))
    # 7. combine + normalize by positive count
    total = L.elementwise_add(
        L.scale(L.reduce_sum(l1), scale=loc_loss_weight),
        L.scale(L.reduce_sum(conf_loss), scale=conf_loss_weight))
    if normalize:
        pos = L.cast(L.greater_equal(
            L.cast(matched_idx, "float32"),
            L.fill_constant([1], "float32", 0.0)), "float32")
        denom = L.elementwise_max(L.reduce_sum(pos),
                                  L.fill_constant([1], "float32", 1.0))
        total = L.elementwise_div(total, denom)
    return total


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """reference: layers/detection.py:1259 multi_box_head — the SSD head:
    per feature map, emit prior boxes plus conv loc/conf predictions, then
    concatenate across maps. Returns (mbox_locs [B, M, 4],
    mbox_confs [B, M, C], prior_boxes [M, 4], variances [M, 4])."""
    from paddle_tpu.fluid import layers as L

    n_maps = len(inputs)
    if min_sizes is None:
        # reference ratio schedule (detection.py multi_box_head): spread
        # min_ratio..max_ratio evenly over maps 2..N, with a fixed
        # 10%/20% first-map entry
        assert min_ratio is not None and max_ratio is not None
        min_sizes = []
        max_sizes = []
        step = (int((max_ratio - min_ratio) / (n_maps - 2))
                if n_maps > 2 else (max_ratio - min_ratio))
        for r in range(min_ratio, max_ratio + 1, max(step, 1)):
            min_sizes.append(base_size * r / 100.0)
            max_sizes.append(base_size * (r + step) / 100.0)
        min_sizes = ([base_size * 0.10] + min_sizes)[:n_maps]
        max_sizes = ([base_size * 0.20] + max_sizes)[:n_maps]
        if len(min_sizes) < n_maps:
            raise ValueError(
                f"min_ratio..max_ratio schedule yields {len(min_sizes)} "
                f"sizes for {n_maps} feature maps — pass explicit "
                f"min_sizes/max_sizes")

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, inp in enumerate(inputs):
        mins = min_sizes[i]
        maxs = max_sizes[i] if max_sizes else None
        ars = aspect_ratios[i] if isinstance(aspect_ratios[i],
                                             (list, tuple)) \
            else [aspect_ratios[i]]
        if steps:
            st = steps[i]
        else:
            # step_w/step_h may be scalars or per-map lists (reference API)
            sw = step_w[i] if isinstance(step_w, (list, tuple)) \
                else (step_w or 0.0)
            sh = step_h[i] if isinstance(step_h, (list, tuple)) \
                else (step_h or 0.0)
            st = [sw, sh]
        if not isinstance(st, (list, tuple)):
            st = [st, st]
        box, var = prior_box(
            inp, image,
            min_sizes=[mins] if not isinstance(mins, (list, tuple))
            else list(mins),
            max_sizes=[maxs] if maxs and not isinstance(maxs, (list, tuple))
            else (list(maxs) if maxs else None),
            aspect_ratios=ars, variance=variance, flip=flip, clip=clip,
            steps=st, offset=offset,
            min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        # priors per cell from the emitted box tensor [H, W, P, 4]
        p = box.shape[2] if box.shape and len(box.shape) == 4 else None
        if p is None:
            from paddle_tpu.ops.detection_ops import _expand_aspect_ratios
            n_mins = len(mins) if isinstance(mins, (list, tuple)) else 1
            n_maxs = (len(maxs) if isinstance(maxs, (list, tuple))
                      else (1 if maxs else 0))
            p = n_mins * len(_expand_aspect_ratios(ars, flip)) + n_maxs
        loc = L.conv2d(inp, p * 4, kernel_size, stride=stride, padding=pad,
                       bias_attr=None)
        conf = L.conv2d(inp, p * num_classes, kernel_size, stride=stride,
                        padding=pad, bias_attr=None)
        # conv output spatial grid must match the prior grid (priors are
        # emitted per input-map cell) — the reference's SSD heads use
        # size-preserving convs; reject silent misalignment
        oh = (int(inp.shape[2]) + 2 * pad - kernel_size) // stride + 1
        ow = (int(inp.shape[3]) + 2 * pad - kernel_size) // stride + 1
        if (oh, ow) != (int(inp.shape[2]), int(inp.shape[3])):
            raise ValueError(
                f"multi_box_head: loc/conf conv (k={kernel_size}, pad={pad}, "
                f"stride={stride}) maps {inp.shape[2]}x{inp.shape[3]} -> "
                f"{oh}x{ow}, misaligned with the per-cell prior grid — use "
                f"a size-preserving conv (e.g. kernel_size=3, pad=1)")
        # NCHW -> [B, H*W*P, 4|C]
        loc = L.reshape(L.transpose(loc, [0, 2, 3, 1]),
                        [-1, oh * ow * p, 4])
        conf = L.reshape(L.transpose(conf, [0, 2, 3, 1]),
                         [-1, oh * ow * p, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes_all.append(L.reshape(box, [-1, 4]))
        vars_all.append(L.reshape(var, [-1, 4]))

    mbox_locs = L.concat(locs, axis=1)
    mbox_confs = L.concat(confs, axis=1)
    prior_boxes = L.concat(boxes_all, axis=0)
    box_vars = L.concat(vars_all, axis=0)
    return mbox_locs, mbox_confs, prior_boxes, box_vars



def generate_proposal_labels(rpn_rois, gt_classes, is_crowd=None,
                             gt_boxes=None, im_info=None,
                             batch_size_per_im=256, fg_fraction=0.25,
                             fg_thresh=0.25, bg_thresh_hi=0.5,
                             bg_thresh_lo=0.0, bbox_reg_weights=(0.1, 0.1,
                                                                 0.2, 0.2),
                             class_nums=None, use_random=True):
    """reference: layers/detection.py generate_proposal_labels →
    detection/generate_proposal_labels_op.cc. Batched dense [B, R, 4]
    rois with sampled-mask outputs replace the reference's LoD lists."""
    helper = LayerHelper("generate_proposal_labels")
    rois = _out(helper)
    labels = _out(helper, "int32")
    targets = _out(helper)
    inw = _out(helper)
    outw = _out(helper)
    helper.append_op(
        "generate_proposal_labels",
        inputs={"RpnRois": [rpn_rois], "GtClasses": [gt_classes],
                "GtBoxes": [gt_boxes]},
        outputs={"Rois": [rois], "LabelsInt32": [labels],
                 "BboxTargets": [targets], "BboxInsideWeights": [inw],
                 "BboxOutsideWeights": [outw]},
        attrs={"batch_size_per_im": batch_size_per_im,
               "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
               "bg_thresh_hi": bg_thresh_hi, "bg_thresh_lo": bg_thresh_lo})
    return rois, labels, targets, inw, outw
