"""Recurrent layers (reference: python/paddle/fluid/layers/nn.py
dynamic_lstm/dynamic_gru/gru_unit/lstm_unit). Padded [B, T, ...] + seq_lens
replaces LoD input (see ops/rnn_ops.py)."""

from __future__ import annotations

from paddle_tpu.fluid.layer_helper import LayerHelper


def dynamic_lstm(input, size, h_0=None, c_0=None, seq_lens=None,
                 param_attr=None, bias_attr=None, use_peepholes=True,
                 is_reverse=False, gate_activation="sigmoid",
                 cell_activation="tanh", candidate_activation="tanh",
                 dtype="float32", name=None):
    """reference: nn.py dynamic_lstm / lstm_op.cc. `input` is the
    pre-projected [B, T, 4H] sequence (apply fc first, as the reference
    requires); `size` is 4H. Returns (hidden, cell) both [B, T, H]."""
    helper = LayerHelper("dynamic_lstm", name=name)
    H = size // 4
    weight = helper.create_parameter(param_attr, shape=[H, 4 * H], dtype=dtype)
    bias_size = 7 * H if use_peepholes else 4 * H
    bias = helper.create_parameter(bias_attr, shape=[1, bias_size],
                                   dtype=dtype, is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    if seq_lens is not None:
        inputs["SeqLens"] = [seq_lens]
    helper.append_op(
        "dynamic_lstm", inputs=inputs,
        outputs={"Hidden": [hidden], "Cell": [cell],
                 "LastHidden": [last_h], "LastCell": [last_c]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation})
    if input.shape is not None:
        B, T = input.shape[0], input.shape[1]
        for v in (hidden, cell):
            v.desc.shape = [B, T, H]
        for v in (last_h, last_c):
            v.desc.shape = [B, H]
    return hidden, cell


def dynamic_gru(input, size, h_0=None, seq_lens=None, param_attr=None,
                bias_attr=None, is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", dtype="float32", name=None):
    """reference: nn.py dynamic_gru / gru_op.cc. `input` is pre-projected
    [B, T, 3H]; `size` is H. Returns hidden [B, T, H]."""
    helper = LayerHelper("dynamic_gru", name=name)
    H = size
    weight = helper.create_parameter(param_attr, shape=[H, 3 * H], dtype=dtype)
    bias = helper.create_parameter(bias_attr, shape=[1, 3 * H], dtype=dtype,
                                   is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if seq_lens is not None:
        inputs["SeqLens"] = [seq_lens]
    helper.append_op(
        "dynamic_gru", inputs=inputs,
        outputs={"Hidden": [hidden], "LastHidden": [last_h]},
        attrs={"is_reverse": is_reverse, "gate_activation": gate_activation,
               "activation": candidate_activation})
    if input.shape is not None:
        hidden.desc.shape = [input.shape[0], input.shape[1], H]
        last_h.desc.shape = [input.shape[0], H]
    return hidden


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """reference: nn.py lstm_unit / lstm_unit_op.cc. Projects
    concat([x_t, h_prev]) to 4H then applies the fused cell. Returns (h, c)."""
    from paddle_tpu.fluid.layers.nn import fc
    from paddle_tpu.fluid.layers.tensor import concat
    helper = LayerHelper("lstm_unit", name=name)
    H = hidden_t_prev.shape[-1]
    gates = fc(concat([x_t, hidden_t_prev], axis=1), 4 * H,
               param_attr=param_attr, bias_attr=bias_attr)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op("lstm_unit",
                     inputs={"X": [gates], "C_prev": [cell_t_prev]},
                     outputs={"C": [c], "H": [h]},
                     attrs={"forget_bias": float(forget_bias)})
    if cell_t_prev.shape is not None:
        c.desc.shape = list(cell_t_prev.shape)
        h.desc.shape = list(cell_t_prev.shape)
    return h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid", name=None):
    """reference: nn.py gru_unit / gru_unit_op.cc. `input` pre-projected
    [B, 3H]; `size` = 3H to match the reference API. Returns (hidden, ...)."""
    helper = LayerHelper("gru_unit", name=name)
    H = size // 3
    weight = helper.create_parameter(param_attr, shape=[H, 3 * H],
                                     dtype=input.dtype)
    bias = helper.create_parameter(bias_attr, shape=[1, 3 * H],
                                   dtype=input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gru_unit",
                     inputs={"Input": [input], "HiddenPrev": [hidden],
                             "Weight": [weight], "Bias": [bias]},
                     outputs={"Hidden": [out]},
                     attrs={"activation": activation,
                            "gate_activation": gate_activation})
    if hidden.shape is not None:
        out.desc.shape = list(hidden.shape)
    return out, None, None


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None):
    """reference: nn.py:657 dynamic_lstmp → lstmp_op.cc. `input` is the
    pre-projected [B, T, 4H] sequence; returns (projection, cell)."""
    helper = LayerHelper("dynamic_lstmp", name=name)
    H = size // 4
    import copy

    from paddle_tpu.fluid.param_attr import ParamAttr

    def slot_attr(suffix):
        # create_parameter stamps attr.name in place — sharing one attr
        # object would alias weight and proj_weight into one variable
        a = copy.copy(ParamAttr._to_attr(param_attr))
        if a.name is not None:
            a.name = a.name + suffix
        return a

    weight = helper.create_parameter(slot_attr(".weight"),
                                     shape=[proj_size, 4 * H], dtype=dtype)
    proj_weight = helper.create_parameter(slot_attr(".proj_weight"),
                                          shape=[H, proj_size], dtype=dtype)
    bias_size = 7 * H if use_peepholes else 4 * H
    bias = helper.create_parameter(bias_attr, shape=[1, bias_size],
                                   dtype=dtype, is_bias=True)
    proj = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "lstmp",
        inputs={"Input": [input], "Weight": [weight],
                "ProjWeight": [proj_weight], "Bias": [bias]},
        outputs={"Projection": [proj], "Cell": [cell]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation,
               "proj_activation": proj_activation})
    return proj, cell
