"""Data-input layers (reference: python/paddle/fluid/layers/io.py — data() at
:data; py_reader :485 and double_buffer are delivered by the host-side
prefetching pipeline in paddle_tpu.data, since on TPU the in-graph reader-op
queue is replaced by host→device async transfer)."""

from __future__ import annotations

from paddle_tpu.fluid import framework
from paddle_tpu.fluid.layer_helper import LayerHelper


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True, type=None):
    """reference: layers/io.py data() — declares a feed target. The -1 batch
    dim binds at compile time from the feed signature."""
    helper = LayerHelper("data", name=name)
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = helper.main_program.global_block()
    if block.has_var(name):
        return block.var(name)
    return block.create_var(name=name, shape=shape, dtype=dtype,
                            lod_level=lod_level, stop_gradient=stop_gradient)
