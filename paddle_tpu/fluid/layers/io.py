"""Data-input layers (reference: python/paddle/fluid/layers/io.py — data() at
:data; py_reader :485 and double_buffer are delivered by the host-side
prefetching pipeline in paddle_tpu.data, since on TPU the in-graph reader-op
queue is replaced by host→device async transfer)."""

from __future__ import annotations

from paddle_tpu.fluid import framework
from paddle_tpu.fluid.layer_helper import LayerHelper


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True, type=None):
    """reference: layers/io.py data() — declares a feed target. The -1 batch
    dim binds at compile time from the feed signature."""
    helper = LayerHelper("data", name=name)
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = helper.main_program.global_block()
    if block.has_var(name):
        return block.var(name)
    return block.create_var(name=name, shape=shape, dtype=dtype,
                            lod_level=lod_level, stop_gradient=stop_gradient)


# ---------------------------------------------------------------------------
# In-graph reader surface (reference: layers/io.py py_reader :485,
# double_buffer, open_files, shuffle, batch, read_file, Preprocessor,
# random_data_generator). The reference implements these as reader OPS
# with C++ blocking queues (operators/reader/); on TPU the queue is a
# host-side prefetch thread and the executor pulls the next batch when
# run() is called with no feed — same user protocol, including
# EOFException/reset() at epoch end.
# ---------------------------------------------------------------------------


class _ReaderError:
    """Wrapper pushed by the fill thread when the user's provider raises:
    the trainer's next run() re-raises the original error instead of
    seeing a clean (and silently truncated) epoch end."""

    def __init__(self, exc):
        self.exc = exc


class PyReader:
    """The host-side successor of create_py_reader_op + blocking_queue
    (reference: operators/reader/create_py_reader_op.cc,
    reader/blocking_queue.h). decorate_paddle_reader/start/reset follow
    the reference protocol: run the program with NO feed and catch
    fluid.core.EOFException at epoch end."""

    def __init__(self, var_names, program, capacity=64):
        import queue as _q
        self.var_names = list(var_names)
        self.capacity = int(capacity)
        self._provider = None
        self._decorators = []      # shuffle/batch wrap at start() time
        self._queue = None
        self._thread = None
        self._stop = None
        self._exhausted = False    # sentinel seen; EOF until reset()
        from collections import deque
        self._pushback = deque()   # batches returned by the executor
        self._program = program
        readers = getattr(program, "_py_readers", None)
        if readers is None:
            readers = program._py_readers = []
        readers.append(self)

    # -- providers ---------------------------------------------------------

    def decorate_paddle_reader(self, reader_creator):
        """reader yields per-batch LISTS of sample tuples (the
        paddle.batch convention) or ready tuples of arrays."""
        self._provider = reader_creator

    decorate_tensor_provider = decorate_paddle_reader

    def _to_feed(self, item):
        import numpy as np
        if isinstance(item, dict):
            return {n: item[n] for n in self.var_names}
        if isinstance(item, (list, tuple)) and item and \
                isinstance(item[0], (list, tuple)):
            # list of sample tuples -> stack per slot
            cols = list(zip(*item))
            arrs = [np.stack([np.asarray(v) for v in col]) for col in cols]
        else:
            arrs = [np.asarray(v) for v in item]
        return dict(zip(self.var_names, arrs))

    # -- the blocking-queue lifecycle -------------------------------------

    def start(self):
        import queue
        import threading
        if self._provider is None:
            raise RuntimeError("py_reader: call decorate_paddle_reader "
                               "before start()")
        provider = self._provider
        for deco in self._decorators:
            provider = deco(provider)
        # bind THIS epoch's queue/stop as locals: a mid-epoch
        # reset()+start() must not let the old fill thread push stale
        # batches or its end-sentinel into the new epoch's queue
        q = self._queue = queue.Queue(self.capacity)
        stop = self._stop = threading.Event()
        self._exhausted = False

        def fill():
            try:
                for item in provider():
                    if stop.is_set():
                        return
                    q.put(self._to_feed(item))
                q.put(None)                  # clean epoch-end sentinel
            except BaseException as e:       # propagate, don't fake EOF
                q.put(_ReaderError(e))

        self._thread = threading.Thread(target=fill, daemon=True)
        self._thread.start()

    def reset(self):
        if self._stop is not None:
            self._stop.set()
        if self._queue is not None:
            try:
                while True:
                    self._queue.get_nowait()
            except Exception:
                pass
        self._queue = None
        self._thread = None
        self._exhausted = False
        self._pushback.clear()

    def _push_back(self, feed):
        """Return an already-pulled batch (the executor aborted a
        multi-reader or multi-step pull midway) — served again first."""
        self._pushback.appendleft(feed)

    def _next_feed(self):
        from paddle_tpu.core.executor import EOFException
        if self._queue is None:
            raise RuntimeError("py_reader: start() not called (or reset)")
        if self._pushback:
            return self._pushback.popleft()
        if self._exhausted:
            # the sentinel was already consumed (e.g. by a multi-step
            # window's partial tail) — keep raising, never block
            raise EOFException("py_reader: epoch exhausted — call reset()")
        item = self._queue.get()
        if item is None:
            self._exhausted = True
            raise EOFException("py_reader: epoch exhausted — call reset()")
        if isinstance(item, _ReaderError):
            self._exhausted = True
            raise RuntimeError(
                "py_reader: the data provider raised") from item.exc
        return item


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """reference: layers/io.py:485. Returns a PyReader; get the data vars
    with read_file(reader)."""
    from paddle_tpu.fluid import unique_name
    base = name or unique_name.generate("py_reader")
    names = []
    for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
        v = data(f"{base}_slot{i}", shape=list(shape)[1:], dtype=dtype,
                 append_batch_size=True)
        names.append(v.name)
    helper = LayerHelper("py_reader")
    reader = PyReader(names, helper.main_program, capacity=capacity)
    return reader


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    """reference: layers/io.py create_py_reader_by_data — py_reader bound
    to existing data vars."""
    helper = LayerHelper("py_reader")
    return PyReader([v.name for v in feed_list], helper.main_program,
                    capacity=capacity)


def read_file(reader):
    """reference: layers/io.py read_file — the reader's data variables."""
    block = framework.default_main_program().global_block()
    outs = [block.var(n) for n in reader.var_names]
    return outs[0] if len(outs) == 1 else outs


def double_buffer(reader, place=None, name=None):
    """reference: layers/io.py double_buffer. Prefetch is inherent here
    (the PyReader fill thread + data/pipeline device double-buffering),
    so this is the identity on PyReader — kept for API parity."""
    return reader


def shuffle(reader, buffer_size):
    """reference: layers/io.py shuffle → shuffle_reader. Registers the
    host-side shuffle decorator; applied to whatever provider is set
    (by either decorate_* spelling) when start() runs."""
    def deco(provider):
        from paddle_tpu.reader.decorator import shuffle as _shuffle
        return _shuffle(provider, buffer_size)

    reader._decorators.append(deco)
    return reader


def batch(reader, batch_size):
    """reference: layers/io.py batch → batch_reader (regroup a
    sample-level provider into batches); applied at start() time."""
    def deco(provider):
        from paddle_tpu.reader.decorator import batch as _batch
        return _batch(provider, batch_size)

    reader._decorators.append(deco)
    return reader


def open_files(filenames, shapes=None, lod_levels=None, dtypes=None,
               thread_num=1, buffer_size=64, pass_num=1, is_test=None,
               name=None):
    """reference: layers/io.py open_files → open_files_op (recordio
    readers). Files are paddle_tpu recordio archives of pickled feed
    dicts (recordio.convert_reader_to_recordio_file)."""
    import pickle

    from paddle_tpu import recordio as _rio
    from paddle_tpu.fluid import unique_name

    if isinstance(filenames, str):
        filenames = [filenames]
    # discover slot names from the first record
    first_rec = next(iter(_rio.Scanner(filenames[0])))
    sample = pickle.loads(first_rec)
    if not isinstance(sample, dict):
        raise ValueError("open_files expects recordio of pickled feed "
                         "dicts (see convert_reader_to_recordio_file)")
    base = name or unique_name.generate("open_files")
    helper = LayerHelper("open_files")
    block = helper.main_program.global_block()
    names = []
    for key, arr in sample.items():
        if not block.has_var(key):
            import numpy as np
            a = np.asarray(arr)
            data(key, shape=list(a.shape)[1:], dtype=str(a.dtype),
                 append_batch_size=True)
        names.append(key)
    reader = PyReader(names, helper.main_program, capacity=buffer_size)

    def provider():
        for _ in range(pass_num):
            for fn in filenames:
                for rec in _rio.Scanner(fn):
                    yield pickle.loads(rec)

    reader.decorate_paddle_reader(lambda: provider())
    return reader


def random_data_generator(low, high, shapes, lod_levels=None,
                          for_parallel=True):
    """reference: layers/io.py random_data_generator — a reader of
    uniform random float batches (used by reader tests/benchmarks)."""
    import numpy as np

    from paddle_tpu.fluid import unique_name
    base = unique_name.generate("rand_reader")
    names = []
    for i, shape in enumerate(shapes):
        v = data(f"{base}_slot{i}", shape=list(shape)[1:], dtype="float32",
                 append_batch_size=True)
        names.append(v.name)
    helper = LayerHelper("random_data_generator")
    reader = PyReader(names, helper.main_program, capacity=16)

    def provider():
        rng = np.random.RandomState(0)
        while True:
            yield tuple(rng.uniform(low, high, size=tuple(s)).astype("float32")
                        for s in shapes)

    reader.decorate_paddle_reader(lambda: provider())
    return reader


class Preprocessor:
    """reference: layers/io.py Preprocessor — rewires a reader through a
    preprocessing block. Host-side form: a python callable over each
    batch, applied in the fill thread."""

    def __init__(self, reader, name=None):
        self.reader = reader
        self._fn = None

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def cm():
            yield self
        return cm()

    def inputs(self):
        raise NotImplementedError(
            "Preprocessor.inputs/outputs (in-graph rewiring) is not "
            "supported; pass a callable to set_transform instead")

    def set_transform(self, fn):
        self._fn = fn
        inner = self.reader._provider
        if inner is None:
            raise RuntimeError("decorate the reader before Preprocessor")

        def provider():
            for item in inner():
                yield fn(item)

        self.reader._provider = provider
        return self.reader
