"""fluid.layers namespace (reference: python/paddle/fluid/layers/__init__.py)."""

from paddle_tpu.fluid.layers.io import data  # noqa: F401
from paddle_tpu.fluid.layers.tensor import (  # noqa: F401
    argmax, argmin, assign, cast, concat, fill_constant,
    fill_constant_batch_size_like, ones, shape, sums, zeros, zeros_like)
from paddle_tpu.fluid.layers.nn import (  # noqa: F401
    affine_channel, affine_grid, grid_sampler, image_resize,
    resize_bilinear, resize_nearest, roi_align, roi_pool,
    argsort, multiplex, warpctc, ctc_greedy_decoder, log_loss, rank_loss, margin_rank_loss, bpr_loss, crop, pad2d, pad_constant_like, random_crop, add_position_encoding, similarity_focus, bilinear_tensor_product, row_conv, unstack, sampling_id,
    accuracy, auc, batch_norm, beam_search, beam_search_decode, chunk_eval,
    clip, conv2d, conv2d_transpose,
    cos_sim, crf_decoding, cross_entropy, dropout, embedding, expand, fc,
    fused_linear_cross_entropy, fused_multi_head_attention,
    kv_attention_prefill, kv_attention_prefill_slot, kv_attention_decode,
    kv_attention_prefill_paged, kv_attention_decode_paged,
    kv_attention_verify, kv_attention_verify_paged,
    token_sample,
    gather, hsigmoid, huber_loss, l2_normalize, label_smooth, layer_norm,
    linear_chain_crf, log, matmul, mean, mul, nce, one_hot, pool2d,
    reduce_max, reduce_mean, reduce_min, reduce_prod, reduce_sum, reshape,
    scale, scaled_dot_product_attention, sigmoid_cross_entropy_with_logits, slice, softmax,
    softmax_with_cross_entropy, split, square_error_cost, squeeze, stack,
    topk, transpose, unsqueeze)
from paddle_tpu.fluid.layers.rnn import (  # noqa: F401
    dynamic_gru, dynamic_lstm, gru_unit, lstm_unit)
from paddle_tpu.fluid.layers.control_flow import (  # noqa: F401
    DynamicRNN, IfElse, StaticRNN, Switch, While, array_length, array_read,
    array_write, create_array, increment)
from paddle_tpu.fluid.layers.sequence import (  # noqa: F401
    edit_distance, sequence_concat, sequence_conv, sequence_enumerate,
    sequence_erase, sequence_expand, sequence_expand_as, sequence_first_step,
    sequence_last_step, sequence_mask, sequence_pad, sequence_pool,
    sequence_reshape, sequence_reverse, sequence_slice, sequence_softmax,
    sequence_unpad)
from paddle_tpu.fluid.layers.ops import (  # noqa: F401
    abs, ceil, cos, elementwise_add, elementwise_div, elementwise_max,
    elementwise_min, elementwise_mod, elementwise_mul, elementwise_pow,
    elementwise_sub, elu, equal, exp, floor, gelu, greater_equal,
    greater_than, hard_sigmoid, leaky_relu, less_equal, less_than,
    logsigmoid, not_equal, pow, reciprocal, relu, relu6, round, rsqrt,
    sigmoid, sin, softplus, softsign, sqrt, square, swish, tanh,
    tanh_shrink, selu, hard_shrink, soft_shrink, softshrink,
    thresholded_relu, brelu, stanh, maxout, flatten, space_to_depth,
    l1_norm)
from paddle_tpu.fluid.layers.parallel import (  # noqa: F401
    Pipeline, switch_moe)
from paddle_tpu.fluid.layers import detection  # noqa: F401
from paddle_tpu.fluid.layers.detection import (  # noqa: F401
    anchor_generator, bipartite_match, box_coder, density_prior_box,
    detection_map, detection_output, generate_proposals, iou_similarity,
    mine_hard_examples, multi_box_head, multiclass_nms,
    polygon_box_transform, prior_box,
    rpn_target_assign, ssd_loss, target_assign, yolov3_loss)

# round-3 API-surface completion: every public name the reference exports
# from fluid.layers resolves (tests/test_layers_api_parity.py)
from paddle_tpu.fluid.layers.nn import (  # noqa: F401
    adaptive_pool2d, adaptive_pool3d, autoincreased_step_counter,
    clip_by_norm, conv3d, conv3d_transpose, data_norm, dice_loss,
    gaussian_random, gaussian_random_batch_size_like,
    get_tensor_from_selected_rows, group_norm, hash, im2sequence,
    image_resize_short, lod_reset, logical_and, logical_not, logical_or,
    logical_xor, lrn, lstm, mean_iou, merge_selected_rows, pad, pool3d,
    prelu, psroi_pool, py_func, roi_perspective_transform, scatter,
    smooth_l1, soft_relu, sum, teacher_student_sigmoid_loss,
    uniform_random_batch_size_like)
from paddle_tpu.fluid.layers.tensor import (  # noqa: F401
    create_global_var, create_parameter, create_tensor, has_inf, has_nan,
    is_empty, isfinite, load, reverse)
from paddle_tpu.fluid.layers.sequence import sequence_scatter  # noqa: F401
from paddle_tpu.fluid.layers.control_flow import (  # noqa: F401
    Print, reorder_lod_tensor_by_rank, tensor_array_to_tensor)
from paddle_tpu.fluid.layers.detection import (  # noqa: F401
    generate_proposal_labels)
from paddle_tpu.fluid.layers.rnn import dynamic_lstmp  # noqa: F401
from paddle_tpu.fluid.layers.io import (  # noqa: F401
    Preprocessor, PyReader, batch, create_py_reader_by_data, double_buffer,
    open_files, py_reader, random_data_generator, read_file, shuffle)
from paddle_tpu.fluid.learning_rate_scheduler import (  # noqa: F401
    append_LARS, exponential_decay, inverse_time_decay, natural_exp_decay,
    noam_decay, piecewise_decay, polynomial_decay)
