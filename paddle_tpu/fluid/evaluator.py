"""In-graph accumulating evaluators.

Capability parity with the reference's `fluid.evaluator` module
(reference: python/paddle/fluid/evaluator.py — Evaluator base :44,
ChunkEvaluator :126, EditDistance :217, DetectionMAP :298): each evaluator
appends accumulation ops to the MAIN program (state += batch statistic per
run), `reset(exe)` zeroes the states through a small side program, and
`eval(exe)` computes the aggregate metric. The reference itself steers new
code toward `fluid.metrics.*` (host-side accumulation, metrics.py); both
surfaces exist here.

TPU note: the accumulating states are persistable scope vars updated by
the compiled step itself — under `exe.run(iterations=N)` the accumulation
rides the device-side loop with no host round-trips.
"""

from __future__ import annotations

import warnings

import numpy as np

from paddle_tpu.fluid import framework
from paddle_tpu.fluid.layer_helper import LayerHelper
from paddle_tpu.fluid import layers


class Evaluator:
    """reference: evaluator.py:44. States zero on reset; subclasses append
    accumulation ops at construction time (inside a program_guard)."""

    def __init__(self, name, **kwargs):
        warnings.warn(
            f"The {type(self).__name__} evaluator is the legacy in-graph "
            f"surface; prefer fluid.metrics.{type(self).__name__} "
            f"(host-side accumulation)", Warning)
        self.states = []
        self.metrics = []
        self.helper = LayerHelper(name, **kwargs)

    def reset(self, executor, reset_program=None):
        """Zero every state var (reference: evaluator.py:76)."""
        if reset_program is None:
            reset_program = framework.Program()
        with framework.program_guard(main_program=reset_program):
            for var in self.states:
                g_var = reset_program.global_block().create_var(
                    name=var.name, shape=var.shape, dtype=var.dtype,
                    persistable=True)
                layers.fill_constant(shape=list(var.shape),
                                     dtype=var.dtype, value=0.0, out=g_var)
        executor.run(reset_program)

    def eval(self, executor, eval_program=None):
        raise NotImplementedError()

    def _create_state(self, suffix, dtype, shape):
        """Persistable accumulator var, zero-initialized in the startup
        program (reference: evaluator.py _create_state)."""
        from paddle_tpu.fluid import unique_name
        name = "_".join([unique_name.generate(self.helper.name), suffix])
        main = framework.default_main_program()
        startup = framework.default_startup_program()
        state = main.global_block().create_var(
            name=name, persistable=True, dtype=dtype, shape=list(shape),
            stop_gradient=True)
        sv = startup.global_block().create_var(
            name=name, persistable=True, dtype=dtype, shape=list(shape))
        with framework.program_guard(startup):
            layers.fill_constant(shape=list(shape), dtype=dtype, value=0.0,
                                 out=sv)
        self.states.append(state)
        return state

    def _accumulate(self, state, batch_value):
        """state += batch_value, in-graph (runs every exe.run of main)."""
        if batch_value.dtype != state.dtype:
            batch_value = layers.cast(batch_value, state.dtype)
        summed = layers.elementwise_add(
            state, layers.reshape(batch_value, shape=list(state.shape)))
        layers.assign(summed, state)


class ChunkEvaluator(Evaluator):
    """Accumulated chunk P/R/F1 (reference: evaluator.py:126). Appends
    chunk_eval to the main program and accumulates the three chunk counts;
    eval() computes precision/recall/F1 from the totals."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None, seq_lens=None):
        super().__init__("chunk_eval")
        (precision, recall, f1,
         num_infer, num_label, num_correct) = layers.chunk_eval(
            input=input, label=label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types, seq_lens=seq_lens)
        self.num_infer_chunks = self._create_state(
            "num_infer_chunks", "int64", (1,))
        self.num_label_chunks = self._create_state(
            "num_label_chunks", "int64", (1,))
        self.num_correct_chunks = self._create_state(
            "num_correct_chunks", "int64", (1,))
        self._accumulate(self.num_infer_chunks, num_infer)
        self._accumulate(self.num_label_chunks, num_label)
        self._accumulate(self.num_correct_chunks, num_correct)
        self.metrics.extend((precision, recall, f1))

    def eval(self, executor, eval_program=None):
        from paddle_tpu.core.scope import global_scope
        ni = float(np.asarray(global_scope().find_var(
            self.num_infer_chunks.name)).reshape(()))
        nl = float(np.asarray(global_scope().find_var(
            self.num_label_chunks.name)).reshape(()))
        nc = float(np.asarray(global_scope().find_var(
            self.num_correct_chunks.name)).reshape(()))
        precision = nc / ni if ni else 0.0
        recall = nc / nl if nl else 0.0
        f1 = (2 * precision * recall / (precision + recall)
              if nc else 0.0)
        return np.array([precision]), np.array([recall]), np.array([f1])


class EditDistance(Evaluator):
    """Accumulated average edit distance + instance error rate
    (reference: evaluator.py:217)."""

    def __init__(self, input, label, ignored_tokens=None, input_length=None,
                 label_length=None):
        super().__init__("edit_distance")
        distances, seq_num = layers.edit_distance(
            input=input, label=label, normalized=False,
            input_length=input_length, label_length=label_length)
        self.total_distance = self._create_state(
            "total_distance", "float32", (1,))
        self.seq_num = self._create_state("seq_num", "int64", (1,))
        self.instance_error = self._create_state(
            "instance_error", "int64", (1,))
        batch_dist = layers.reduce_sum(distances)
        batch_err = layers.reduce_sum(
            layers.cast(layers.greater_than(
                distances, layers.fill_constant([1], "float32", 0.0)),
                "int64"))
        self._accumulate(self.total_distance, batch_dist)
        self._accumulate(self.seq_num, seq_num)
        self._accumulate(self.instance_error, batch_err)

    def eval(self, executor, eval_program=None):
        from paddle_tpu.core.scope import global_scope
        dist = float(np.asarray(global_scope().find_var(
            self.total_distance.name)).reshape(()))
        n = float(np.asarray(global_scope().find_var(
            self.seq_num.name)).reshape(()))
        err = float(np.asarray(global_scope().find_var(
            self.instance_error.name)).reshape(()))
        avg = dist / n if n else 0.0
        rate = err / n if n else 0.0
        return np.array([avg]), np.array([rate])


class DetectionMAP(Evaluator):
    """Accumulated detection mAP (reference: evaluator.py:298). The
    stateless detection_map op scores each batch; cur_map is the
    per-batch value and accum_map the running average over batches
    (static-shape redesign of the reference's accumulating
    PosCount/TruePos/FalsePos states — detection_map_op.cc)."""

    def __init__(self, input, gt_label, gt_box, class_num,
                 background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral"):
        super().__init__("map_eval")
        label = layers.concat([layers.cast(gt_label, "float32"), gt_box],
                              axis=-1)
        cur = layers.detection_map(
            input, label, class_num, background_label=background_label,
            overlap_threshold=overlap_threshold, ap_version=ap_version)
        self.map_sum = self._create_state("map_sum", "float32", (1,))
        self.batches = self._create_state("batches", "float32", (1,))
        self._accumulate(self.map_sum, cur)
        self._accumulate(self.batches,
                         layers.fill_constant([1], "float32", 1.0))
        # accum_map is mAP-VALUED (running average), matching the
        # reference contract (evaluator.py:298 returns accum_map) — not
        # the raw sum
        self.accum_map = layers.elementwise_div(self.map_sum, self.batches)
        self.cur_map = cur
        self.metrics.append(cur)

    def get_map_var(self):
        return self.cur_map, self.accum_map

    def eval(self, executor, eval_program=None):
        from paddle_tpu.core.scope import global_scope
        s = float(np.asarray(global_scope().find_var(
            self.map_sum.name)).reshape(()))
        n = float(np.asarray(global_scope().find_var(
            self.batches.name)).reshape(()))
        return np.array([s / n if n else 0.0])
