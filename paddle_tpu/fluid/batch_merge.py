"""Gradient-accumulation (multi-batch-merge) program rewrite.

Capability parity with the reference's multi_batch_merge_pass
(reference: framework/ir/multi_batch_merge_pass.cc — repeats the
forward/backward k times and applies the optimizer once on the merged
gradients, for large effective batches that don't fit memory).

TPU-native redesign: instead of cloning the graph k times (the
reference's repeat_grad approach), the rewrite keeps ONE step graph and
makes the optimizer CONDITIONAL — jit-friendly dataflow, no control-flow
divergence between steps:

  acc      += grad                    (persistable accumulator per grad)
  counter  += 1
  apply     = (counter % k == 0)      ([1] bool)
  opt step runs on (acc / k) into fresh names
  state     = select(apply, new, old) (params + every optimizer state)
  acc       = acc * (1 - apply)       (zeroed after an apply step)

Every k-th `exe.run` (or scan iteration under `iterations=N`) performs
exactly one optimizer update on the k-step mean gradient; the others only
accumulate. Equivalent to one big-batch step for mean-reduced losses
(test_batch_merge.py asserts exact parity vs the 2x batch for SGD).

Divergence from the reference, by design: batch_norm statistics see each
micro-batch (the reference's repeated forward does too); in-graph lr
schedulers advance per micro-step.
"""

from __future__ import annotations

from paddle_tpu.core import ir

OPT_OP_TYPES = ("sgd", "momentum", "adam", "adamax", "adagrad", "adadelta",
                "decayed_adagrad", "ftrl", "rmsprop", "proximal_gd",
                "proximal_adagrad", "lars_momentum")


def _startup_fill(startup, name, shape, dtype, value):
    blk = startup.desc.global_block
    if not blk.has_var(name):
        blk.add_var(ir.VarDesc(name=name, shape=list(shape), dtype=dtype,
                               persistable=True))
    blk.append_op(ir.OpDesc(
        type="fill_constant", outputs={"Out": [name]},
        attrs={"shape": list(shape), "dtype": dtype, "value": value}))


def apply_batch_merge(main_program, startup_program, k: int):
    """Rewrite `main_program` (after minimize()) for k-step gradient
    accumulation. Returns the number of optimizer ops rewritten."""
    if k < 2:
        return 0
    blk = main_program.desc.global_block
    opt_idxs = [i for i, op in enumerate(blk.ops)
                if op.type in OPT_OP_TYPES]
    if not opt_idxs:
        raise ValueError("apply_batch_merge: no optimizer ops in the "
                         "program — call minimize() first")

    # int32 counter: a float32 counter stops incrementing at 2^24
    # micro-steps and the apply gate would silently freeze; int32 is exact
    # to 2^31 micro-steps (ample) and — unlike int64, which JAX truncates
    # at runtime with x64 disabled — the declared dtype is the executed
    # dtype (advisor finding, round 2)
    cnt = "batch_merge_step@BM"
    blk.add_var(ir.VarDesc(name=cnt, shape=[1], dtype="int32",
                           persistable=True))
    _startup_fill(startup_program, cnt, [1], "int32", 0.0)

    def op(type_, ins, outs, attrs=None):
        return ir.OpDesc(type=type_, inputs=ins, outputs=outs,
                         attrs=attrs or {})

    # counter/apply-flag ops, emitted once before the first optimizer op
    pre = [
        op("fill_constant", {}, {"Out": ["one_i@BM"]},
           {"shape": [1], "dtype": "int32", "value": 1.0}),
        op("elementwise_add", {"X": [cnt], "Y": ["one_i@BM"]},
           {"Out": ["cnt_new@BM"]}),
        op("assign", {"X": ["cnt_new@BM"]}, {"Out": [cnt]}),
        op("fill_constant", {}, {"Out": ["k@BM"]},
           {"shape": [1], "dtype": "int32", "value": float(k)}),
        op("elementwise_mod", {"X": ["cnt_new@BM"], "Y": ["k@BM"]},
           {"Out": ["rem@BM"]}),
        op("fill_constant", {}, {"Out": ["zero_i@BM"]},
           {"shape": [1], "dtype": "int32", "value": 0.0}),
        op("equal", {"X": ["rem@BM"], "Y": ["zero_i@BM"]},
           {"Out": ["apply@BM"]}),
        op("cast", {"X": ["apply@BM"]}, {"Out": ["apply_f@BM"]},
           {"out_dtype": "float32"}),
        op("fill_constant", {}, {"Out": ["one@BM"]},
           {"shape": [1], "dtype": "float32", "value": 1.0}),
        op("elementwise_sub", {"X": ["one@BM"], "Y": ["apply_f@BM"]},
           {"Out": ["keep_f@BM"]}),
    ]

    new_ops = []
    first_opt = opt_idxs[0]
    n_rewritten = 0
    for i, o in enumerate(blk.ops):
        if i == first_opt:
            new_ops.extend(pre)
        if o.type not in OPT_OP_TYPES:
            new_ops.append(o)
            continue
        gname = o.inputs["Grad"][0]
        acc = gname + "@BM_ACC"
        gvd = blk.var(gname) if blk.has_var(gname) else None
        pshape = list((gvd.shape if gvd is not None and gvd.shape
                       else blk.var(o.inputs["Param"][0]).shape) or [1])
        blk.add_var(ir.VarDesc(name=acc, shape=pshape, dtype="float32",
                               persistable=True))
        _startup_fill(startup_program, acc, pshape, "float32", 0.0)
        tag = f"@BM{n_rewritten}"
        new_ops.append(op("elementwise_add", {"X": [acc], "Y": [gname]},
                          {"Out": [f"gsum{tag}"]}))
        new_ops.append(op("scale", {"X": [f"gsum{tag}"]},
                          {"Out": [f"geff{tag}"]}, {"scale": 1.0 / k}))
        o.inputs = dict(o.inputs)
        o.inputs["Grad"] = [f"geff{tag}"]
        # optimizer writes into fresh names; selects gate the commit
        selects = []
        new_outputs = {}
        for slot, names in o.outputs.items():
            fresh = []
            for j, name in enumerate(names):
                nn = f"{slot}{j}{tag}"
                fresh.append(nn)
                selects.append(op("select",
                                  {"Condition": ["apply@BM"],
                                   "X": [nn], "Y": [name]},
                                  {"Out": [name]}))
            new_outputs[slot] = fresh
        o.outputs = new_outputs
        new_ops.append(o)
        new_ops.extend(selects)
        new_ops.append(op("elementwise_mul",
                          {"X": [f"gsum{tag}"], "Y": ["keep_f@BM"]},
                          {"Out": [f"acc_new{tag}"]}))
        new_ops.append(op("assign", {"X": [f"acc_new{tag}"]},
                          {"Out": [acc]}))
        n_rewritten += 1

    blk.ops[:] = new_ops
    main_program.desc.bump_version()
    startup_program.desc.bump_version()
    return n_rewritten
