"""Program visualization + numerical debug helpers (reference:
python/paddle/fluid/debugger.py draw_block_graphviz, net_drawer.py,
framework/ir/graph_viz_pass.cc FLAGS_debug_graphviz_path, and the
FLAGS_check_nan_inf per-op output scan, operator.cc:978-990)."""

from __future__ import annotations

from typing import Optional

_OP_STYLE = 'shape=box, style="rounded,filled", fillcolor="#E6F2FF"'
_VAR_STYLE = 'shape=oval, style=filled, fillcolor="#EFEFEF"'
_PARAM_STYLE = 'shape=oval, style=filled, fillcolor="#DFF7DF"'


def draw_block_graphviz(block, highlights=None, path: Optional[str] = None):
    """Emit a graphviz dot description of a BlockDesc's dataflow
    (reference: debugger.py draw_block_graphviz; graph_viz_pass.cc).
    Returns the dot source; writes it to `path` if given."""
    highlights = set(highlights or [])
    lines = ["digraph G {", "  rankdir=TB;"]
    seen_vars = {}

    def var_node(name):
        if name in seen_vars:
            return seen_vars[name]
        nid = f"var_{len(seen_vars)}"
        seen_vars[name] = nid
        style = _VAR_STYLE
        if block.has_var(name):
            vd = block.var(name)
            if getattr(vd, "persistable", False):
                style = _PARAM_STYLE
            label = f"{name}\\n{vd.shape or ''} {vd.dtype}"
        else:
            label = name
        if name in highlights:
            style += ', color=red, penwidth=2'
        lines.append(f'  {nid} [label="{label}", {style}];')
        return nid

    for i, op in enumerate(block.ops):
        op_id = f"op_{i}"
        lines.append(f'  {op_id} [label="{op.type}", {_OP_STYLE}];')
        for names in op.inputs.values():
            for n in names:
                lines.append(f"  {var_node(n)} -> {op_id};")
        for names in op.outputs.values():
            for n in names:
                lines.append(f"  {op_id} -> {var_node(n)};")
    lines.append("}")
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot


def draw_program(program, path: Optional[str] = None):
    return draw_block_graphviz(program.desc.global_block, path=path)
